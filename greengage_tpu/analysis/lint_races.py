"""Cross-role race analysis: shared state written by one thread role and
touched by another with no common lock.

PR 8's lock lint proves the package acquires locks in a consistent
ORDER; nothing proved shared state is locked AT ALL. This analyzer walks
interprocedurally from each declared thread role's entry points
(analysis/threadmodel.py) and collects every ``self.<attr>`` /
module-global access with the set of locks held around it — with-block
tracking and lock-identity resolution shared with ``lint_locks``, one
more hop of call resolution (self-calls in-class; attribute/global
receivers through constructor typing; distinctive bare names
package-wide). An attribute *written* by one role and *touched* by
another where the two access paths hold no common lock is a finding
carrying both paths.

Instance-vs-identity honesty: a static identity (``mod.Class.attr``)
merges every instance of the class, so per-statement objects (Compiler,
Binder, plan nodes) would fabricate races. The analyzer therefore pairs
accesses only on classes declared genuinely shared
(``threadmodel.SHARED_CLASSES``) and on module globals — which are
shared by construction. ``self.x = threading.local()`` containers are
recognized and their contents exempted (per-thread by construction).

Suppression: the usual two channels — ``# gg:ok(races)`` on either
access line, or the checked-in baseline. The runtime complement is the
``GGTPU_RACE_DEBUG`` access witness in ``runtime/lockdebug.py``: the
analyzer proves the *model* has no bare cross-role access; the witness
catches a real interleaving the model missed.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from dataclasses import dataclass, field
from itertools import combinations

from greengage_tpu.analysis import astutil, threadmodel
from greengage_tpu.analysis.lint_locks import (_GENERIC_METHODS, _lock_ctor,
                                               _module_key)
from greengage_tpu.analysis.report import Report

# method names that mutate their receiver: `self.x.append(...)` is a
# WRITE to x even though x itself is only loaded
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear", "move_to_end", "sort", "reverse",
})


# ---------------------------------------------------------------------
# package model: locks, classes, globals, imports, typing
# ---------------------------------------------------------------------

@dataclass
class _Model:
    sites: dict = field(default_factory=dict)        # lock id -> (rel, line)
    per_module: dict = field(default_factory=dict)   # mod -> attr -> [ids]
    by_attr: dict = field(default_factory=dict)      # attr -> [ids]
    aliases: dict = field(default_factory=dict)      # (mod,cls,attr) -> attr
    classes: set = field(default_factory=set)        # class names
    global_types: dict = field(default_factory=dict)  # (mod, name) -> class
    attr_types: dict = field(default_factory=dict)   # attr -> class | None
    imports: dict = field(default_factory=dict)      # (mod, name) -> (mod2, name2)
    module_globals: dict = field(default_factory=dict)  # mod -> set of names
    thread_locals: set = field(default_factory=set)  # (mod, cls, attr)


def _build_model(srcs, receiver_types) -> _Model:
    m = _Model()
    for src in srcs:
        mod = _module_key(src.rel)
        gl: set = set()
        for node in src.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        gl.add(t.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                gl.add(node.target.id)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("greengage_tpu"):
                o = _module_key(node.module.replace(".", "/") + ".py")
                for alias in node.names:
                    m.imports[(mod, alias.asname or alias.name)] = \
                        (o, alias.name)
        m.module_globals[mod] = gl
        cls_stack: list[str] = []

        def walk(node, in_fn: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    m.classes.add(child.name)
                    cls_stack.append(child.name)
                    walk(child, in_fn)
                    cls_stack.pop()
                    continue
                if isinstance(child, ast.Assign):
                    _assign(child, in_fn)
                walk(child, in_fn or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)))

        def _assign(node: ast.Assign, in_fn: bool):
            cls = cls_stack[-1] if cls_stack else ""
            val = node.value
            if _lock_ctor(val):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        t = t.value
                    if isinstance(t, ast.Attribute):
                        ident = f"{mod}.{cls}.{t.attr}"
                        m.sites[ident] = (src.rel, node.lineno)
                        m.per_module.setdefault(mod, {}).setdefault(
                            t.attr, []).append(ident)
                        m.by_attr.setdefault(t.attr, []).append(ident)
                    elif isinstance(t, ast.Name) and not in_fn:
                        # bare-name lock sites are module globals only —
                        # a function-local Lock is not a shared identity
                        ident = f"{mod}.{t.id}"
                        m.sites[ident] = (src.rel, node.lineno)
                        m.per_module.setdefault(mod, {}).setdefault(
                            t.id, []).append(ident)
                        m.by_attr.setdefault(t.id, []).append(ident)
            if isinstance(val, ast.Call):
                name = astutil.call_name(val)
                # Condition(self._mu) keeps the underlying lock identity
                if name == "Condition" and val.args \
                        and isinstance(val.args[0], ast.Attribute) \
                        and isinstance(val.args[0].value, ast.Name) \
                        and val.args[0].value.id == "self":
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            m.aliases[(mod, cls, t.attr)] = val.args[0].attr
                elif name == "local":       # threading.local(): per-thread
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            m.thread_locals.add((mod, cls, t.attr))
                elif name is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            prev = m.attr_types.get(t.attr, name)
                            # conflicting ctor classes: untyped
                            m.attr_types[t.attr] = \
                                name if prev == name else None
                        elif isinstance(t, ast.Name) and not cls_stack \
                                and not in_fn:
                            # TOP-LEVEL singletons only (counters = ...):
                            # a function-local `x = C()` must not type
                            # every `x.m()` in the package — and same-name
                            # conflicts untype, like attr_types
                            prev = m.global_types.get((mod, t.id), name)
                            m.global_types[(mod, t.id)] = \
                                name if prev == name else None

        walk(src.tree, False)
        # properties backed by a threading.local container (the
        # last_prune pattern): every self-attr their bodies touch is a
        # declared thread-local -> the property name itself is per-thread
        for cls_node in ast.walk(src.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for item in cls_node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                decs = {astutil.dotted(d) for d in item.decorator_list}
                if not any(d and (d == "property" or d.endswith(".setter"))
                           for d in decs):
                    continue
                touched = {n.attr for n in ast.walk(item)
                           if isinstance(n, ast.Attribute)
                           and isinstance(n.value, ast.Name)
                           and n.value.id == "self"}
                if touched and all(
                        (mod, cls_node.name, a) in m.thread_locals
                        for a in touched):
                    m.thread_locals.add((mod, cls_node.name, item.name))
    # drop ctor "types" that aren't package classes (np.zeros etc.) and
    # fold in the declared receiver typing (factory returns)
    m.attr_types = {a: c for a, c in m.attr_types.items()
                    if c is not None and c in m.classes}
    for attr, cname in (receiver_types or {}).items():
        if cname in m.classes:
            m.attr_types[attr] = cname
    m.global_types = {k: c for k, c in m.global_types.items()
                      if c in m.classes}
    return m


def _resolve_lock(expr, mod: str, cls: str, model: _Model) -> str | None:
    """Best-effort lock identity for a with/acquire target. Exact
    self-site first, then module-unique, then package-unique; a known
    lock attr that stays ambiguous gets a synthetic per-module identity
    (same receiver text in the same module = same lock for common-lock
    purposes) rather than silently dropping the protection."""
    if isinstance(expr, ast.Call):
        name = astutil.call_name(expr)
        if name == "acquire" and isinstance(expr.func, ast.Attribute):
            expr = expr.func.value
        else:
            return None
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute):
        attr = model.aliases.get((mod, cls, expr.attr), expr.attr)
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            ident = f"{mod}.{cls}.{attr}"
            if ident in model.sites:
                return ident
        mod_ids = model.per_module.get(mod, {}).get(attr, [])
        if len(mod_ids) == 1:
            return mod_ids[0]
        ids = model.by_attr.get(attr, [])
        if len(ids) == 1:
            return ids[0]
        if ids:
            return f"{mod}.~{attr}"
        return None
    if isinstance(expr, ast.Name):
        ident = f"{mod}.{expr.id}"
        if ident in model.sites:
            return ident
        orig = model.imports.get((mod, expr.id))
        if orig is not None:
            ident = f"{orig[0]}.{orig[1]}"
            if ident in model.sites:
                return ident
    return None


# ---------------------------------------------------------------------
# per-function scan: accesses + calls, each with the local lock set
# ---------------------------------------------------------------------

@dataclass
class _FnInfo:
    key: tuple                      # (rel, cls, name)
    src: object
    accesses: list = field(default_factory=list)
    # (ident, owner_cls|None, "r"/"w", frozenset(locks), lineno)
    calls: list = field(default_factory=list)
    # ((kind, name, detail), frozenset(locks), lineno)


class _Scanner(ast.NodeVisitor):
    def __init__(self, info: _FnInfo, mod: str, cls: str, model: _Model):
        self.info, self.mod, self.cls, self.model = info, mod, cls, model
        self.held: list[str] = []

    # -- helpers --------------------------------------------------------
    def _locks(self) -> frozenset:
        return frozenset(self.held)

    def _acc(self, ident, owner, rw, lineno):
        # an access line carrying `# gg:ok(races)` is exempt at the
        # source: the justification sits next to the code
        if self.info.src.pragma_ok(lineno, "races"):
            return
        self.info.accesses.append((ident, owner, rw, self._locks(), lineno))

    def _global_ident(self, name: str):
        if name in self.model.module_globals.get(self.mod, ()):
            return f"{self.mod}.{name}"
        orig = self.model.imports.get((self.mod, name))
        if orig is not None and name in \
                self.model.module_globals.get(orig[0], ()):
            return f"{orig[0]}.{orig[1]}"
        return None

    # -- lock flow ------------------------------------------------------
    def visit_With(self, node: ast.With):
        got = []
        for item in node.items:
            lk = _resolve_lock(item.context_expr, self.mod, self.cls,
                               self.model)
            if lk is not None:
                got.append(lk)
        self.held.extend(got)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self.held.pop()

    # -- accesses -------------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and self.cls and not node.attr.startswith("__") \
                and (self.mod, self.cls, node.attr) \
                not in self.model.thread_locals:
            rw = "r" if isinstance(node.ctx, ast.Load) else "w"
            self._acc(f"{self.mod}.{self.cls}.{node.attr}", self.cls,
                      rw, node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        ident = self._global_ident(node.id)
        if ident is not None:
            rw = "r" if isinstance(node.ctx, ast.Load) else "w"
            self._acc(ident, None, rw, node.lineno)

    def visit_Subscript(self, node: ast.Subscript):
        # self.x[k] = v / del self.x[k] mutate x even though the
        # attribute itself is only loaded
        if not isinstance(node.ctx, ast.Load):
            t = node.value
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and self.cls \
                    and (self.mod, self.cls, t.attr) \
                    not in self.model.thread_locals:
                self._acc(f"{self.mod}.{self.cls}.{t.attr}", self.cls,
                          "w", node.lineno)
            elif isinstance(t, ast.Name):
                gid = self._global_ident(t.id)
                if gid is not None:
                    self._acc(gid, None, "w", node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = astutil.call_name(node)
        lineno = node.lineno
        f = node.func
        if name == "acquire" and isinstance(f, ast.Attribute):
            # linear held tracking for acquire()/release() pairs (the
            # try/finally pattern): source order approximates hold scope
            lk = _resolve_lock(node, self.mod, self.cls, self.model)
            if lk is not None:
                self.held.append(lk)
        elif name == "release" and isinstance(f, ast.Attribute):
            lk = _resolve_lock(f.value, self.mod, self.cls, self.model)
            if lk is not None and lk in self.held:
                self.held.remove(lk)
        elif name is not None and isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.info.calls.append((("self", name, None),
                                        self._locks(), lineno))
            elif isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                # self.X.m(): mutators write X — except when X's class is
                # known (the walk descends into the real method, which
                # does its own locking; a blind write here would indict
                # e.g. every internally-locked BlockCache.pop call)
                if name in _MUTATORS and self.cls \
                        and recv.attr not in self.model.attr_types \
                        and (self.mod, self.cls, recv.attr) \
                        not in self.model.thread_locals:
                    self._acc(f"{self.mod}.{self.cls}.{recv.attr}",
                              self.cls, "w", lineno)
                self.info.calls.append((("selfattr", name, recv.attr),
                                        self._locks(), lineno))
            elif isinstance(recv, ast.Name):
                gid = self._global_ident(recv.id)
                if gid is not None and name in _MUTATORS:
                    self._acc(gid, None, "w", lineno)
                self.info.calls.append((("recv", name, recv.id),
                                        self._locks(), lineno))
            else:
                self.info.calls.append((("other", name, None),
                                        self._locks(), lineno))
        elif name is not None and isinstance(f, ast.Name):
            self.info.calls.append((("bare", name, None),
                                    self._locks(), lineno))
        self.generic_visit(node)

    # nested defs are separate walk targets, not part of this body's
    # execution (they run when called/spawned)
    def visit_FunctionDef(self, node):   # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):   # noqa: D102
        pass


def _index_functions(srcs, model):
    """-> {(rel, cls, name): _FnInfo}, nested defs attributed to their
    nearest enclosing class."""
    out: dict[tuple, _FnInfo] = {}

    def walk(node, cls, src, mod):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name, src, mod)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (src.rel, cls, child.name)
                info = _FnInfo(key, src)
                sc = _Scanner(info, mod, cls, model)
                for stmt in child.body:
                    sc.visit(stmt)
                out.setdefault(key, info)
                walk(child, cls, src, mod)
            else:
                walk(child, cls, src, mod)

    for src in srcs:
        walk(src.tree, "", src, _module_key(src.rel))
    return out


# ---------------------------------------------------------------------
# call resolution + role walk
# ---------------------------------------------------------------------

class _Resolver:
    def __init__(self, fns: dict, model: _Model):
        self.fns = fns
        self.model = model
        self.by_cls_name: dict = defaultdict(list)   # (cls, name) -> keys
        self.by_rel_name: dict = defaultdict(list)   # (rel, name) -> keys
        self.by_name: dict = defaultdict(list)       # name -> keys
        for key in fns:
            rel, cls, name = key
            if cls:
                self.by_cls_name[(cls, name)].append(key)
            self.by_rel_name[(rel, name)].append(key)
            self.by_name[name].append(key)

    def targets(self, callspec, caller_key) -> list:
        kind, name, detail = callspec
        rel, cls, _ = caller_key
        if kind == "self" and cls:
            keys = self.by_cls_name.get((cls, name), [])
            same = [k for k in keys if k[0] == rel]
            return same or keys
        if kind == "selfattr":
            tcls = self.model.attr_types.get(detail)
            if tcls is not None:
                return self.by_cls_name.get((tcls, name), [])
            kind = "other"          # untyped receiver: distinctive-name
        if kind == "recv":
            mod = _module_key(rel)
            g = self.model.global_types.get((mod, detail))
            if g is None:
                orig = self.model.imports.get((mod, detail))
                if orig is not None:
                    g = self.model.global_types.get(orig)
            if g is not None:
                return self.by_cls_name.get((g, name), [])
            kind = "other"
        if kind == "bare":
            same = self.by_rel_name.get((rel, name), [])
            if len(same) == 1:
                return same
        if name in _GENERIC_METHODS:
            return []
        keys = self.by_name.get(name, [])
        return keys if len(keys) == 1 else []


def _entry_keys(role, fns) -> list:
    out = []
    for suffix, cls, name in role.entries:
        for key in fns:
            rel, kcls, kname = key
            if rel.endswith(suffix) and kname == name \
                    and (cls == "" or kcls == cls):
                out.append(key)
    return out


def run(sources=None, roles=None, shared_classes=None,
        receiver_types=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet(
        exclude=("greengage_tpu/analysis/",))
    srcs = list(sources)
    roles = roles if roles is not None else threadmodel.THREAD_ROLES
    shared = set(shared_classes if shared_classes is not None
                 else threadmodel.SHARED_CLASSES)
    model = _build_model(srcs, receiver_types if receiver_types is not None
                         else threadmodel.RECEIVER_TYPES)
    fns = _index_functions(srcs, model)
    resolver = _Resolver(fns, model)
    src_by_rel = {s.rel: s for s in srcs}

    entries = {name: _entry_keys(role, fns) for name, role in roles.items()}
    entry_owner: dict[tuple, set] = defaultdict(set)
    for rname, keys in entries.items():
        for k in keys:
            entry_owner[k].add(rname)

    # ident -> role -> {(rw, lockset): (rel, line, fn)}
    acc: dict[str, dict] = defaultdict(dict)
    owner_of: dict[str, str | None] = {}

    for rname in sorted(roles):
        stack = [(k, frozenset()) for k in entries[rname]]
        seen = set(stack)
        while stack:
            key, held = stack.pop()
            info = fns.get(key)
            if info is None:
                continue
            if key[2] == "__init__":
                continue    # construction precedes sharing
            for ident, owner, rw, locks, lineno in info.accesses:
                eff = held | locks
                slot = acc[ident].setdefault(rname, {})
                slot.setdefault((rw, eff), (key[0], lineno, key[2]))
                owner_of.setdefault(ident, owner)
            for callspec, locks, lineno in info.calls:
                for tgt in resolver.targets(callspec, key):
                    if entry_owner.get(tgt) \
                            and rname not in entry_owner[tgt]:
                        continue    # another role's surface starts here
                    st = (tgt, held | locks)
                    if st not in seen:
                        seen.add(st)
                        stack.append(st)

    report.notes["races_functions"] = len(fns)
    report.notes["races_shared_idents"] = sum(
        1 for i, by_role in acc.items() if len(by_role) > 1)

    def _fmt(locks: frozenset) -> str:
        return "{" + ", ".join(sorted(locks)) + "}" if locks else "no lock"

    for ident in sorted(acc):
        owner = owner_of.get(ident)
        if owner is not None and owner not in shared:
            continue
        by_role = acc[ident]
        if len(by_role) < 2:
            continue
        # one finding per identity: the first offending (writer, toucher)
        # pair as the evidence, every racing role pair in the tally —
        # a per-pair fan-out would bury one unlocked structure under
        # len(roles)^2 findings
        hit = None
        pairs = set()
        for a, b in combinations(sorted(by_role), 2):
            for (rw1, l1), w1 in sorted(by_role[a].items()):
                for (rw2, l2), w2 in sorted(by_role[b].items()):
                    if "w" not in (rw1, rw2) or (l1 & l2):
                        continue
                    pairs.add((a, b))
                    if hit is None:
                        wa = (a, rw1, l1, w1)
                        wb = (b, rw2, l2, w2)
                        hit = (wa, wb) if rw1 == "w" else (wb, wa)
        if hit is None:
            continue
        (wr, wrw, wl, wloc), (tr, trw, tl, tloc) = hit
        s1 = src_by_rel.get(wloc[0])
        s2 = src_by_rel.get(tloc[0])
        if (s1 is not None and s1.pragma_ok(wloc[1], "races")) or \
                (s2 is not None and s2.pragma_ok(tloc[1], "races")):
            continue
        more = len(pairs) - 1
        report.add(
            "races", wloc[0], wloc[1],
            f"race:{ident}",
            f"{ident} is written by role {wr} in {wloc[2]}() "
            f"[{wloc[0]}:{wloc[1]}, {_fmt(wl)}] and "
            f"{'written' if trw == 'w' else 'read'} by role {tr} in "
            f"{tloc[2]}() [{tloc[0]}:{tloc[1]}, {_fmt(tl)}] with no "
            "common lock — one role can observe the other's "
            "half-applied update"
            + (f" (+{more} more racing role pair(s))" if more else ""))
    return report
