"""Registry-hygiene lint: declared catalogs vs. what the code does.

Four registries drift silently without this check:

* **metrics** — ``runtime/logger.py`` declares ``COUNTER_NAMES`` /
  ``GAUGE_NAMES`` / ``HISTOGRAM_NAMES``; every ``counters.inc()`` /
  ``counters.set()`` / ``histograms.observe()`` site must name a
  declared metric of the right KIND (inc on a gauge or set on a counter
  is the exposition-type bug PR 7 fixed for mh_topology_version), and
  every declared metric must have a writer. F-string families
  (``statements_cancelled_{cause}``) match declared names by their
  literal prefix.
* **GUCs** — every ``Settings`` field must be documented in
  ``docs/GUCS.md``, and every row there must be a real field (SET-able
  knobs with no docs and documented knobs that no longer exist both
  fail).
* **fault points** — ``runtime/faultinject.py`` declares
  ``FAULT_POINTS``; every ``faults.check()`` in the package and every
  ``faults.inject()`` in the test tree must name a registered point,
  and every registered point must have a check site (a point tests arm
  but nothing fires is a dead test).
* **plan-cache GUCs** — the SET handler in ``exec/session.py`` clears
  ``_select_cache`` for a literal tuple of GUC names; every ``Settings``
  field the binding/paramization path reads must appear in that tuple
  (or carry a declared exemption with its reason), or a SET serves
  cached bound plans produced under the old regime — the footgun each
  of optimizer/plan_cache_params/scalar_device_enabled once was. Checked
  both ways: a tuple entry nothing in the binding path reads is stale.
"""

from __future__ import annotations

import ast
import os
import re

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report

_GUC_DOC = os.path.join("docs", "GUCS.md")
_GUC_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`")


def _metric_calls(sources):
    """Yield (src, node, kind, name, is_prefix) for every metric write.
    kind: inc | set | observe; is_prefix marks f-string families."""
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            recv = (astutil.dotted(node.func.value) or "").rsplit(".", 1)[-1]
            meth = node.func.attr
            if meth in ("inc", "set") and recv.lstrip("_") == "counters":
                kind = meth
            elif meth == "observe" and recv.lstrip("_") == "histograms":
                kind = "observe"
            else:
                continue
            if not node.args:
                continue
            name = astutil.const_str(node.args[0])
            if name is not None:
                yield src, node, kind, name, False
                continue
            prefix = astutil.fstring_prefix(node.args[0])
            if prefix is not None:
                yield src, node, kind, prefix, True
            else:
                yield src, node, kind, None, False


def _check_metrics(sources, report: Report) -> None:
    from greengage_tpu.runtime.logger import (COUNTER_NAMES, GAUGE_NAMES,
                                              HISTOGRAM_NAMES)

    counters, gauges = set(COUNTER_NAMES), set(GAUGE_NAMES)
    hists = set(HISTOGRAM_NAMES)
    declared = {"inc": counters, "set": gauges, "observe": hists}
    kind_word = {"inc": "counter", "set": "gauge", "observe": "histogram"}
    written: set[str] = set()
    logger_src = sources.get("runtime/logger.py")
    for src, node, kind, name, is_prefix in _metric_calls(sources):
        if src.rel.endswith("runtime/logger.py"):
            continue   # the registry module's own plumbing
        if name is None:
            if not src.pragma_ok(node.lineno, "registry"):
                report.add("registry", src.rel, node.lineno,
                           f"metric-dynamic:{kind}",
                           f"{kind}() with a non-literal metric name — "
                           "the hygiene check cannot see it; use a "
                           "literal or an f-string with a literal prefix")
            continue
        if is_prefix:
            family = {n for n in declared[kind] if n.startswith(name)}
            if not family:
                if not src.pragma_ok(node.lineno, "registry"):
                    report.add("registry", src.rel, node.lineno,
                               f"metric-family:{name}",
                               f"f-string metric family {name!r}* matches "
                               f"no declared {kind_word[kind]} in "
                               "runtime/logger.py")
            written |= family
            continue
        written.add(name)
        if name not in declared[kind] \
                and not src.pragma_ok(node.lineno, "registry"):
            other = ("gauge (use counters.set)" if kind == "inc"
                     and name in gauges else
                     "counter (use counters.inc)" if kind == "set"
                     and name in counters else None)
            detail = (f"declared as a {other}" if other else
                      f"not declared a {kind_word[kind]} in "
                      "runtime/logger.py "
                      f"(COUNTER_NAMES/GAUGE_NAMES/HISTOGRAM_NAMES)")
            report.add("registry", src.rel, node.lineno,
                       f"metric-undeclared:{name}",
                       f"{kind}({name!r}): {detail}")
    for name in sorted((counters | gauges | hists) - written):
        line = 1
        report.add("registry",
                   logger_src.rel if logger_src else "runtime/logger.py",
                   line, f"metric-unwritten:{name}",
                   f"declared metric {name!r} has no writer in the "
                   "package — dead catalog entry (or a family prefix "
                   "typo)")


def _check_gucs(sources, report: Report) -> None:
    cfg = sources.get("config.py")
    if cfg is None:
        return
    fields: dict[str, int] = {}
    for node in ast.walk(cfg.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Settings":
            for item in node.body:
                if isinstance(item, ast.AnnAssign) \
                        and isinstance(item.target, ast.Name) \
                        and not item.target.id.startswith("_"):
                    fields[item.target.id] = item.lineno
    doc_path = os.path.join(astutil.repo_root(), _GUC_DOC)
    documented: set[str] = set()
    if os.path.exists(doc_path):
        with open(doc_path) as f:
            for line in f:
                m = _GUC_ROW_RE.match(line)
                if m:
                    documented.add(m.group(1))
    else:
        report.add("registry", _GUC_DOC, 1, "guc-doc-missing",
                   f"{_GUC_DOC} does not exist: the GUC reference the "
                   "hygiene check validates Settings against")
        return
    for name, line in sorted(fields.items()):
        if name not in documented and not cfg.pragma_ok(line, "registry"):
            report.add("registry", cfg.rel, line, f"guc-undocumented:{name}",
                       f"GUC {name!r} is SET-able but has no row in "
                       f"{_GUC_DOC}")
    for name in sorted(documented - set(fields)):
        report.add("registry", _GUC_DOC, 1, f"guc-phantom:{name}",
                   f"{_GUC_DOC} documents {name!r}, which is not a "
                   "Settings field")


def _fault_name_calls(sources, meth: str):
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != meth:
                continue
            recv = (astutil.dotted(node.func.value) or "").rsplit(".", 1)[-1]
            if recv != "faults":
                continue
            if node.args:
                yield src, node, astutil.const_str(node.args[0])


def _check_faults(pkg_sources, test_sources, report: Report) -> None:
    from greengage_tpu.runtime.faultinject import FAULT_POINTS

    checked: set[str] = set()
    for src, node, name in _fault_name_calls(pkg_sources, "check"):
        if src.rel.endswith("runtime/faultinject.py"):
            continue   # the registry module's own docstring examples
        if name is None:
            continue
        checked.add(name)
        if name not in FAULT_POINTS \
                and not src.pragma_ok(node.lineno, "registry"):
            report.add("registry", src.rel, node.lineno,
                       f"fault-unregistered:{name}",
                       f"faults.check({name!r}) names a point missing "
                       "from runtime/faultinject.py FAULT_POINTS")
    fi = pkg_sources.get("runtime/faultinject.py")
    for name in sorted(FAULT_POINTS - checked):
        report.add("registry",
                   fi.rel if fi else "runtime/faultinject.py", 1,
                   f"fault-unfired:{name}",
                   f"registered fault point {name!r} has no "
                   "faults.check() site — tests arming it test nothing")
    if test_sources is not None:
        for src, node, name in _fault_name_calls(test_sources, "inject"):
            if name is None or name in FAULT_POINTS:
                continue
            if src.pragma_ok(node.lineno, "registry"):
                continue
            report.add("registry", src.rel, node.lineno,
                       f"fault-inject-unknown:{name}",
                       f"test injects unregistered fault point {name!r} "
                       "— it will never fire in the package")


# Binding-path scope: the functions (by module suffix) whose Settings
# reads shape the BOUND PLAN that _select_cache memoizes. sql/binder.py
# and sql/paramize.py are swept whole (they receive settings values via
# these functions today; a future direct read must not escape).
_BINDING_FUNCS = {
    "exec/session.py": ("_cached_plan", "_plan"),
    "sql/binder.py": ("*",),
    "sql/paramize.py": ("*",),
}

# Settings fields the binding path reads that legitimately stay OUT of
# the clear list — each with the reason the cached plans stay valid
PLAN_CACHE_GUC_EXEMPT = {
    "plan_validate": "validation hook only: toggling it changes whether "
                     "_plan raises, never the bound plan it returns",
    "plan_cache_size": "bounds the cache itself, not the plans in it",
}


def _settings_reads(src, fn_names):
    """Yield (field, lineno) for settings.<field> / getattr(settings,
    "<field>") reads inside the named functions ("*" = all)."""
    for fn in astutil.functions(src.tree):
        if "*" not in fn_names and fn.name not in fn_names:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                recv = astutil.dotted(node.value) or ""
                if recv == "settings" or recv.endswith(".settings"):
                    yield node.attr, node.lineno
            elif isinstance(node, ast.Call) \
                    and astutil.call_name(node) == "getattr" \
                    and len(node.args) >= 2:
                recv = astutil.dotted(node.args[0]) or ""
                name = astutil.const_str(node.args[1])
                if name is not None and (recv == "settings"
                                         or recv.endswith(".settings")):
                    yield name, node.lineno


def _clear_list(session_src):
    """The literal tuple guarding the SET handler's _select_cache.clear()
    -> ({names}, lineno) or (None, 0) when the pattern is missing."""
    for node in ast.walk(session_src.tree):
        if not isinstance(node, ast.If) \
                or not isinstance(node.test, ast.Compare) \
                or len(node.test.ops) != 1 \
                or not isinstance(node.test.ops[0], ast.In):
            continue
        lhs = astutil.dotted(node.test.left) or ""
        if not lhs.endswith(".name"):
            continue
        clears = any(
            isinstance(n, ast.Call) and astutil.call_name(n) == "clear"
            and "_select_cache" in (astutil.dotted(n.func.value) or "")
            for stmt in node.body for n in ast.walk(stmt)
            if isinstance(n, ast.Call))
        comp = node.test.comparators[0]
        if clears and isinstance(comp, (ast.Tuple, ast.List)):
            names = {astutil.const_str(e) for e in comp.elts}
            if None not in names:
                return names, node.lineno
    return None, 0


def _check_plan_cache_gucs(sources, report: Report) -> None:
    session = sources.get("exec/session.py")
    if session is None:
        return
    cfg = sources.get("config.py")
    fields = set()
    if cfg is not None:
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Settings":
                fields = {i.target.id for i in node.body
                          if isinstance(i, ast.AnnAssign)
                          and isinstance(i.target, ast.Name)}
    cleared, tuple_line = _clear_list(session)
    if cleared is None:
        report.add("registry", session.rel, 1, "plan-cache-clear-missing",
                   "exec/session.py SET handler no longer clears "
                   "_select_cache for a literal GUC tuple — the "
                   "plan-cache invalidation contract this lint checks")
        return
    reads: dict[str, tuple[str, int]] = {}
    for suffix, fn_names in _BINDING_FUNCS.items():
        src = sources.get(suffix)
        if src is None:
            continue
        for field, line in _settings_reads(src, fn_names):
            if field in fields:
                reads.setdefault(field, (src.rel, line))
    for field in sorted(set(reads) - cleared - set(PLAN_CACHE_GUC_EXEMPT)):
        rel, line = reads[field]
        src = next((s for s in sources if s.rel == rel), None)
        if src is not None and src.pragma_ok(line, "registry"):
            continue
        report.add(
            "registry", rel, line, f"plan-cache-guc-unclears:{field}",
            f"binding/paramization reads Settings.{field} but the SET "
            "handler's _select_cache.clear() tuple does not list it — "
            "SET would keep serving bound plans from the old regime "
            "(add it to the tuple in exec/session.py, or to "
            "PLAN_CACHE_GUC_EXEMPT with its reason)")
    for name in sorted(cleared - set(reads)):
        report.add(
            "registry", session.rel, tuple_line,
            f"plan-cache-guc-stale:{name}",
            f"the SET handler clears _select_cache for {name!r}, but the "
            "binding path no longer reads that field — stale tuple entry")
    for name in sorted(cleared - fields):
        report.add(
            "registry", session.rel, tuple_line,
            f"plan-cache-guc-phantom:{name}",
            f"the SET handler's clear tuple names {name!r}, which is not "
            "a Settings field")


def run(sources=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet()
    tests_dir = os.path.join(astutil.repo_root(), "tests")
    test_sources = (astutil.SourceSet(roots=[tests_dir])
                    if os.path.isdir(tests_dir) else None)
    _check_metrics(sources, report)
    _check_gucs(sources, report)
    _check_faults(sources, test_sources, report)
    _check_plan_cache_gucs(sources, report)
    return report
