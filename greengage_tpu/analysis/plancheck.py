"""Plan-tree invariant validation — the checkPlan-before-dispatch analog.

Reference parity: the reference walks every sliced plan and asserts its
Motion/slice/distribution structure before dispatch (cdbmutate.c's
checkPlan machinery); Theseus (PAPERS.md) credits validating
data-movement plans *before* execution for much of its reliability at
scale. ``validate_plan`` is that walk for our trees: it runs on every
planned statement when the ``plan_validate`` GUC is on (the default —
the walk is O(nodes) of pure-host attribute checks, noise against
planning cost) and over the whole TPC-H/TPC-DS corpus in
``tests/test_analysis.py``.

Invariants (each names its planner contract):

I1  every node carries a locus; partitioned/replicated loci carry a
    positive segment width; HASHED loci carry keys resolvable in the
    node's own or its children's output columns.
I2  Motions sit exactly at distribution boundaries: GATHER lands on
    ENTRY, BROADCAST turns a partitioned/SingleQE child replicated,
    REDISTRIBUTE carries hash exprs and lands HASHED (or SingleQE via
    the constant-key funnel the planner uses for buried LIMITs and
    exotic windows, or STREWN for computed keys); a range-spec
    REDISTRIBUTE (sampled-splitter window repartition) lands STREWN
    with exactly the leading order key.
I3  ENTRY exists only at the root, which is the single Gather Motion —
    an interior Gather is a hidden one-chip funnel in a plan that
    claims parallel execution; a global-mode Window above a SingleQE
    funnel is the same lie one node up.
I4  a Join whose two children are both partitioned must have them
    co-located on its join keys (cdbpath_motion_for_join's contract):
    HASHED sides correspond pairwise through the join-key equivalence,
    computed-key sides are the planner's own paired Redistributes.
I5  Aggregate/Window locality claims hold: a single-phase grouped agg
    over a HASHED child is hashed on its group keys; a grouped final
    agg sits above the state Redistribute; a scalar final sits above
    the partial-state Broadcast; a non-global Window owns whole
    partitions per segment; an ordered-global Window carries a
    packed/full64 gkey_spec inside the 64-bit budget; a range-mode
    Window sits directly above its range Redistribute (whole key
    ranges per segment).
I6  Scan annotations are well-formed: prune predicates reference only
    existing storage columns with sane ops and Param/host values,
    direct dispatch targets a real segment, index hits name real
    indexes.
I7  (via ``validate_capacities``, needs a Compiler) every node's static
    batch capacity is a positive int and every unpinned scan capacity
    sits on its pow2 bucket — the PR-5 executable-reuse contract.

Violations raise ``PlanInvariantError`` naming the node path from the
root, e.g. ``Motion(Gather)/Sort/Aggregate(final)``.
"""

from __future__ import annotations

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu.planner.locus import LocusKind
from greengage_tpu.planner.logical import (Aggregate, ConstRel, Join, Limit,
                                           Motion, MotionKind, PartialState,
                                           Plan, Scan, Window)

_PRUNE_OPS = ("=", "<", "<=", ">", ">=")


class PlanInvariantError(AssertionError):
    """A planned tree violates a distribution/shape invariant. ``path``
    names the offending node from the root; ``invariant`` is the I-code
    above (stable for tests and triage)."""

    def __init__(self, invariant: str, path: str, message: str):
        super().__init__(f"{invariant} at {path}: {message}")
        self.invariant = invariant
        self.path = path


def _node_label(node: Plan) -> str:
    name = type(node).__name__
    if isinstance(node, Motion):
        return f"{name}({node.kind.value})"
    if isinstance(node, Aggregate):
        return f"{name}({node.phase})"
    if isinstance(node, Scan):
        return f"{name}({node.table})"
    if isinstance(node, Join):
        return f"{name}({node.kind})"
    return name


def _out_ids(node: Plan) -> set[str]:
    try:
        return {c.id for c in node.out_cols()}
    except NotImplementedError:
        return set()


def _is_const_expr(e) -> bool:
    return isinstance(e, E.Literal) or (
        isinstance(e, E.Cast) and _is_const_expr(e.arg))


def _is_param_value(v) -> bool:
    if isinstance(v, E.Param):
        return v.slot >= 0
    if isinstance(v, E.Cast):
        return _is_param_value(v.arg)
    return False


def _redistributed_by(child: Plan, keys: list) -> bool:
    """True when ``child`` is the planner's own Redistribute by exactly
    these join keys (the computed-key co-location path: both sides land
    STREWN but physically aligned because the SAME expressions hash)."""
    if not (isinstance(child, Motion)
            and child.kind is MotionKind.REDISTRIBUTE):
        return False
    he = child.hash_exprs
    if len(he) != len(keys):
        return False
    return all(a is b or repr(a) == repr(b) for a, b in zip(he, keys))


def _join_colocated(node: Join) -> bool:
    ll, rl = node.left.locus, node.right.locus
    pairs = [(lk.name if isinstance(lk, E.ColRef) else None,
              rk.name if isinstance(rk, E.ColRef) else None)
             for lk, rk in zip(node.left_keys, node.right_keys)]
    l2r = {a: b for a, b in pairs if a and b}
    if ll.kind is LocusKind.HASHED and rl.kind is LocusKind.HASHED:
        if ll.numsegments != rl.numsegments or len(ll.keys) != len(rl.keys):
            return False
        return all(l2r.get(a) == b for a, b in zip(ll.keys, rl.keys))
    # computed-key co-location: a STREWN side must be the planner's own
    # paired Redistribute; a HASHED side must cover its join keys
    ok_left = (_redistributed_by(node.left, list(node.left_keys))
               if ll.kind is LocusKind.STREWN
               else ll.kind is LocusKind.HASHED
               and all(k in {a for a, _ in pairs if a} for k in ll.keys))
    ok_right = (_redistributed_by(node.right, list(node.right_keys))
                if rl.kind is LocusKind.STREWN
                else rl.kind is LocusKind.HASHED
                and all(k in {b for _, b in pairs if b} for k in rl.keys))
    return ok_left and ok_right


def validate_plan(plan: Plan, catalog=None) -> None:
    """Walk a PLANNED tree and raise ``PlanInvariantError`` on the first
    violated invariant. ``catalog`` (optional) enables the schema-aware
    half of I6 (prune columns / indexes actually exist)."""
    root = plan
    gathers = [n for n in _walk(plan) if isinstance(n, Motion)
               and n.kind is MotionKind.GATHER]
    if len(gathers) > 1 or (gathers and gathers[0] is not root):
        bad = next(g for g in gathers if g is not root)
        raise PlanInvariantError(
            "I3", _path_to(root, bad),
            "interior Gather Motion: a funnel inside a plan that claims "
            "parallel execution (only the root gathers)")
    _validate(root, root, [], catalog)


def _walk(plan: Plan):
    stack = [plan]
    while stack:
        p = stack.pop()
        yield p
        stack.extend(p.children)


def _path_to(root: Plan, target: Plan) -> str:
    """Root-to-target label path (for error text)."""
    path: list[str] = []

    def rec(node: Plan, acc: list[str]) -> bool:
        acc.append(_node_label(node))
        if node is target:
            path.extend(acc)
            return True
        for c in node.children:
            if rec(c, acc):
                return True
        acc.pop()
        return False

    rec(root, [])
    return "/".join(path) or _node_label(target)


def _fail(invariant: str, trail: list[str], node: Plan, msg: str):
    path = "/".join(trail + [_node_label(node)])
    raise PlanInvariantError(invariant, path, msg)


def _validate(node: Plan, root: Plan, trail: list[str], catalog) -> None:
    locus = node.locus
    # ---- I1: locus well-formedness ---------------------------------
    if locus is None:
        _fail("I1", trail, node, "node has no locus (planner never "
              "visited it)")
    if locus.kind in (LocusKind.HASHED, LocusKind.STREWN,
                      LocusKind.SEGMENT_GENERAL, LocusKind.SINGLE_QE) \
            and locus.numsegments < 1:
        _fail("I1", trail, node,
              f"{locus.kind.value} locus with numsegments="
              f"{locus.numsegments}")
    if locus.kind is LocusKind.HASHED:
        if not locus.keys:
            _fail("I1", trail, node, "HASHED locus with no keys")
        visible = _out_ids(node)
        for c in node.children:
            visible |= _out_ids(c)
        missing = [k for k in locus.keys if k not in visible]
        if missing and visible:
            _fail("I1", trail, node,
                  f"HASHED locus keys {missing} resolve in neither this "
                  "node's nor its children's output columns")
    if node.est_rows < 0:
        _fail("I1", trail, node, f"negative est_rows {node.est_rows}")
    # ---- I3: ENTRY only at the root --------------------------------
    if locus.kind is LocusKind.ENTRY and node is not root:
        _fail("I3", trail, node,
              "interior ENTRY locus (coordinator-only rows below the "
              "top Gather)")
    # ---- I2: Motion boundary shapes --------------------------------
    if isinstance(node, Motion):
        child_locus = node.child.locus
        if child_locus is None:
            _fail("I1", trail, node, "Motion child has no locus")
        elif node.kind is MotionKind.GATHER:
            if locus.kind is not LocusKind.ENTRY:
                _fail("I2", trail, node,
                      f"Gather lands on {locus.kind.value}, not Entry")
            if child_locus.kind is LocusKind.ENTRY:
                _fail("I2", trail, node, "Gather above ENTRY rows moves "
                      "nothing")
        elif node.kind is MotionKind.BROADCAST:
            if locus.kind is not LocusKind.SEGMENT_GENERAL:
                _fail("I2", trail, node,
                      f"Broadcast lands on {locus.kind.value}, not "
                      "SegmentGeneral")
            if child_locus.kind not in (LocusKind.HASHED, LocusKind.STREWN,
                                        LocusKind.SINGLE_QE):
                _fail("I2", trail, node,
                      f"Broadcast of already-replicated "
                      f"{child_locus.kind.value} rows duplicates them")
        elif node.kind is MotionKind.REDISTRIBUTE:
            if locus.kind not in (LocusKind.HASHED, LocusKind.STREWN,
                                  LocusKind.SINGLE_QE):
                _fail("I2", trail, node,
                      f"Redistribute lands on {locus.kind.value}")
            if not node.hash_exprs:
                _fail("I2", trail, node, "Redistribute with no hash exprs")
            if locus.kind is LocusKind.SINGLE_QE \
                    and not all(_is_const_expr(e) for e in node.hash_exprs):
                _fail("I2", trail, node,
                      "SingleQE funnel must hash on constants")
            if getattr(node, "range_spec", None) is not None:
                # range repartition: rows route by key RANGES, not a
                # hash — claiming HASHED (or a funnel) would let a join
                # co-locate against a distribution that does not exist
                if locus.kind is not LocusKind.STREWN:
                    _fail("I2", trail, node,
                          f"range Redistribute lands {locus.kind.value}, "
                          "not Strewn")
                if len(node.hash_exprs) != 1:
                    _fail("I2", trail, node,
                          "range Redistribute must carry exactly the "
                          "leading order key")
            if locus.kind is LocusKind.HASHED \
                    and len(locus.keys) != len(node.hash_exprs):
                _fail("I2", trail, node,
                      f"{len(locus.keys)} locus keys for "
                      f"{len(node.hash_exprs)} hash exprs")
    # ---- I4: join co-location --------------------------------------
    if isinstance(node, Join):
        ll, rl = node.left.locus, node.right.locus
        if ll is not None and rl is not None \
                and ll.is_partitioned and rl.is_partitioned:
            if node.kind == "cross":
                _fail("I4", trail, node,
                      "cross join with BOTH sides partitioned (build side "
                      "must be replicated)")
            if not _join_colocated(node):
                _fail("I4", trail, node,
                      f"sides {ll.describe()} x {rl.describe()} are not "
                      "co-located on the join keys and neither moved")
    # ---- I5: aggregate / window locality ---------------------------
    if isinstance(node, Aggregate):
        child_locus = node.child.locus
        if child_locus is not None and node.phase == "single" \
                and node.group_keys and child_locus.is_partitioned:
            key_ids = tuple(e.name for _, e in node.group_keys
                            if isinstance(e, E.ColRef))
            if not child_locus.hashed_on(key_ids):
                _fail("I5", trail, node,
                      f"single-phase grouped aggregate over "
                      f"{child_locus.describe()} child not hashed on its "
                      f"group keys {key_ids}")
        if child_locus is not None and node.phase == "final":
            if node.group_keys:
                ids = tuple(c.id for c, _ in node.group_keys)
                if child_locus.is_partitioned \
                        and not child_locus.hashed_on(ids):
                    _fail("I5", trail, node,
                          "final grouped aggregate child is partitioned "
                          f"({child_locus.describe()}) but not hashed on "
                          "the group state keys")
            elif child_locus.kind not in (LocusKind.SEGMENT_GENERAL,
                                          LocusKind.ENTRY,
                                          LocusKind.SINGLE_QE):
                _fail("I5", trail, node,
                      "scalar final aggregate needs replicated partial "
                      f"states, child is {child_locus.describe()}")
    if isinstance(node, Window):
        child_locus = node.child.locus
        gm = getattr(node, "global_mode", False)
        is_global = bool(gm)
        if child_locus is not None and not is_global \
                and child_locus.is_partitioned:
            key_ids = tuple(e.name for e in node.partition_keys
                            if isinstance(e, E.ColRef))
            if not node.partition_keys:
                _fail("I5", trail, node,
                      "non-global whole-table window over partitioned "
                      f"rows ({child_locus.describe()}) — partitions "
                      "span segments")
            elif not child_locus.hashed_on(key_ids):
                _fail("I5", trail, node,
                      f"window partitions split across segments: child "
                      f"{child_locus.describe()} not hashed on "
                      f"PARTITION BY keys {key_ids}")
        if is_global:
            # gather-free global windows: the shape claims rows never
            # funnel, so the claim must be machine-checkable — a global
            # window above a SingleQE funnel is a hidden one-chip plan
            # wearing a distributed label (I3's spirit, node-local half)
            if node.partition_keys:
                _fail("I5", trail, node,
                      "global window carries PARTITION BY keys")
            if child_locus is not None \
                    and child_locus.kind is LocusKind.SINGLE_QE:
                _fail("I3", trail, node,
                      "global-mode window above a SingleQE funnel — the "
                      "gather-free claim is false")
            spec = getattr(node, "gkey_spec", None)
            if gm == "ordered":
                if not node.order_keys:
                    _fail("I5", trail, node,
                          "ordered-global window with no ORDER BY keys")
                if not isinstance(spec, dict) \
                        or spec.get("mode") not in ("packed", "full64"):
                    _fail("I5", trail, node,
                          "ordered-global window without a packed/full64 "
                          f"gkey_spec (got {spec!r})")
                if spec.get("mode") == "packed":
                    total = sum(int(f.get("bits", 0)) + 1
                                for f in spec.get("fields", ()))
                    if not spec.get("fields") or total > 64 or any(
                            int(f.get("bits", 0)) < 1
                            for f in spec["fields"]):
                        _fail("I5", trail, node,
                              f"packed gkey_spec fields exceed the 64-bit "
                              f"budget or carry zero-width fields "
                              f"({total} bits)")
            elif gm == "range":
                if not isinstance(spec, dict) or spec.get("mode") != "range":
                    _fail("I5", trail, node,
                          "range-mode window without a range gkey_spec")
                ch = node.child
                if not (isinstance(ch, Motion)
                        and ch.kind is MotionKind.REDISTRIBUTE
                        and getattr(ch, "range_spec", None) is not None):
                    _fail("I5", trail, node,
                          "range-mode window's child is not a range "
                          "Redistribute — segments would not own whole "
                          "key ranges")
            elif node.order_keys:
                _fail("I5", trail, node,
                      "unordered-global window carries ORDER BY keys")
    # ---- I6: scan annotations --------------------------------------
    if isinstance(node, Scan):
        _validate_scan(node, trail, catalog)
    trail.append(_node_label(node))
    for c in node.children:
        _validate(c, root, trail, catalog)
    trail.pop()


def _validate_scan(node: Scan, trail: list[str], catalog) -> None:
    schema = None
    if catalog is not None:
        try:
            schema = catalog.get(node.table)
        except Exception:
            schema = None   # aux/external relations live outside it
    col_names = ({c.name for c in schema.columns} if schema is not None
                 else {c.name for c in node.cols})
    for pred in node.prune_preds or ():
        if len(pred) != 3:
            _fail("I6", trail, node, f"malformed prune predicate {pred!r}")
        col, op, v = pred
        if op not in _PRUNE_OPS:
            _fail("I6", trail, node, f"prune predicate op {op!r}")
        # raw-TEXT device predicates prune on derived sidecar columns:
        # @rl:<col> (byte length) and @rp:<col>:<word> (prefix words) —
        # the BASE column must exist (binder _device_raw_pred)
        base = col
        if col.startswith("@rl:"):
            base = col[4:]
        elif col.startswith("@rp:"):
            base = col[4:].rsplit(":", 1)[0]
        if base not in col_names:
            _fail("I6", trail, node,
                  f"prune predicate references unknown column {col!r} "
                  f"of {node.table}")
        if isinstance(v, E.Expr):
            if not _is_param_value(v):
                _fail("I6", trail, node,
                      f"prune value for {col} is a non-Param expression "
                      f"{type(v).__name__} (must resolve at staging)")
        elif not isinstance(v, (int, float, np.integer, np.floating)):
            _fail("I6", trail, node,
                  f"prune value for {col} is {type(v).__name__}, not a "
                  "host scalar")
    if node.direct_seg is not None:
        nseg = node.locus.numsegments if node.locus is not None else 0
        if not (0 <= node.direct_seg < max(nseg, 1)):
            _fail("I6", trail, node,
                  f"direct dispatch to segment {node.direct_seg} of "
                  f"{nseg}")
    if node.index_hits and schema is not None:
        known = set(getattr(schema, "indexes", {}) or {})
        bad = [i for i in node.index_hits if i not in known]
        if bad:
            _fail("I6", trail, node, f"index hits {bad} name no index of "
                  f"{node.table}")


# ---------------------------------------------------------------------
# I7: capacity bucketing (needs a Compiler — used by the corpus test and
# `gg check --plans`, not the per-statement GUC hook, because capacities
# are a compile-time property, not a plan property)
# ---------------------------------------------------------------------

def validate_capacities(compiler, plan: Motion) -> None:
    """Assert the PR-5 capacity contract over a compiled statement's
    Compiler: every node's static batch capacity is a positive int and
    every non-overridden scan capacity sits exactly on its pow2 bucket
    (shape-stable executable reuse across within-bucket DML)."""
    from greengage_tpu.exec.compile import _pow2

    compiler._reset_scan_state()
    compiler._nids = {}
    stack = [plan]
    while stack:
        p = stack.pop()
        compiler._nids[id(p)] = len(compiler._nids)
        stack.extend(reversed(p.children))
    compiler._collect_scans(plan.child if isinstance(plan, Motion) else plan)
    compiler._merge_unpinned_scan_caps()
    for table, cap in compiler.scan_caps.items():
        if table in compiler.scan_cap_override:
            continue   # spill chunk bounds are exact pass boundaries
        if cap < 1 or _pow2(cap) != cap:
            raise PlanInvariantError(
                "I7", f"Scan({table})",
                f"scan capacity {cap} is not pow2-bucketed")
    for p in _walk(plan):
        if isinstance(p, (ConstRel, PartialState)):
            continue
        try:
            cap = compiler._capacity_of(p)
        except NotImplementedError:
            continue
        if not isinstance(cap, (int, np.integer)) or cap < 1:
            raise PlanInvariantError(
                "I7", _path_to(plan, p),
                f"node capacity {cap!r} is not a positive host int")
        if isinstance(p, Limit) and p.limit is not None:
            if cap > max(compiler._capacity_of(p.child), 1):
                raise PlanInvariantError(
                    "I7", _path_to(plan, p),
                    f"Limit capacity {cap} exceeds its child's")
