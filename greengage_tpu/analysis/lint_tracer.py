"""Tracer-safety lint — the PR-5 jnp-identity-under-jit bug class.

Two checks:

**tracer-sync** — inside jit-traced code (every function in
``greengage_tpu/ops/`` — the device scalar library ``ops/scalar.py``
included, whose byte-window and civil-date kernels run under trace —
plus the closures nested inside ``exec/compile.py`` methods, the
``seg_fn``/``run`` bodies that execute
under ``jax.jit(_shard_map(...))``), a value produced by
``jnp.*``/``lax.*`` is a *tracer*; forcing it to a host scalar —
``.item()``, ``float()``/``int()``/``bool()``, ``np.asarray``/
``np.array`` — either raises ``ConcretizationTypeError`` at trace time
or, worse, silently bakes a wrong constant (the PR-5 fused min/max
identity bug: ``jnp.array`` identity + ``ident.item()``). The lint
taints names assigned from jnp/lax calls (propagated through simple
expressions and method chains) and flags host-forcing operations on
tainted values. Host-concrete numpy identities (``np.array(...)`` then
``.item()``) stay legal — that IS the fix pattern.

**cache-key** — the executable-reuse signature must digest only
bucketed/stable inputs: every ``est_*`` estimate field on a plan node
dataclass must be listed in ``Compiler._SIG_SKIP_FIELDS`` (estimates
reach the program only through pow2-bucketed capacities), and the
signature functions must not read estimate fields or nondeterministic
sources (``id()``, ``time.*``, ``random.*``) directly — any of those in
the key silently fractures (or worse, falsely merges) executable reuse.
"""

from __future__ import annotations

import ast

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report

_TRACED_ROOTS = ("jnp", "lax")
_HOST_FORCE = {"float", "int", "bool", "complex"}
_SIG_FUNCS = ("shape_signature", "codegen_settings_sig")
_EST_PREFIXES = ("est_", "expand_est")


def _is_traced_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = astutil.dotted(node.func)
    return bool(d) and d.split(".", 1)[0] in _TRACED_ROOTS


def _tainted_names(fn: ast.AST) -> set[str]:
    """Names bound (directly or through simple expressions/method chains)
    to jnp/lax results within this function body."""
    tainted: set[str] = set()

    def expr_tainted(e: ast.expr) -> bool:
        if _is_traced_call(e):
            return True
        if isinstance(e, ast.Name):
            return e.id in tainted
        if isinstance(e, ast.BinOp):
            return expr_tainted(e.left) or expr_tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return expr_tainted(e.operand)
        if isinstance(e, ast.Subscript):
            return expr_tainted(e.value)
        if isinstance(e, ast.IfExp):
            return expr_tainted(e.body) or expr_tainted(e.orelse)
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute):
            # method chain on a tainted value (x.astype(...), x.sum())
            return expr_tainted(e.func.value)
        if isinstance(e, ast.Attribute):
            return expr_tainted(e.value)
        return False

    for _ in range(3):   # small fixpoint for chained assignments
        before = len(tainted)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        tainted.update(e.id for e in t.elts
                                       if isinstance(e, ast.Name))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and expr_tainted(node.value):
                tainted.add(node.target.id)
        if len(tainted) == before:
            break
    return tainted


def _flag_host_sync(src, fn, where: str, report: Report) -> None:
    tainted = _tainted_names(fn)

    def is_tainted(e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in tainted
        if _is_traced_call(e):
            return True
        if isinstance(e, (ast.Subscript, ast.Attribute)):
            return is_tainted(e.value)
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute):
            return is_tainted(e.func.value)
        return False

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        name = astutil.call_name(node)
        if name == "item" and isinstance(node.func, ast.Attribute) \
                and is_tainted(node.func.value):
            hit = ".item() on a traced value"
        elif isinstance(node.func, ast.Name) and name in _HOST_FORCE \
                and node.args and is_tainted(node.args[0]):
            hit = f"{name}() on a traced value"
        else:
            d = astutil.dotted(node.func) or ""
            if d in ("np.asarray", "np.array", "numpy.asarray",
                     "numpy.array") and node.args \
                    and is_tainted(node.args[0]):
                hit = f"{d}() on a traced value"
        if hit is None:
            continue
        if src.pragma_ok(node.lineno, "tracer"):
            continue
        report.add(
            "tracer", src.rel, node.lineno,
            f"{where}:{fn.name}:{hit}",
            f"host sync inside jit-traced code: {hit} in {fn.name}() — "
            "under trace this concretizes a tracer (the PR-5 identity "
            "bug class); keep the value device-side or build it "
            "host-concrete with numpy BEFORE tracing")


def _est_fields(sources) -> set[str]:
    src = sources.get("planner/logical.py")
    out: set[str] = set()
    if src is None:
        return out
    for node in ast.walk(src.tree):
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            n = node.target.id
            if n.startswith(_EST_PREFIXES[0]) or n in _EST_PREFIXES:
                out.add(n)
    # est_rows lives on the Plan base via field(); AnnAssign covers it
    return out


def _check_cache_keys(sources, report: Report) -> None:
    comp = sources.get("exec/compile.py")
    if comp is None:
        return
    est = _est_fields(sources)
    skip: set[str] = set()
    skip_line = 1
    for node in ast.walk(comp.tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "_SIG_SKIP_FIELDS"
                        for t in node.targets):
            skip_line = node.lineno
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    skip.add(c.value)
    missing = sorted(est - skip)
    if missing:
        report.add(
            "tracer", comp.rel, skip_line, "sig-skip:" + ",".join(missing),
            f"estimate field(s) {missing} are not in "
            "Compiler._SIG_SKIP_FIELDS: raw estimates in the shape "
            "signature fracture executable reuse on every ANALYZE "
            "(estimates may only reach programs via bucketed capacities)")
    for fn in astutil.functions(comp.tree):
        if fn.name not in _SIG_FUNCS:
            continue
        # id() used as a SUBSCRIPT KEY builds the preorder-ordinal map
        # (id -> ordinal, a per-walk identity table) — only id() values
        # flowing into the digested payload itself are unstable
        keyed_ids: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript):
                for sub in ast.walk(node.slice):
                    if isinstance(sub, ast.Call) \
                            and astutil.dotted(sub.func) == "id":
                        keyed_ids.add(id(sub))
        for node in ast.walk(fn):
            bad = None
            if isinstance(node, ast.Attribute) and (
                    node.attr.startswith("est_")
                    or node.attr in _EST_PREFIXES):
                bad = f"reads .{node.attr}"
            elif isinstance(node, ast.Call):
                d = astutil.dotted(node.func) or ""
                if (d == "id" and id(node) not in keyed_ids) \
                        or d.startswith(("time.", "random.")):
                    bad = f"calls {d}()"
            if bad is None:
                continue
            if comp.pragma_ok(node.lineno, "tracer"):
                continue
            report.add(
                "tracer", comp.rel, node.lineno,
                f"sig-unstable:{fn.name}:{bad}",
                f"{fn.name}() {bad}: executable-cache keys must digest "
                "only bucketed, process-stable values")


def run(sources=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet()
    for src in sources:
        in_ops = "/ops/" in src.rel.replace("\\", "/")
        is_compile = src.rel.endswith("exec/compile.py")
        if not in_ops and not is_compile:
            continue
        if in_ops:
            for fn in astutil.functions(src.tree):
                _flag_host_sync(src, fn, "ops", report)
        else:
            # compile.py: only the NESTED closures run under trace (the
            # methods themselves run at compile time on the host)
            nested: dict[int, ast.AST] = {}
            for f in astutil.functions(src.tree):
                for inner in ast.walk(f):
                    if inner is not f and isinstance(
                            inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        nested[id(inner)] = inner
            for inner in nested.values():
                _flag_host_sync(src, inner, "traced", report)
    _check_cache_keys(sources, report)
    return report
