"""Multi-key sort via lexicographic lax.sort — the tuplesort analog.

Every ORDER BY key is encoded into an order-preserving uint64:

  int-like  : x XOR sign-bit                         (two's complement flip)
  float64   : IEEE trick (negatives bit-inverted)
  text      : dictionary-rank LUT gather (int32 rank), host-precomputed —
              code order is first-seen order, NOT collation order, so the
              binder always routes text keys through a rank Lut
  DESC      : bitwise NOT of the encoding
  NULLs     : a separate leading uint8 operand per nullable key orders the
              null group before/after values without sacrificing key bits
              (PG defaults: NULLS LAST for ASC, NULLS FIRST for DESC)

Dead rows (sel = false) sort to the end via a leading liveness key, so the
output batch keeps static capacity with survivors compacted to the front —
which is what LIMIT slicing and host gather want.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from greengage_tpu import types as T


@dataclass
class SortKey:
    values: jnp.ndarray
    valid: jnp.ndarray | None
    type: object                      # T.SqlType
    desc: bool = False
    nulls_first: bool | None = None   # None = PG default by direction
    rank_lut: jnp.ndarray | None = None  # TEXT collation ranks


def encode_key64(v, desc: bool, kind: str) -> jnp.ndarray:
    """Order-preserving uint64 encoding of one order-key column — the
    SINGLE source of the sign-flip / IEEE-monotone transform, shared by
    the multi-operand sort (_order_encode), the full64 ordered-global
    window ranks, and the range-repartition Motion routing
    (exec/compile.py) so the encodings can never drift apart.
    ``kind``: "float" (IEEE trick, negatives bit-inverted) or "int"
    (two's-complement sign flip); DESC = bitwise NOT."""
    if kind == "float":
        bits = v.astype(jnp.float64).view(jnp.uint64)
        sign = bits >> jnp.uint64(63)
        enc = jnp.where(sign == 1, ~bits,
                        bits | jnp.uint64(1) << jnp.uint64(63))
    else:
        enc = v.astype(jnp.int64).view(jnp.uint64) \
            ^ (jnp.uint64(1) << jnp.uint64(63))
    if desc:
        enc = ~enc
    return enc


def _order_encode(k: SortKey) -> list[jnp.ndarray]:
    """-> sort operands for this key: [null_order?, encoded_values]."""
    t: T.SqlType = k.type
    v = k.values
    if t.kind is T.Kind.TEXT:
        if k.rank_lut is None:
            raise ValueError("text sort key requires rank LUT")
        idx = jnp.where(v < 0, k.rank_lut.shape[0] - 1, v)
        v = k.rank_lut[idx]
    enc = encode_key64(
        v, k.desc, "float" if t.kind is T.Kind.FLOAT64 else "int")
    ops = [enc]
    if k.valid is not None:
        nulls_first = k.nulls_first if k.nulls_first is not None else k.desc
        null_pos = jnp.uint8(0) if nulls_first else jnp.uint8(1)
        ops.insert(0, jnp.where(k.valid, jnp.uint8(1) - null_pos, null_pos))
        # neutralize the value operand for null rows so ties are deterministic
        ops[1] = jnp.where(k.valid, enc, jnp.uint64(0))
    return ops


def order_bounds_bits(bounds: list | None, nkeys: int) -> int | None:
    """Shared field-width budget for ORDER BY key packing: per-key (lo, hi)
    integer bounds must be known for EVERY key and their (span + NULL slot)
    fields fit 63 bits (bit 63 carries the dead-row flag). Both the runtime
    check (order_pack_bits) and the compiler's static feasibility mirror
    (exec/compile._static_order_packable) call this, so the width rule can
    never drift between them."""
    if bounds is None or len(bounds) != nkeys \
            or any(b is None for b in bounds):
        return None
    total = 0
    for lo, hi in bounds:
        span = int(hi) - int(lo) + 1
        if span <= 0:
            return None
        total += max(span.bit_length(), 1)   # span+1 field values (NULL)
        if total > 63:
            return None
    return total


def order_pack_bits(keys: list[SortKey], bounds: list | None) -> int | None:
    """Packed-operand feasibility for concrete SortKeys: the shared bounds
    budget plus per-key runtime facts (TEXT collation ranks are not
    packable)."""
    if any(k.rank_lut is not None for k in keys):
        return None
    return order_bounds_bits(bounds, len(keys))


def pack_order_keys(keys: list[SortKey], bounds: list, sel):
    """Order-preserving pack of bounded integer ORDER BY keys into one
    uint64 (dead flag at bit 63, fields MSB-first in key priority):

      ASC : field = v - lo (+1 when NULLS FIRST); NULL = 0 or span
      DESC: field = hi - v (+1 when NULLS FIRST); NULL = 0 or span

    -> (word uint64[n], violation bool scalar): violation = a live non-NULL
    value outside its advertised bound (stale stats) — packing would
    mis-order, caller re-runs unpacked."""
    n = sel.shape[0]
    word = jnp.zeros((n,), jnp.uint64)
    violation = jnp.zeros((), bool)
    for k, (lo, hi) in zip(keys, bounds):
        span = int(hi) - int(lo) + 1
        width = max(span.bit_length(), 1)
        v = k.values.astype(jnp.int64)
        in_b = (v >= lo) & (v <= hi)
        live = sel if k.valid is None else (sel & k.valid)
        violation = violation | jnp.any(live & ~in_b)
        base = (jnp.int64(hi) - v) if k.desc else (v - jnp.int64(lo))
        base = jnp.where(in_b, base, 0)
        nulls_first = k.nulls_first if k.nulls_first is not None else k.desc
        if nulls_first:
            field = base + 1
            null_val = 0
        else:
            field = base
            null_val = span
        if k.valid is not None:
            field = jnp.where(k.valid, field, jnp.int64(null_val))
        word = (word << jnp.uint64(width)) | field.astype(jnp.uint64)
    word = jnp.where(sel, word, word | (jnp.uint64(1) << jnp.uint64(63)))
    return word, violation


def sort_batch(keys: list[SortKey], sel, capacity: int,
               bounds: list | None = None):
    """-> (perm int32[capacity], sel_sorted bool[capacity], violation).

    perm is the gather permutation: out_col = col[perm]. Stable on ties
    (row index is the final operand). ``bounds`` enables the packed
    single-operand sort; violation is None when packing wasn't attempted,
    else a bool scalar the caller must route to a pack-overflow flag.
    """
    if bounds is not None and order_pack_bits(keys, bounds) is not None:
        word, violation = pack_order_keys(keys, bounds, sel)
        sorted_ops = lax.sort(
            (word, jnp.arange(capacity, dtype=jnp.int32)), num_keys=2)
        perm = sorted_ops[-1]
        sel_sorted = (sorted_ops[0] >> jnp.uint64(63)) == 0
        return perm, sel_sorted, violation

    dead = (~sel).astype(jnp.uint8)        # live rows first
    operands = [dead]
    for k in keys:
        operands.extend(_order_encode(k))
    operands.append(jnp.arange(capacity, dtype=jnp.int32))
    sorted_ops = lax.sort(tuple(operands), num_keys=len(operands))
    perm = sorted_ops[-1]
    sel_sorted = sorted_ops[0] == 0
    return perm, sel_sorted, None


def apply_perm(cols: dict, valids: dict, perm):
    out_c = {n: a[perm] for n, a in cols.items()}
    out_v = {n: (a[perm] if a is not None else None) for n, a in valids.items()}
    return out_c, out_v


def limit(cols: dict, valids: dict, sel, k: int):
    """Static LIMIT after a sort (rows already compacted to the front)."""
    out_c = {n: a[:k] for n, a in cols.items()}
    out_v = {n: (a[:k] if a is not None else None) for n, a in valids.items()}
    return out_c, out_v, sel[:k]


def compact(cols: dict, valids: dict, sel, k: int):
    """Gather the live rows (order preserved) into the first min(live, k)
    slots of a k-capacity batch — WITHOUT a sort. On TPU a lax.sort costs
    ~25s of XLA compile time per call site and hundreds of ms at runtime;
    this is a cumsum + one binary-search gather per column instead:
    output slot j reads the row where cumsum(sel) first reaches j+1.

    -> (cols, valids, sel_out) with capacity k; rows beyond k are DROPPED
    (callers pair this with an overflow flag on count > k).
    """
    n = sel.shape[0]
    cs = jnp.cumsum(sel.astype(jnp.int32))
    total = cs[-1] if n else jnp.int32(0)
    src = jnp.searchsorted(cs, jnp.arange(1, k + 1, dtype=jnp.int32))
    src = jnp.clip(src, 0, max(n - 1, 0)).astype(jnp.int32)
    out_c = {name: a[src] for name, a in cols.items()}
    out_v = {name: (a[src] if a is not None else None) for name, a in valids.items()}
    sel_out = jnp.arange(k, dtype=jnp.int32) < total
    return out_c, out_v, sel_out
