"""Device expression evaluator: typed Expr IR -> whole-column JAX ops.

The vectorized ExecQual/ExecProject (reference: src/backend/executor/
execQual.c). Every node evaluates to ``(values, valid|None)`` where valid is
the SQL NULL mask; comparisons/boolean ops follow Kleene three-valued logic.
DECIMAL arithmetic is exact scaled-int64: +/- align scales, * adds scales,
/ computes in float64 and rounds half-up back to the result scale.
"""

from __future__ import annotations

import jax.numpy as jnp

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.ops import scalar as scalar_ops
from greengage_tpu.ops.batch import Batch

# shared NULL/DECIMAL algebra lives with the scalar function library
# (ops/scalar.py) so device functions and the evaluator agree on it
_and_valid = scalar_ops.and_valid
_pow10 = scalar_ops.pow10
_rescale = scalar_ops.rescale


def _rescale_host(v: int, from_scale: int, to_scale: int) -> int:
    """Host-side scalar version of _rescale (literal coercion)."""
    if from_scale == to_scale:
        return v
    if to_scale > from_scale:
        return v * 10 ** (to_scale - from_scale)
    p = 10 ** (from_scale - to_scale)
    half = p // 2
    return (v + half) // p if v >= 0 else -((-v + half) // p)


def _lit_array(lit: E.Literal, n: int):
    t = lit.type
    if lit.value is None:
        return jnp.zeros((n,), dtype=t.np_dtype), jnp.zeros((n,), dtype=bool)
    v = lit.value
    return jnp.full((n,), v, dtype=t.np_dtype), None


def _num_align(lt: T.SqlType, lv, rt: T.SqlType, rv, out: T.SqlType):
    """Align two numeric operands for + - * / under the result type."""
    if out.kind is T.Kind.FLOAT64:
        def to_f(t, v):
            if t.kind is T.Kind.DECIMAL:
                return v.astype(jnp.float64) / (10.0 ** t.scale)
            return v.astype(jnp.float64)
        return to_f(lt, lv), to_f(rt, rv)
    if out.kind is T.Kind.DECIMAL:
        def to_d(t, v):
            s = t.scale if t.kind is T.Kind.DECIMAL else 0
            return v.astype(jnp.int64), s
        return to_d(lt, lv), to_d(rt, rv)
    return lv.astype(out.np_dtype), rv.astype(out.np_dtype)


class Evaluator:
    """Evaluates Expr trees over a Batch. ``consts`` is the plan's constant
    pool: host numpy arrays (LUTs) placed on device by the compiler."""

    def __init__(self, batch: Batch, consts: dict[str, jnp.ndarray] | None = None):
        self.batch = batch
        self.consts = consts or {}
        self.n = batch.capacity

    # ---- public --------------------------------------------------------
    def value(self, e: E.Expr):
        """-> (values, valid|None)"""
        m = getattr(self, "_eval_" + type(e).__name__.lower(), None)
        if m is None:
            raise NotImplementedError(f"eval {type(e).__name__}")
        return m(e)

    def predicate(self, e: E.Expr):
        """WHERE semantics: NULL -> false. Returns bool array."""
        v, valid = self.value(e)
        v = v.astype(bool)
        if valid is not None:
            v = v & valid
        return v

    # ---- leaves --------------------------------------------------------
    def _eval_colref(self, e: E.ColRef):
        return self.batch.cols[e.name], self.batch.valids.get(e.name)

    def _eval_literal(self, e: E.Literal):
        return _lit_array(e, self.n)

    def _eval_param(self, e: E.Param):
        # hoisted literal (sql/paramize.py): read the slot's traced scalar
        # input — the compiler stashes the per-slot (1,)-arrays under
        # "@params@rt" at trace time, so ONE executable serves every value
        rt = self.consts.get("@params@rt")
        if rt is not None and e.slot in rt:
            return jnp.broadcast_to(rt[e.slot][0], (self.n,)), None
        # host path (no compiled program in play): bake the current value
        vec = self.consts.get("@params@")
        if vec is None:
            raise RuntimeError(
                f"parameter slot {e.slot} has no bound value (plan cache "
                "entry executed without its parameter vector)")
        return jnp.full((self.n,), vec.values[e.slot],
                        dtype=e.type.np_dtype), None

    # ---- arithmetic ----------------------------------------------------
    def _eval_binop(self, e: E.BinOp):
        lv, lval = self.value(e.left)
        rv, rval = self.value(e.right)
        lt = _expr_type(e.left)
        rt = _expr_type(e.right)
        out = e.type
        valid = _and_valid(lval, rval)

        # date arithmetic
        if lt.kind is T.Kind.DATE and rt.kind is T.Kind.DATE and e.op == "-":
            return (lv.astype(jnp.int32) - rv.astype(jnp.int32)), valid
        if lt.kind is T.Kind.DATE:
            r = rv.astype(jnp.int32)
            return (lv + r if e.op == "+" else lv - r), valid

        if out.kind is T.Kind.DECIMAL:
            (la, ls), (ra, rs) = _num_align(lt, lv, rt, rv, out)
            if e.op in ("+", "-"):
                s = max(ls, rs)
                la, ra = _rescale(la, ls, s), _rescale(ra, rs, s)
                res = la + ra if e.op == "+" else la - ra
                return _rescale(res, s, out.scale), valid
            if e.op == "*":
                res = la * ra  # scale ls+rs
                return _rescale(res, ls + rs, out.scale), valid
            if e.op == "/":
                q = (la.astype(jnp.float64) / (10.0 ** ls)) / jnp.where(
                    ra == 0, jnp.float64(1), ra.astype(jnp.float64) / (10.0 ** rs))
                # round half AWAY from zero (PG numeric; matches _rescale),
                # not jnp.round's half-to-even. Division by zero yields NULL
                # (valid=False below) rather than an error.
                scaled = q * (10.0 ** out.scale)
                res = jnp.trunc(scaled + jnp.copysign(0.5, scaled)).astype(jnp.int64)
                if valid is None:
                    valid = ra != 0
                else:
                    valid = valid & (ra != 0)
                return res, valid
            raise NotImplementedError(e.op)

        la, ra = _num_align(lt, lv, rt, rv, out)
        if e.op == "+":
            return la + ra, valid
        if e.op == "-":
            return la - ra, valid
        if e.op == "*":
            return la * ra, valid
        if e.op == "/":
            if out.kind is T.Kind.FLOAT64:
                res = la / jnp.where(ra == 0.0, 1.0, ra)
            else:  # integer division truncating toward zero (PG)
                safe = jnp.where(ra == 0, 1, ra)
                q = jnp.abs(la) // jnp.abs(safe)
                res = (jnp.where((la < 0) ^ (safe < 0), -q, q)).astype(out.np_dtype)
            zero = ra == 0
            valid = zero_invalid(valid, zero)
            return res, valid
        if e.op == "&":
            # bitwise AND over integer lanes (device raw-TEXT prefix
            # compares mask the straddling packed word)
            return la & ra, valid
        if e.op == "%":
            safe = jnp.where(ra == 0, 1, ra)
            res = la - (jnp.abs(la) // jnp.abs(safe)) * jnp.sign(la) * jnp.abs(safe)
            valid = zero_invalid(valid, ra == 0)
            return res.astype(out.np_dtype), valid
        raise NotImplementedError(e.op)

    # ---- comparison ----------------------------------------------------
    def _eval_cmp(self, e: E.Cmp):
        lv, lval = self.value(e.left)
        rv, rval = self.value(e.right)
        lt, rt = _expr_type(e.left), _expr_type(e.right)
        la, ra = _cmp_align(lt, lv, rt, rv)
        res = {
            "=": lambda: la == ra,
            "<>": lambda: la != ra,
            "<": lambda: la < ra,
            "<=": lambda: la <= ra,
            ">": lambda: la > ra,
            ">=": lambda: la >= ra,
        }[e.op]()
        return res, _and_valid(lval, rval)

    # ---- boolean (Kleene 3VL) -----------------------------------------
    def _eval_boolop(self, e: E.BoolOp):
        vals, valids = [], []
        for a in e.args:
            v, val = self.value(a)
            vals.append(v.astype(bool))
            valids.append(val)
        if e.op == "and":
            # false if any false; null if no false but some null
            acc_v, acc_val = vals[0], valids[0]
            for v, val in zip(vals[1:], valids[1:]):
                known_false = (~v & _or_true(val)) | (~acc_v & _or_true(acc_val))
                both_valid = _and_valid(acc_val, val)
                acc_v = acc_v & v
                acc_val = known_false | both_valid if both_valid is not None else None
                if both_valid is None:
                    acc_val = None
            return acc_v, acc_val
        else:
            acc_v, acc_val = vals[0], valids[0]
            for v, val in zip(vals[1:], valids[1:]):
                known_true = (v & _or_true(val)) | (acc_v & _or_true(acc_val))
                both_valid = _and_valid(acc_val, val)
                acc_v = acc_v | v
                acc_val = known_true | both_valid if both_valid is not None else None
                if both_valid is None:
                    acc_val = None
            return acc_v, acc_val

    def _eval_not(self, e: E.Not):
        v, val = self.value(e.arg)
        return ~v.astype(bool), val

    def _eval_isnull(self, e: E.IsNull):
        _, val = self.value(e.arg)
        if val is None:
            res = jnp.zeros((self.n,), dtype=bool)
        else:
            res = ~val
        if e.negate:
            res = ~res
        return res, None

    def _eval_case(self, e: E.Case):
        n = self.n
        out_t = e.type
        res = jnp.zeros((n,), dtype=out_t.np_dtype)
        res_valid = jnp.zeros((n,), dtype=bool)
        decided = jnp.zeros((n,), dtype=bool)
        for cond, val in e.whens:
            c = Evaluator.predicate(self, cond)
            take = c & ~decided
            v, vval = self.value(val)
            v = _cast_to(v, _expr_type(val), out_t)
            res = jnp.where(take, v, res)
            res_valid = jnp.where(take, jnp.ones((n,), bool) if vval is None else vval, res_valid)
            decided = decided | c
        if e.else_ is not None:
            v, vval = self.value(e.else_)
            v = _cast_to(v, _expr_type(e.else_), out_t)
            res = jnp.where(decided, res, v)
            res_valid = jnp.where(decided, res_valid,
                                  jnp.ones((n,), bool) if vval is None else vval)
        return res, res_valid

    def _eval_cast(self, e: E.Cast):
        v, val = self.value(e.arg)
        return _cast_to(v, _expr_type(e.arg), e.type), val

    def _eval_lut(self, e: E.Lut):
        codes, val = self.value(e.arg)
        table = jnp.asarray(self.consts[e.table_id])
        # code -1 (literal absent from dictionary) indexes the sentinel row
        idx = jnp.where(codes < 0, table.shape[0] - 1, codes)
        return table[idx], val

    def _eval_rawchain(self, e: "E.RawChain"):
        # device representation of a raw-TEXT function result is the
        # untouched row surrogate; the host applies the chain at decode
        return self.value(e.arg)

    def _eval_inlist(self, e: E.InList):
        v, val = self.value(e.arg)
        res = jnp.zeros((self.n,), dtype=bool)
        for c in e.values:
            res = res | (v == c)
        return res, val

    def _eval_rawlike(self, e: E.RawLike):
        """General device LIKE over the staged wide byte window — the
        whole-string case of the shared byte-window machinery
        (ops/scalar.py unpack_bytes/view_like): match the pattern's
        literal parts greedily left-to-right with rolling byte-window
        equality over the [rows, W] byte matrix (pure VPU
        elementwise/reduce work, no gather/scatter; greedy-leftmost is
        exact for %-separated literal parts)."""
        word_vals = []
        valid = None
        for wref in e.words:
            v, wv = self.value(wref)
            word_vals.append(v)
            valid = _and_valid(valid, wv)
        lens, lv = self.value(e.length)
        valid = _and_valid(valid, lv)
        B = scalar_ops.unpack_bytes(word_vals)
        start = jnp.zeros((self.n,), jnp.int32)
        ok = scalar_ops.view_like(B, start, lens.astype(jnp.int32), e.parts,
                                  e.anchored_start, e.anchored_end)
        return ok, valid

    def _eval_rawstrop(self, e: "E.RawStrOp"):
        """Scalar string chain over the staged wide byte window (the
        raw-TEXT half of ops/scalar.py): unpack the int64 lanes, narrow
        the per-row (start, length) view through the chain, then compare /
        measure — pure VPU elementwise/reduce work, no gather."""
        word_vals = []
        valid = None
        for wref in e.words:
            v, wv = self.value(wref)
            word_vals.append(v)
            valid = _and_valid(valid, wv)
        lens, lv = self.value(e.length)
        valid = _and_valid(valid, lv)
        B = scalar_ops.unpack_bytes(word_vals)
        start = jnp.zeros((self.n,), jnp.int32)
        B, start, ln = scalar_ops.apply_steps(B, start,
                                              lens.astype(jnp.int32), e.steps)
        if e.out == "length":
            return ln, valid
        if e.out == "cmp":
            return scalar_ops.view_eq(B, start, ln, e.literal), valid
        if e.out == "like":
            return scalar_ops.view_like(B, start, ln, e.parts,
                                        e.anchored_start, e.anchored_end), \
                valid
        raise NotImplementedError(f"RawStrOp out={e.out}")

    def _eval_func(self, e: E.Func):
        args = [self.value(a) for a in e.args]
        # device scalar library first (typed registry, per-function NULL
        # semantics — coalesce/greatest are NOT strict)
        dev = scalar_ops.lookup(e.name)
        if dev is not None:
            return dev.apply(e, args, self.n)
        valid = None
        for _, av in args:
            valid = _and_valid(valid, av)
        vals = [a for a, _ in args]
        from greengage_tpu import extensions as X

        spec = X.lookup(e.name, len(vals))
        if spec is None:
            raise NotImplementedError(f"function {e.name}")
        if spec.masked:
            v, bad = spec.fn(*vals)
            return v, _and_valid(valid, ~bad)
        return spec.fn(*vals), valid


# back-compat alias: the civil-calendar algebra moved to ops/scalar.py
_civil_from_days = scalar_ops.civil_from_days


def _or_true(valid):
    return valid if valid is not None else True


def zero_invalid(valid, zero):
    """Division by zero yields NULL (deviation: PG raises; MPP-friendly NULL
    keeps the kernel branch-free — the session layer can check and raise)."""
    nz = ~zero
    return nz if valid is None else valid & nz


def _expr_type(e: E.Expr) -> T.SqlType:
    return e.type


def _cmp_align(lt, lv, rt, rv):
    if lt.kind is T.Kind.TEXT and rt.kind is T.Kind.TEXT:
        return lv, rv  # code equality only; binder guarantees same dictionary
    if lt.kind is T.Kind.DECIMAL or rt.kind is T.Kind.DECIMAL:
        ls = lt.scale if lt.kind is T.Kind.DECIMAL else 0
        rs = rt.scale if rt.kind is T.Kind.DECIMAL else 0
        s = max(ls, rs)
        la = _rescale(lv.astype(jnp.int64), ls, s)
        ra = _rescale(rv.astype(jnp.int64), rs, s)
        return la, ra
    if lt.kind is T.Kind.FLOAT64 or rt.kind is T.Kind.FLOAT64:
        return lv.astype(jnp.float64), rv.astype(jnp.float64)
    return lv, rv


def _cast_to(v, from_t: T.SqlType, to_t: T.SqlType):
    if from_t == to_t:
        return v
    if to_t.kind is T.Kind.DECIMAL:
        if from_t.kind is T.Kind.DECIMAL:
            return _rescale(v.astype(jnp.int64), from_t.scale, to_t.scale)
        if from_t.kind is T.Kind.FLOAT64:
            return jnp.round(v * (10.0 ** to_t.scale)).astype(jnp.int64)
        return v.astype(jnp.int64) * _pow10(to_t.scale)
    if to_t.kind is T.Kind.FLOAT64:
        if from_t.kind is T.Kind.DECIMAL:
            return v.astype(jnp.float64) / (10.0 ** from_t.scale)
        return v.astype(jnp.float64)
    if to_t.is_integer:
        if from_t.kind is T.Kind.DECIMAL:
            return _rescale(v, from_t.scale, 0).astype(to_t.np_dtype)
        return v.astype(to_t.np_dtype)
    if to_t.kind is T.Kind.BOOL:
        return v.astype(bool)
    if to_t.kind is T.Kind.DATE and from_t.is_integer:
        return v.astype(jnp.int32)
    raise NotImplementedError(f"cast {from_t} -> {to_t}")
