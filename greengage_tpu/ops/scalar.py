"""Device-native scalar function library (the pg_proc builtin slice,
fused into the compiled scan/filter/agg programs).

Where the reference evaluates scalar functions per tuple through fmgr
(src/backend/utils/adt/date.c, timestamp.c, numeric.c, formatting.c),
every function here is a whole-column jax computation the expression
evaluator (ops/expr_eval.py) inlines into the surrounding traced closure
— XLA fuses it into the same kernel as the scan decode, filter mask, and
aggregate update, so scalar work never materializes a tuple between
operators (the data-path-fusion argument; docs/PERF.md "Scalar data-path
fusion").

Three families live here:

* **date functions** — ``extract_*`` / ``date_trunc`` / ``add_months``
  over days-since-epoch int32, built on Howard Hinnant's branchless
  civil-calendar algebra (``civil_from_days`` / ``days_from_civil``);
* **NULL-aware constructs** — ``coalesce`` / ``nullif`` / ``greatest`` /
  ``least``, which are NOT strict (PG treats them as expression syntax,
  not functions): each carries its own validity algebra;
* **DECIMAL-exact numerics** — ``round_dec`` / ``mod_dec`` on scaled
  int64 with bind-time scales in ``Func.params`` (the float64 variants
  stay in extensions.py; the binder routes DECIMAL arguments here so
  exactness survives).

The byte-window helpers at the bottom are the raw-TEXT half of the
story: string functions over raw (non-dictionary) TEXT evaluate on
device as elementwise/reduce work over the staged wide byte window
(``E.RawStrOp``) — a function chain narrows a per-row (start, length)
view over the unpacked [rows, W] byte matrix instead of materializing
strings. Dictionary-encoded TEXT needs none of this: the binder applies
utils/strfuncs.py once per distinct value and ships a LUT const.

TEXT strategy table (the binder's lowering decision; the host path
survives only for shapes neither device form can express):

    encoding   function shape                     lowering
    ---------  ---------------------------------  ------------------------
    dict       any strfuncs function              LUT const (device gather)
    raw        chain + [=|<>|LIKE] vs literal     RawStrOp byte ops
    raw        length(chain)                      RawStrOp length view
    raw        strpos/replace/lpad/... , non-     host chain (@hp pred /
               ASCII data, rows past the window   finalize decode), counted
                                                  in scalar_host_fallback_total
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp

from greengage_tpu import types as T

# ---------------------------------------------------------------------------
# shared validity / DECIMAL-rescale algebra (also used by ops/expr_eval.py)
# ---------------------------------------------------------------------------


def and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def pow10(k: int):
    return jnp.int64(10 ** k)


def rescale(vals, from_scale: int, to_scale: int):
    """Scaled-int64 DECIMAL rescale, rounding half AWAY from zero on
    narrowing (PG numeric rounding)."""
    if from_scale == to_scale:
        return vals
    if to_scale > from_scale:
        return vals * pow10(to_scale - from_scale)
    p = pow10(from_scale - to_scale)
    half = p // 2
    return jnp.where(vals >= 0, (vals + half) // p, -((-vals + half) // p))


# ---------------------------------------------------------------------------
# civil-calendar algebra (Howard Hinnant; valid for the SQL date range)
# ---------------------------------------------------------------------------


def civil_from_days(z):
    """days-since-1970 -> (year, month, day), branchless integer math."""
    z = z.astype(jnp.int64) + 719468
    era = z // 146097   # // already floors (Hinnant's C version must adjust)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    """(year, month, day) -> days-since-1970 — Hinnant's inverse, the other
    half the date_trunc / add_months round trips need."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9).astype(jnp.int64)
    doy = (153 * mp + 2) // 5 + d.astype(jnp.int64) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _is_leap(y):
    return (jnp.mod(y, 4) == 0) & ((jnp.mod(y, 100) != 0)
                                   | (jnp.mod(y, 400) == 0))


_DAYS_IN_MONTH = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DeviceFn:
    """One device scalar function: ``apply(func_node, args, n)`` where
    ``args`` is ``[(values, valid|None), ...]`` — each entry owns its NULL
    semantics (strict functions AND-combine validity via ``_strict``)."""

    name: str
    apply: Callable


_REG: dict[str, DeviceFn] = {}


def register(name: str, apply: Callable) -> None:
    _REG[name] = DeviceFn(name, apply)


def lookup(name: str) -> DeviceFn | None:
    return _REG.get(name)


def names() -> tuple[str, ...]:
    return tuple(sorted(_REG))


def _strict(fn):
    """Wrap a values-only implementation with the strict NULL rule
    (NULL in -> NULL out): validity is the AND of argument validities."""
    def apply(e, args, n):
        valid = None
        for _, av in args:
            valid = and_valid(valid, av)
        return fn(e, [v for v, _ in args]), valid
    return apply


# ---- date functions -------------------------------------------------------

_EXTRACT_FIELDS = ("year", "month", "day", "quarter", "dow", "isodow",
                   "doy", "week", "epoch", "decade", "century")


def extract_fields() -> tuple[str, ...]:
    """Fields the binder may lower to extract_<field> Func nodes."""
    return _EXTRACT_FIELDS


def _extract(field: str):
    def fn(e, vals):
        d = vals[0]
        if field == "epoch":
            return d.astype(jnp.int64) * jnp.int64(86400)
        if field == "dow":       # PG: Sunday=0; 1970-01-01 was a Thursday
            return jnp.mod(d.astype(jnp.int32) + 4, 7)
        if field == "isodow":    # Monday=1 .. Sunday=7
            return jnp.mod(d.astype(jnp.int32) + 3, 7) + 1
        y, m, dd = civil_from_days(d)
        if field == "year":
            return y
        if field == "month":
            return m
        if field == "day":
            return dd
        if field == "quarter":
            return (m + 2) // 3
        if field == "doy":
            return (d.astype(jnp.int32)
                    - days_from_civil(y, jnp.ones_like(m), jnp.ones_like(m))
                    + 1)
        if field == "week":      # ISO 8601 week of the week's Thursday
            thu = (d.astype(jnp.int32)
                   - jnp.mod(d.astype(jnp.int32) + 3, 7) + 3)
            ty, _, _ = civil_from_days(thu)
            jan1 = days_from_civil(ty, jnp.ones_like(ty, jnp.int32),
                                   jnp.ones_like(ty, jnp.int32))
            return ((thu - jan1) // 7 + 1).astype(jnp.int32)
        if field == "decade":
            return y // 10
        if field == "century":   # PG: 2000 -> 20, 2001 -> 21
            return (y + 99) // 100
        raise NotImplementedError(field)
    return fn


for _f in _EXTRACT_FIELDS:
    register(f"extract_{_f}", _strict(_extract(_f)))


_TRUNC_FIELDS = ("year", "quarter", "month", "week", "day")


def trunc_fields() -> tuple[str, ...]:
    return _TRUNC_FIELDS


def _date_trunc(e, vals):
    field = e.params[0]
    d = vals[0].astype(jnp.int32)
    if field == "day":
        return d
    if field == "week":          # ISO week starts Monday
        return d - jnp.mod(d + 3, 7)
    y, m, _dd = civil_from_days(d)
    one = jnp.ones_like(m)
    if field == "year":
        return days_from_civil(y, one, one)
    if field == "quarter":
        return days_from_civil(y, 3 * ((m - 1) // 3) + 1, one)
    if field == "month":
        return days_from_civil(y, m, one)
    raise NotImplementedError(field)


register("date_trunc", _strict(_date_trunc))


def _add_months(e, vals):
    """date + INTERVAL 'n' month|year over a column (the literal-base case
    folds at bind time): civil shift with end-of-month clamping, matching
    timestamp.c's timestamp_pl_interval day clamp."""
    months = int(e.params[0])
    y, m, dd = civil_from_days(vals[0])
    tot = y.astype(jnp.int64) * 12 + (m.astype(jnp.int64) - 1) + months
    y2 = (tot // 12).astype(jnp.int32)
    m2 = (tot - (tot // 12) * 12 + 1).astype(jnp.int32)
    dim = jnp.asarray(_DAYS_IN_MONTH, dtype=jnp.int32)[m2 - 1]
    dim = jnp.where((m2 == 2) & _is_leap(y2), dim + 1, dim)
    return days_from_civil(y2, m2, jnp.minimum(dd, dim))


register("add_months", _strict(_add_months))


# ---- NULL-aware constructs (non-strict) -----------------------------------


def _bool_valid(v, n):
    return jnp.ones((n,), bool) if v is None else v


def _coalesce(e, args, n):
    vals = [a for a, _ in args]
    valids = [_bool_valid(v, n) for _, v in args]
    res, resv = vals[-1], valids[-1]
    for v, ok in zip(reversed(vals[:-1]), reversed(valids[:-1])):
        res = jnp.where(ok, v, res)
        resv = ok | resv
    return res, resv


register("coalesce", _coalesce)


def _nullif(e, args, n):
    (a, av), (b, bv) = args
    known_eq = (a == b) & _bool_valid(bv, n)
    valid = _bool_valid(av, n) & ~known_eq
    return a, valid


register("nullif", _nullif)


def _extreme(pick):
    """GREATEST/LEAST: NULL arguments are IGNORED (the documented PG
    deviation from the SQL standard); NULL only when every argument is."""
    def apply(e, args, n):
        res, resv = args[0][0], _bool_valid(args[0][1], n)
        for v, ok in args[1:]:
            ok = _bool_valid(ok, n)
            both = resv & ok
            res = jnp.where(both, pick(res, v), jnp.where(ok, v, res))
            resv = resv | ok
        return res, resv
    return apply


register("greatest", _extreme(jnp.maximum))
register("least", _extreme(jnp.minimum))


# ---- DECIMAL-exact numerics ----------------------------------------------


def _round_dec(e, vals):
    """round(DECIMAL(s), digits) -> DECIMAL(max(digits, 0)), exact scaled
    int64 (the extensions.py float64 round loses exactness past 2^53;
    numeric.c keeps the scale — so do we). Negative digits round to tens/
    hundreds and re-widen to scale 0."""
    from_scale, digits = e.params
    r = rescale(vals[0].astype(jnp.int64), from_scale, digits)
    if digits < 0:
        r = r * pow10(-digits)
    return r


register("round_dec", _strict(_round_dec))


def _trunc_dec(e, vals):
    from_scale, digits = e.params
    v = vals[0].astype(jnp.int64)
    if digits >= from_scale:
        return rescale(v, from_scale, digits)
    p = pow10(from_scale - digits)
    q = jnp.abs(v) // p
    r = jnp.where(v < 0, -q, q)
    if digits < 0:
        r = r * pow10(-digits)
    return r


register("trunc_dec", _strict(_trunc_dec))


def _mod_dec(e, args, n):
    """mod over DECIMALs: align scales, truncation semantics with the
    dividend's sign (numeric.c); mod(x, 0) yields NULL via the validity
    mask (the zero_invalid deviation — PG raises)."""
    ls, rs, out_scale = e.params
    (a, av), (b, bv) = args
    s = max(ls, rs)
    a2 = rescale(a.astype(jnp.int64), ls, s)
    b2 = rescale(b.astype(jnp.int64), rs, s)
    zero = b2 == 0
    safe = jnp.where(zero, jnp.int64(1), b2)
    m = a2 - (jnp.abs(a2) // jnp.abs(safe)) * jnp.sign(a2) * jnp.abs(safe)
    valid = and_valid(and_valid(av, bv), ~zero)
    return rescale(m, s, out_scale), valid


register("mod_dec", _mod_dec)


# ---------------------------------------------------------------------------
# raw-TEXT byte-window ops (E.RawStrOp evaluation; runs under trace)
# ---------------------------------------------------------------------------

# chain steps the byte-window path can express; True = the step's
# semantics count CHARACTERS, so the byte view is only exact over pure
# ASCII data (the binder checks store.raw_is_ascii before lowering)
RAW_STEPS = {
    "upper": True, "lower": True,
    "trim": False, "ltrim": False, "rtrim": False,
    "substr": True, "substring": True, "left": True, "right": True,
    "length": True, "char_length": True, "character_length": True,
}


def raw_steps_ok(steps) -> tuple[bool, bool]:
    """-> (device-expressible, needs-ascii) for a strfuncs chain."""
    needs_ascii = False
    for step in steps:
        name = step[0]
        if name not in RAW_STEPS:
            return False, False
        if name in ("ltrim", "rtrim") and len(step) > 1 and step[1] != " ":
            return False, False   # non-space trim sets stay on the host
        if name in ("substr", "substring"):
            if int(step[1]) < 1:
                return False, False   # start < 1 shortens the window (host)
            if len(step) > 2 and int(step[2]) < 0:
                return False, False   # negative length: host path RAISES
        needs_ascii = needs_ascii or RAW_STEPS[name]
    return True, needs_ascii


def unpack_bytes(word_vals):
    """[(n,) int64 word lanes] -> [n, 8*len] uint8 byte matrix, big-endian
    within each word (the RawLike unpack, shared)."""
    cols = []
    for wv in word_vals:
        w64 = wv.astype(jnp.uint64)
        for j in range(8):
            cols.append(((w64 >> jnp.uint64(56 - 8 * j))
                         & jnp.uint64(0xFF)).astype(jnp.uint8))
    return jnp.stack(cols, axis=1)


def apply_steps(B, start, ln, steps):
    """Apply a function chain to the (start, ln) view over byte matrix B.
    upper/lower transform B elementwise; the rest only narrow the view —
    no bytes move, so everything stays VPU elementwise/reduce work."""
    W = B.shape[1]
    idx = jnp.arange(W, dtype=jnp.int32)[None, :]
    for step in steps:
        name = step[0]
        if name == "upper":
            B = jnp.where((B >= 97) & (B <= 122), B - 32, B)
        elif name == "lower":
            B = jnp.where((B >= 65) & (B <= 90), B + 32, B)
        elif name in ("trim", "ltrim", "rtrim"):
            in_win = (idx >= start[:, None]) & (idx < (start + ln)[:, None])
            nonsp = in_win & (B != 32)
            if name in ("trim", "ltrim"):
                first = jnp.min(jnp.where(nonsp, idx, W), axis=1).astype(
                    jnp.int32)
                lead = jnp.minimum(first - start, ln)
                start = start + lead
                ln = ln - lead
            if name in ("trim", "rtrim"):
                last = jnp.max(jnp.where(nonsp, idx, -1), axis=1).astype(
                    jnp.int32)
                ln = jnp.where(last < start, 0, last - start + 1)
        elif name in ("substr", "substring"):
            a = int(step[1]) - 1          # binder guarantees start >= 1
            take = jnp.minimum(jnp.int32(a), ln)
            start = start + take
            ln = ln - take
            if len(step) > 2:
                ln = jnp.minimum(ln, jnp.int32(int(step[2])))
        elif name == "left":
            k = int(step[1])
            ln = (jnp.minimum(ln, jnp.int32(k)) if k >= 0
                  else jnp.maximum(ln + jnp.int32(k), 0))
        elif name == "right":
            k = int(step[1])
            if k >= 0:
                shift = jnp.maximum(ln - jnp.int32(k), 0)
                start = start + shift
                ln = ln - shift
            else:
                take = jnp.minimum(jnp.int32(-k), ln)
                start = start + take
                ln = ln - take
        elif name in ("length", "char_length", "character_length"):
            pass   # terminal; the caller reads ln
        else:
            raise NotImplementedError(f"raw byte-op step {name}")
    return B, start, ln


def view_eq(B, start, ln, lit: bytes):
    """view == literal, gather-free: match the literal at every static
    offset (rolled byte-window equality), then select the per-row offset
    with a positional mask instead of a dynamic index."""
    n, W = B.shape
    L = len(lit)
    len_ok = ln == jnp.int32(L)
    if L == 0:
        return len_ok
    nwin = W - L + 1
    if nwin <= 0:
        return jnp.zeros((n,), bool)
    m = jnp.ones((n, nwin), bool)
    for k, byte in enumerate(lit):
        m = m & (B[:, k:k + nwin] == jnp.uint8(byte))
    pos = jnp.arange(nwin, dtype=jnp.int32)[None, :]
    at_start = (m & (pos == start[:, None])).any(axis=1)
    return len_ok & at_start


def view_like(B, start, ln, parts, anchored_start: bool, anchored_end: bool):
    """RawLike's greedy leftmost %-part matching, constrained to the
    (start, ln) view (exact for %-separated literal parts)."""
    n, W = B.shape
    if not parts:
        # '' matches only the empty string; '%' (any %-only pattern)
        # matches everything
        return (ln == 0 if anchored_start and anchored_end
                else jnp.ones((n,), bool))
    end = start + ln
    ok = jnp.ones((n,), bool)
    prev_end = start
    for i, part in enumerate(parts):
        L = len(part)
        nwin = W - L + 1
        if nwin <= 0:
            return jnp.zeros((n,), bool)
        m = jnp.ones((n, nwin), bool)
        for k, byte in enumerate(part):
            m = m & (B[:, k:k + nwin] == jnp.uint8(byte))
        pos = jnp.arange(nwin, dtype=jnp.int32)[None, :]
        m = m & (pos >= prev_end[:, None])
        m = m & (pos + L <= end[:, None])
        if i == 0 and anchored_start:
            m = m & (pos == start[:, None])
        if i == len(parts) - 1 and anchored_end:
            m = m & (pos + L == end[:, None])
        ok = ok & m.any(axis=1)
        prev_end = jnp.argmax(m, axis=1).astype(jnp.int32) + L
    return ok


# ---------------------------------------------------------------------------
# binder-facing typing tables
# ---------------------------------------------------------------------------

# date_part / extract function-call aliases resolve through the same
# field registry the EXTRACT(.. FROM ..) spelling uses
FIELD_RESULT = {f: (T.INT64 if f == "epoch" else T.INT32)
                for f in _EXTRACT_FIELDS}
