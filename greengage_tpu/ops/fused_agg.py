"""Single-pass fused dense aggregation — the Q1 roofline kernel.

The XLA dense path (ops/agg.py dense_aggregate) emits one [n, D] masked
reduction per aggregate input; XLA compiles each into its own pass over
the batch, so TPC-H Q1's 8 aggregates re-read gid and value columns ~12x
(measured 86ms for 60M rows at SF10 ≈ 31 GB/s effective vs ~819 GB/s v5e
HBM peak). This pallas kernel makes ONE pass: each row block is loaded
once, every accumulator updates from VMEM, and only [D] partials per
accumulator ever leave the core.

Semantics come for free by record-replay around agg._run_aggs (the single
source of SQL aggregate truth): a recording pass captures every segmented
reduction _run_aggs asks for (already masked/identity-filled), the kernel
computes ALL of them in one sweep, and a replay pass hands the results
back in the same order. sum/count/avg/min/max all ride the same kernel.

Layout: rows reshaped to [n/128, 128] (lane-major); the grid walks row
blocks sequentially (TPU grid semantics), accumulating per-(accum, group,
lane) partials in VMEM scratch and collapsing lanes on the final block.
int64 accumulators keep scaled-decimal SQL sums exact (no float path is).

Reference parity: the vectorized replacement for the per-tuple
advance_aggregates loop of the hybrid hash agg (execHHashagg.c) in the
small-domain regime.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 128
SUBLANES = 64          # rows per grid step = SUBLANES * LANES


def supported(aggs) -> bool:
    return all(s.func in ("sum", "count", "count_star", "avg", "min", "max")
               for s in aggs)


def _segment_reduce_fused(gid, D: int, jobs, interpret: bool):
    """jobs: list of (values[n] pre-masked/filled, op, ident) with op in
    {'sum','min','max'}. -> list of per-group [D] results, one HBM pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = gid.shape[0]
    block = SUBLANES * LANES
    nblocks = max((n + block - 1) // block, 1)
    npad = nblocks * block

    # split jobs by accumulator dtype (pallas scratch is single-dtype)
    lanes: dict[str, list[int]] = {"i": [], "f": []}
    for j, (v, _, _) in enumerate(jobs):
        lanes["f" if v.dtype.kind == "f" else "i"].append(j)
    ki, kf = len(lanes["i"]), len(lanes["f"])

    def pad2(x, fill):
        x = jnp.pad(x, (0, npad - n), constant_values=fill)
        return x.reshape(nblocks * SUBLANES, LANES)

    gid2 = pad2(gid.astype(jnp.int32), 0)
    arrs = []
    idents = []
    ops = []
    order = lanes["i"] + lanes["f"]
    for j in order:
        v, op, ident = jobs[j]
        if v.dtype.kind == "f":
            v = v.astype(jnp.float64)
        else:
            v = v.astype(jnp.int64)
        arrs.append(pad2(v, ident))   # padding rows carry the identity
        idents.append(ident)
        ops.append(op)

    def kernel(gid_ref, *rest):
        vrefs = rest[:len(arrs)]
        outs = rest[len(arrs):len(arrs) + (1 if ki else 0) + (1 if kf else 0)]
        scratches = rest[len(arrs) + len(outs):]
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            si = 0
            for kind, count in (("i", ki), ("f", kf)):
                if count:
                    sc = scratches[si]
                    init = jnp.stack([
                        jnp.full((D, LANES), idents[ (0 if kind == "i" else ki) + a],
                                 sc.dtype)
                        for a in range(count)])
                    sc[...] = init
                    si += 1

        g = gid_ref[...]
        si = 0
        base = 0
        for kind, count in (("i", ki), ("f", kf)):
            if not count:
                continue
            sc = scratches[si]
            for a in range(count):
                v = vrefs[base + a][...]
                op = ops[base + a]
                ident = idents[base + a]
                for gi in range(D):
                    m = g == gi
                    masked = jnp.where(m, v, jnp.asarray(ident, v.dtype))
                    if op == "sum":
                        sc[a, gi, :] += jnp.sum(masked, axis=0)
                    elif op == "min":
                        sc[a, gi, :] = jnp.minimum(
                            sc[a, gi, :], jnp.min(masked, axis=0))
                    else:
                        sc[a, gi, :] = jnp.maximum(
                            sc[a, gi, :], jnp.max(masked, axis=0))
            si += 1
            base += count

        @pl.when(step == nblocks - 1)
        def _finish():
            si = 0
            base = 0
            oi = 0
            for kind, count in (("i", ki), ("f", kf)):
                if not count:
                    continue
                sc = scratches[si]
                red = []
                for a in range(count):
                    op = ops[base + a]
                    if op == "sum":
                        red.append(jnp.sum(sc[a], axis=1))
                    elif op == "min":
                        red.append(jnp.min(sc[a], axis=1))
                    else:
                        red.append(jnp.max(sc[a], axis=1))
                outs[oi][...] = jnp.stack(red)
                si += 1
                base += count
                oi += 1

    row_spec = pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0))
    out_shapes = []
    out_specs = []
    scratch_shapes = []
    if ki:
        out_shapes.append(jax.ShapeDtypeStruct((ki, D), jnp.int64))
        out_specs.append(pl.BlockSpec((ki, D), lambda i: (0, 0)))
        scratch_shapes.append(pltpu.VMEM((ki, D, LANES), jnp.int64))
    if kf:
        out_shapes.append(jax.ShapeDtypeStruct((kf, D), jnp.float64))
        out_specs.append(pl.BlockSpec((kf, D), lambda i: (0, 0)))
        scratch_shapes.append(pltpu.VMEM((kf, D, LANES), jnp.float64))

    res = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[row_spec] * (1 + len(arrs)),
        out_specs=out_specs if len(out_specs) > 1 else out_specs[0],
        out_shape=out_shapes if len(out_shapes) > 1 else out_shapes[0],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(gid2, *arrs)
    if not isinstance(res, (list, tuple)):
        res = [res]

    results: list = [None] * len(jobs)
    oi = 0
    if ki:
        for a, j in enumerate(lanes["i"]):
            results[j] = res[oi][a]
        oi += 1
    if kf:
        for a, j in enumerate(lanes["f"]):
            results[j] = res[oi][a]
    return results


def fused_dense_aggregate(gid, D: int, aggs, sel, interpret: bool = False):
    """Drop-in for agg.dense_aggregate: -> (vals, valids) with identical
    semantics, computed in one pass. Only call when supported(aggs)."""
    from greengage_tpu.ops import agg as agg_ops

    # pass 1: record every segmented reduction _run_aggs asks for; the
    # dummy [D] returns flow into dead arithmetic XLA removes (only the
    # replay pass's outputs are kept)
    jobs: list = []

    def rec_sum(masked):
        jobs.append((masked, "sum",
                     0.0 if masked.dtype.kind == "f" else 0))
        return jnp.zeros((D,), masked.dtype)

    def rec_minmax(filled, func, ident):
        jobs.append((filled, func, ident.item() if hasattr(ident, "item")
                     else ident))
        return jnp.zeros((D,), filled.dtype)

    agg_ops._run_aggs(aggs, sel, rec_sum, rec_minmax)

    results = _segment_reduce_fused(gid, D, jobs, interpret)

    # pass 2: replay with the fused results, in the same call order
    it = iter(results)

    def replay_sum(masked):
        r = next(it)
        return r.astype(jnp.float64 if masked.dtype.kind == "f" else jnp.int64)

    def replay_minmax(filled, func, ident):
        r = next(it)
        return r.astype(filled.dtype) if filled.dtype.kind != "f" else r

    return agg_ops._run_aggs(aggs, sel, replay_sum, replay_minmax)
