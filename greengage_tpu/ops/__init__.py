from greengage_tpu.ops.batch import Batch  # noqa: F401
