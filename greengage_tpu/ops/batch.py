"""Batch: the columnar unit flowing between operators (TupleTableSlot analog).

Unlike the reference's per-tuple slots (src/include/executor/tuptable.h) a
Batch is a fixed-capacity set of whole columns plus

- ``valids``: per-column NULL masks (absent = all valid)
- ``sel``: the selection mask — rows logically alive. Filters narrow ``sel``
  instead of compacting, keeping shapes static for XLA (the vectorized
  ExecQual). Operators that must materialize cardinality (agg, join, motion)
  consume ``sel`` directly.

Registered as a JAX pytree so whole plans trace through jit/shard_map.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp


@dataclass
class Batch:
    cols: dict[str, jax.Array]
    valids: dict[str, jax.Array] = field(default_factory=dict)
    sel: jax.Array | None = None   # bool[capacity]; None = all rows live

    @property
    def capacity(self) -> int:
        for a in self.cols.values():
            return int(a.shape[0])
        # columnless batch (ConstRel): the selection mask carries the shape
        if self.sel is not None:
            return int(self.sel.shape[0])
        return 0

    def selection(self) -> jax.Array:
        if self.sel is None:
            return jnp.ones((self.capacity,), dtype=bool)
        return self.sel

    def valid(self, name: str) -> jax.Array:
        v = self.valids.get(name)
        if v is None:
            return jnp.ones((self.capacity,), dtype=bool)
        return v

    def column(self, name: str) -> jax.Array:
        return self.cols[name]

    def with_sel(self, sel: jax.Array) -> "Batch":
        return Batch(dict(self.cols), dict(self.valids), sel)

    def project(self, names: list[str]) -> "Batch":
        return Batch(
            {n: self.cols[n] for n in names},
            {n: self.valids[n] for n in names if n in self.valids},
            self.sel,
        )

    def num_live(self) -> jax.Array:
        return jnp.sum(self.selection())


def _flatten(b: Batch):
    ck = sorted(b.cols)
    vk = sorted(b.valids)
    children = [b.cols[k] for k in ck] + [b.valids[k] for k in vk] + [b.sel]
    return children, (tuple(ck), tuple(vk))


def _unflatten(aux, children):
    ck, vk = aux
    cols = dict(zip(ck, children[: len(ck)]))
    valids = dict(zip(vk, children[len(ck) : len(ck) + len(vk)]))
    sel = children[len(ck) + len(vk)]
    return Batch(cols, valids, sel)


jax.tree_util.register_pytree_node(Batch, _flatten, _unflatten)
