"""Window functions — nodeWindowAgg.c as sort + segmented scans.

Rows are sorted by (partition keys, order keys); partition and peer-group
boundaries become monotone index arrays via cummax, and every window value
is then pure vectorized arithmetic:

  row_number  = position - partition_start + 1
  rank        = peer_start - partition_start + 1
  dense_rank  = segmented count of peer boundaries
  sum/count/avg (ORDER BY present)  = running-to-last-peer via cumsum diffs
                (PG's default frame RANGE UNBOUNDED PRECEDING..CURRENT ROW)
  sum/count/avg (no ORDER BY)       = whole-partition via cumsum diffs
  min/max     = segmented scan (associative op with partition reset)

The planner guarantees each partition is wholly on one segment
(redistribute by partition keys; no PARTITION BY -> single-segment motion),
so everything here is segment-local.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax


@dataclass
class WinFunc:
    name: str              # output column id
    func: str              # row_number | rank | dense_rank | sum | count |
    #                        avg | min | max | lag | lead | first_value |
    #                        last_value | ntile
    values: jnp.ndarray | None
    valid: jnp.ndarray | None
    decimal_scale: int = 0
    ordered: bool = False  # window had ORDER BY -> running (peer) frame
    param: int | None = None   # lag/lead offset, ntile buckets


def ntile_bucket(rn, cnt, param):
    """PG ntile bucket (1-based) from a 0-based position within the
    partition and the partition row count — the ONE formula shared by
    the segment-local kernel below and the global ordered/range window
    kernels (exec/compile.py), so the bucket arithmetic can't drift."""
    nb = jnp.int64(param)
    q, r = cnt // nb, cnt % nb
    big = r * (q + 1)
    bucket = jnp.where(rn < big,
                       rn // jnp.maximum(q + 1, 1),
                       r + (rn - big) // jnp.maximum(q, 1))
    # more buckets than rows: bucket = rn
    return jnp.where(q == 0, jnp.minimum(rn, nb - 1), bucket) + 1


def _starts(boundary, idx):
    """Monotone start-index array: for each row, the index of the first row
    of its group (boundary True marks group firsts)."""
    return lax.cummax(jnp.where(boundary, idx, 0))


def _ends(starts, n):
    """Last index of each group, via a REVERSE cummax instead of a
    searchsorted over all rows (row-count-sized searchsorted costs ~1s/6M
    on TPU; two cummaxes are ~35ms): row i's group end is the smallest
    j >= i that is the last row before a boundary."""
    idx = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((min(n, 1),), bool), starts[1:] != starts[:-1]]) \
        if n > 1 else jnp.ones((n,), bool)
    # i is a group END iff the next row starts a group (or i is last)
    is_end = jnp.concatenate([is_start[1:], jnp.ones((min(n, 1),), bool)])
    rev = lax.cummax(jnp.where(is_end[::-1], idx, 0))
    return (jnp.int32(n - 1) - rev)[::-1]


def _seg_scan_minmax(v, boundary, op):
    """Segmented running min/max: associative scan with reset at boundaries."""
    def combine(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, op(av, bv))

    _, out = lax.associative_scan(combine, (boundary, v))
    return out


def compute(partition_eq_prev, peer_eq_prev, sel_sorted, funcs: list[WinFunc],
            frame: tuple | None = None):
    """Window values over the SORTED batch.

    partition_eq_prev[i]: row i has the same partition keys as row i-1
    peer_eq_prev[i]: same partition AND same order keys as row i-1
    (both False at i=0 and for dead rows — dead rows sit at the end).
    frame: None = default RANGE peers; (a, b) = ROWS a PRECEDING..b
    FOLLOWING offsets (None = unbounded) applied to sum/count/avg and
    first/last_value via cumsum span differences clamped to the partition.
    -> {name: values}, {name: valid}
    """
    n = sel_sorted.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    p_bound = ~partition_eq_prev
    peer_bound = ~peer_eq_prev
    p_start = _starts(p_bound, idx)
    peer_start = _starts(peer_bound, idx)
    peer_end = _ends(peer_start, n)
    p_end = _ends(p_start, n)

    def frame_span(has_order):
        """Per-row inclusive [lo, hi] row range the aggregate covers."""
        if frame is None:
            return p_start, (peer_end if has_order else p_end)
        a, b = frame
        lo = p_start if a is None else jnp.maximum(p_start, idx - a)
        hi = p_end if b is None else jnp.minimum(p_end, idx + b)
        return lo, hi

    out_vals, out_valid = {}, {}
    for f in funcs:
        if f.func == "row_number":
            out_vals[f.name] = (idx - p_start + 1).astype(jnp.int64)
            out_valid[f.name] = None
            continue
        if f.func == "rank":
            out_vals[f.name] = (peer_start - p_start + 1).astype(jnp.int64)
            out_valid[f.name] = None
            continue
        if f.func == "dense_rank":
            cb = jnp.cumsum(peer_bound.astype(jnp.int64))
            out_vals[f.name] = cb - cb[jnp.clip(p_start, 0, n - 1)] + 1
            out_valid[f.name] = None
            continue

        if f.func == "ntile":
            cnt_p = (p_end - p_start + 1).astype(jnp.int64)
            rn = (idx - p_start).astype(jnp.int64)
            out_vals[f.name] = ntile_bucket(rn, cnt_p, f.param)
            out_valid[f.name] = None
            continue
        if f.func in ("lag", "lead"):
            k, default = f.param if isinstance(f.param, tuple) else (f.param, None)
            src = idx - k if f.func == "lag" else idx + k
            ok = (src >= p_start) if f.func == "lag" else (src <= p_end)
            srcc = jnp.clip(src, 0, n - 1)
            vals = f.values[srcc]
            v = jnp.ones((n,), bool) if f.valid is None else f.valid
            if default is not None:
                # SQL-standard third argument: out-of-partition offsets
                # yield the default instead of NULL
                vals = jnp.where(ok, vals, jnp.asarray(default, vals.dtype))
                out_valid[f.name] = (ok & v[srcc] | ~ok) & sel_sorted
            else:
                out_valid[f.name] = ok & v[srcc] & sel_sorted
            out_vals[f.name] = vals
            continue
        if f.func in ("first_value", "last_value"):
            lo, hi = frame_span(f.ordered)
            src = lo if f.func == "first_value" else hi
            srcc = jnp.clip(src, 0, n - 1)
            out_vals[f.name] = f.values[srcc]
            v = jnp.ones((n,), bool) if f.valid is None else f.valid
            out_valid[f.name] = v[srcc] & (hi >= lo) & sel_sorted
            continue

        has_order = f.ordered
        lv = sel_sorted if f.valid is None else (sel_sorted & f.valid)
        lo_i, end = frame_span(has_order)
        if f.func in ("sum", "count", "avg"):
            if f.func == "count" and f.values is None:
                vals = jnp.ones((n,), dtype=jnp.int64)
            else:
                vals = f.values
            acc = jnp.float64 if vals.dtype.kind == "f" else jnp.int64
            cs = jnp.cumsum(jnp.where(lv, vals.astype(acc), acc(0)))
            cnt = jnp.cumsum(jnp.where(lv, jnp.int64(1), jnp.int64(0)))
            base = jnp.where(lo_i > 0, cs[jnp.clip(lo_i - 1, 0, n - 1)], acc(0))
            cbase = jnp.where(lo_i > 0, cnt[jnp.clip(lo_i - 1, 0, n - 1)], 0)
            s = cs[jnp.clip(end, 0, n - 1)] - base
            c = cnt[jnp.clip(end, 0, n - 1)] - cbase
            s = jnp.where(end >= lo_i, s, acc(0))
            c = jnp.where(end >= lo_i, c, 0)
            if f.func == "count":
                out_vals[f.name] = c
                out_valid[f.name] = None
            elif f.func == "sum":
                out_vals[f.name] = s
                out_valid[f.name] = c > 0
            else:
                avg = s.astype(jnp.float64) / jnp.where(c == 0, 1, c).astype(jnp.float64)
                if f.decimal_scale:
                    avg = avg / (10.0 ** f.decimal_scale)
                out_vals[f.name] = avg
                out_valid[f.name] = c > 0
            continue
        if f.func in ("min", "max"):
            vals = f.values
            if vals.dtype.kind == "f":
                ident = jnp.array(jnp.inf if f.func == "min" else -jnp.inf, vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                ident = jnp.array(info.max if f.func == "min" else info.min, vals.dtype)
            filled = jnp.where(lv, vals, ident)
            op = jnp.minimum if f.func == "min" else jnp.maximum
            run = _seg_scan_minmax(filled, p_bound, op)
            cnt = jnp.cumsum(jnp.where(lv, jnp.int64(1), jnp.int64(0)))
            cbase = jnp.where(p_start > 0, cnt[jnp.clip(p_start - 1, 0, n - 1)], 0)
            # frame semantics (binder allows running/whole ROWS frames only):
            #   default       -> peers (ordered) / whole partition
            #   ROWS ..CURRENT ROW   -> running value AT this row
            #   ROWS ..UNBOUNDED FOLLOWING -> whole partition
            if frame == (None, 0):
                end_mm = idx
            elif frame == (None, None):
                end_mm = p_end
            elif has_order:
                end_mm = peer_end
            else:
                end_mm = p_end
            out_vals[f.name] = run[end_mm]
            out_valid[f.name] = (cnt[end_mm] - cbase) > 0
            continue
        raise NotImplementedError(f.func)
    return out_vals, out_valid
