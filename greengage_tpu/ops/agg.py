"""Vectorized grouped aggregation — the execHHashagg.c analog, TPU-first.

Two production regimes (no scatter-heavy hash table — TPU scatters
serialize on colliding indices):

  * DENSE: every group key has a known finite domain (TEXT dictionary /
    BOOL); gid is a mixed-radix index and every aggregate is one fused
    masked reduction (the Q1-class fast path).
  * SORT: unbounded cardinality; rows lax.sort by key and each run reduces
    with segmented scans. Where the reference spills its hash table to
    workfiles (execHHashagg.c), this path cannot overflow at all — only the
    output batch capacity can, which retries via the executor's exact-count
    tier mechanism.

Scalar (ungrouped) aggregates use ``aggregate`` with a single slot.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

BIG = jnp.iinfo(jnp.int32).max   # scatter-min identity (used by ops/join)


@dataclass
class KeySpec:
    values: jnp.ndarray
    valid: jnp.ndarray | None
    type: object            # T.SqlType
    hash_lut: jnp.ndarray | None = None  # TEXT: per-dict-entry hashes


@dataclass
class AggSpec:
    name: str
    func: str               # count_star | count | sum | min | max | avg
    values: jnp.ndarray | None
    valid: jnp.ndarray | None
    # DECIMAL inputs are scaled int64; avg must descale its float64 result
    # by 10^scale (sum/min/max stay in scaled-int domain, declared DECIMAL).
    decimal_scale: int = 0


# ---------------------------------------------------------------------------
# Dense path: small known key domains (TEXT dictionaries / BOOL)
#
# gid = mixed-radix index over (code+1) digits (0 = NULL), and every
# aggregate is a fused masked reduction over a [rows, D] broadcast — one
# HBM pass, VPU-only, no scatter/gather. This is the Q1-class fast path;
# high-cardinality keys use the sort path below.
# ---------------------------------------------------------------------------


def dense_gid(keys: list[KeySpec], domains: list[int], sel):
    """-> (gid int32[n] in [0, D), D). domains[i] = |dict_i| + 1 (NULL)."""
    gid = None
    for k, dom in zip(keys, domains):
        idx = k.values.astype(jnp.int32) + 1
        if k.valid is not None:
            idx = jnp.where(k.valid, idx, 0)
        gid = idx if gid is None else gid * jnp.int32(dom) + idx
    D = 1
    for dom in domains:
        D *= dom
    return jnp.where(sel, gid, jnp.int32(0)), D


def dense_decode_keys(keys: list[KeySpec], domains: list[int], D: int):
    """Reconstruct per-group key code arrays [D] (and NULL masks) from gid
    arithmetic — no gathers."""
    iota = jnp.arange(D, dtype=jnp.int32)
    out = []
    strides = []
    s = 1
    for dom in reversed(domains):
        strides.append(s)
        s *= dom
    strides = list(reversed(strides))
    for k, dom, st in zip(keys, domains, strides):
        idx = (iota // jnp.int32(st)) % jnp.int32(dom)
        code = (idx - 1).astype(k.values.dtype)
        valid = idx > 0
        out.append((code, valid))
    return out


def _masked_reduce(op, vals, gid, D, mask, ident):
    """One fused pass: reduce vals into D groups via broadcast-compare.
    XLA fuses the [n, D] compare+select into the reduction tiles."""
    sel2 = mask[:, None] & (gid[:, None] == jnp.arange(D, dtype=jnp.int32)[None, :])
    filled = jnp.where(sel2, vals[:, None], ident)
    return op(filled, axis=0)


def _run_aggs(aggs: list[AggSpec], sel, seg_sum, seg_minmax):
    """The per-function aggregate semantics, shared by every grouping
    regime. The reduce primitives are injected:

      seg_sum(masked_vals) -> per-group sums (inputs pre-masked to 0)
      seg_minmax(filled_vals, func, ident) -> per-group min/max
        (inputs pre-filled with the identity at dead/NULL rows)

    Semantics kept in ONE place: count(*)/count ignore NULLs per column;
    sum of no rows is NULL; avg = float64 sum/count descaled by the decimal
    scale; min/max of no rows is NULL.
    """
    out_vals: dict[str, jnp.ndarray] = {}
    out_valid: dict[str, jnp.ndarray] = {}
    counts_cache: dict = {}

    def live_count(spec):
        key = None if spec is None or spec.valid is None else id(spec.valid)
        if key not in counts_cache:
            lv = sel if spec is None or spec.valid is None else (sel & spec.valid)
            counts_cache[key] = seg_sum(lv.astype(jnp.int64))
        return counts_cache[key]

    group_count = live_count(None)
    for spec in aggs:
        if spec.func == "count_star":
            out_vals[spec.name] = group_count
            out_valid[spec.name] = None
            continue
        lv = sel if spec.valid is None else sel & spec.valid
        if spec.func == "count":
            out_vals[spec.name] = live_count(spec)
            out_valid[spec.name] = None
            continue
        vals = spec.values
        if spec.func in ("sum", "avg"):
            acc = jnp.float64 if vals.dtype.kind == "f" else jnp.int64
            s = seg_sum(jnp.where(lv, vals.astype(acc), acc(0)))
            cnt = live_count(spec)
            if spec.func == "sum":
                out_vals[spec.name] = s
                out_valid[spec.name] = cnt > 0   # SQL: sum of no rows is NULL
            else:
                denom = jnp.where(cnt == 0, jnp.int64(1), cnt).astype(jnp.float64)
                avg = s.astype(jnp.float64) / denom
                if spec.decimal_scale:
                    avg = avg / (10.0 ** spec.decimal_scale)
                out_vals[spec.name] = avg
                out_valid[spec.name] = cnt > 0
        elif spec.func in ("min", "max"):
            # the identity must stay HOST-concrete (numpy, not jnp): under a
            # jit trace jnp.array() yields a tracer, and the fused kernel
            # needs ident.item() for pad/scratch-init constants
            if vals.dtype.kind == "f":
                ident = np.array(np.inf if spec.func == "min" else -np.inf,
                                 vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                ident = np.array(info.max if spec.func == "min" else info.min,
                                 vals.dtype)
            filled = jnp.where(lv, vals, ident)
            out_vals[spec.name] = seg_minmax(filled, spec.func, ident)
            out_valid[spec.name] = live_count(spec) > 0
        else:
            raise NotImplementedError(spec.func)
    return out_vals, out_valid


def dense_aggregate(gid, D: int, aggs: list[AggSpec], sel):
    """aggregate() semantics over dense group ids."""
    def seg_sum(masked):
        sel2 = gid[:, None] == jnp.arange(D, dtype=jnp.int32)[None, :]
        return jnp.sum(jnp.where(sel2, masked[:, None], masked.dtype.type(0)), axis=0)

    def seg_minmax(filled, func, ident):
        op = jnp.min if func == "min" else jnp.max
        return _masked_reduce(op, filled, gid, D, jnp.ones_like(sel), ident)

    return _run_aggs(aggs, sel, seg_sum, seg_minmax)


# ---------------------------------------------------------------------------
# Sort-based grouping: the high-cardinality path.
#
# The reference spills its hybrid hash agg to workfiles when the table
# overflows (src/backend/executor/execHHashagg.c); on TPU the scatter-heavy
# slot table serializes on colliding indices, so past the dense-domain
# regime we lax.sort rows by their group keys and reduce each run with
# segmented cumsum-diffs and scans — O(n log n), fully vectorized, no
# scatter, and cardinality bounded only by the batch itself (a GROUP BY can
# never produce more groups than input rows, so nothing ever "overflows"
# the way a hash table does; only the *output capacity* chosen for the
# batch above can, which retries via the executor's tier mechanism).
# ---------------------------------------------------------------------------


def _group_encode(k: KeySpec) -> list:
    """Equality-preserving uint64 encoding (+ null operand when nullable).
    Grouping needs equal-keys-adjacent, not collation order, so TEXT groups
    by dictionary code and float64 only canonicalizes -0.0/NaN."""
    from greengage_tpu import types as T

    v = k.values
    if k.type.kind is T.Kind.FLOAT64:
        v = jnp.where(v == 0.0, 0.0, v)
        v = jnp.where(jnp.isnan(v), jnp.float64(jnp.nan), v)
        enc = v.view(jnp.uint64)
    else:
        enc = v.astype(jnp.int64).view(jnp.uint64)
    ops = []
    if k.valid is not None:
        ops.append(jnp.where(k.valid, jnp.uint8(1), jnp.uint8(0)))
        enc = jnp.where(k.valid, enc, jnp.uint64(0))
    ops.append(enc)
    return ops


def pack_bits(bounds: list) -> int | None:
    """Total packed bits for per-key integer bounds [(lo, hi) | None].
    Each key takes ceil(log2(hi - lo + 2)) bits (the +2 reserves field
    value 0 for NULL) plus nothing else. None when any key is unbounded
    or the fields exceed 63 bits (bit 63 carries the dead-row flag)."""
    if not bounds or any(b is None for b in bounds):
        return None
    total = 0
    for lo, hi in bounds:
        span = int(hi) - int(lo) + 2
        if span <= 1:
            span = 2
        total += max(span - 1, 1).bit_length()
        if total > 63:
            return None
    return total


def pack_keys(keys: list[KeySpec], bounds: list, sel):
    """Pack stats-bounded integer keys into ONE uint64 word per row
    (dead flag in bit 63, then per-key fields, NULL = field value 0).

    -> (packed uint64[n], violation bool scalar). ``violation`` fires when
    any LIVE, non-NULL value falls outside its advertised bound — packing
    would alias distinct keys, so the caller must re-run unpacked (stale
    ANALYZE stats after DML). Equal packed words <=> equal key tuples
    (including NULL positions) whenever violation is False.

    Motivation (measured v5e, NOTES.md): lax.sort costs ~40 ns/row per
    OPERAND — Q3's 3-key group sort carries dead + 3 encodings + rowid = 5
    operands; packed it carries 2. That is the difference between a ~10s
    and a ~4s group phase at SF10.
    """
    n = sel.shape[0]
    word = jnp.zeros((n,), jnp.uint64)
    violation = jnp.zeros((), bool)
    for k, (lo, hi) in zip(keys, bounds):
        span = max(int(hi) - int(lo) + 2, 2)
        width = max(span - 1, 1).bit_length()
        v = k.values.astype(jnp.int64)
        in_b = (v >= lo) & (v <= hi)
        live = sel if k.valid is None else (sel & k.valid)
        violation = violation | jnp.any(live & ~in_b)
        field = jnp.where(in_b, v - jnp.int64(lo) + 1, 0).astype(jnp.uint64)
        if k.valid is not None:
            field = jnp.where(k.valid, field, jnp.uint64(0))
        word = (word << jnp.uint64(width)) | field
    word = jnp.where(sel, word, word | (jnp.uint64(1) << jnp.uint64(63)))
    return word, violation


def group_sort(keys: list[KeySpec], sel, bounds: list | None = None):
    """Sort rows by group keys, dead rows last.

    -> (perm int32[n], boundary bool[n], sel_sorted bool[n], violation):
    perm is the gather permutation (sorted_col = col[perm]); boundary marks
    the first (live) row of each equal-key run — the group's representative
    row. ``bounds`` (per-key (lo, hi) from ANALYZE) enables the packed
    single-operand sort; violation is a bool scalar the caller must route
    to an overflow flag (None when packing was not attempted).
    """
    from jax import lax

    n = sel.shape[0]
    violation = None
    if bounds is not None and pack_bits(bounds) is not None:
        word, violation = pack_keys(keys, bounds, sel)
        sorted_ops = lax.sort(
            (word, jnp.arange(n, dtype=jnp.int32)), num_keys=2)
        wkey = sorted_ops[0]
        perm = sorted_ops[-1]
        sel_sorted = (wkey >> jnp.uint64(63)) == 0
        if n > 1:
            first = jnp.concatenate(
                [jnp.ones((1,), bool), wkey[1:] != wkey[:-1]])
        else:
            first = jnp.ones((n,), bool)
        return perm, sel_sorted & first, sel_sorted, violation

    dead = (~sel).astype(jnp.uint8)
    key_ops = []
    for k in keys:
        key_ops.extend(_group_encode(k))
    operands = [dead] + key_ops + [jnp.arange(n, dtype=jnp.int32)]
    sorted_ops = lax.sort(tuple(operands), num_keys=len(operands))
    perm = sorted_ops[-1]
    sel_sorted = sorted_ops[0] == 0
    if key_ops and n > 1:
        neq = None
        for s in sorted_ops[1:1 + len(key_ops)]:
            d = s[1:] != s[:-1]
            neq = d if neq is None else (neq | d)
        first = jnp.concatenate([jnp.ones((1,), bool), neq])
    else:
        first = jnp.concatenate(
            [jnp.ones((min(n, 1),), bool), jnp.zeros((max(n - 1, 0),), bool)])
    return perm, sel_sorted & first, sel_sorted, violation


def sorted_group_aggregate(boundary, sel_sorted, aggs: list[AggSpec],
                           out_cap: int):
    """Table-shaped aggregation over key-sorted rows.

    -> (vals {name: [out_cap]}, valids, srcpos int32[out_cap], total) where
    group g's values live at slot g (groups numbered in key-sort order) and
    srcpos[g] is the SORTED-row index of g's first row (gather keys there).
    Groups beyond out_cap are dropped — the caller flags total > out_cap
    and retries with the exact count.

    TPU cost model (measured on v5e): cumsum ~40ms/6M, scatter ~540ms/6M,
    gather ~64ms/6M, associative_scan/searchsorted-over-rows unusably slow.
    So: sums/counts = whole-batch cumsum + span difference at the M group
    boundaries (M-sized gathers are ~free). int64 (scaled DECIMAL) sums
    split into 32-bit limbs with separate cumsums so the span difference is
    EXACT regardless of batch magnitude; float64 keeps one cumsum (group
    error ~ batch_total * eps — floats round under any summation order).
    min/max are not invertible, so they scatter into the group-id table
    (the only scatter in the path, paid per min/max aggregate).
    All spec arrays must already be key-sorted."""
    n = sel_sorted.shape[0]
    csb = jnp.cumsum(boundary.astype(jnp.int32))
    total = csb[-1] if n else jnp.int32(0)
    # first sorted row of group g; RAW positions keep n for absent groups
    # so the span ends don't truncate the last real group off by one.
    # Two interchangeable forms, picked by measured v5e costs: binary
    # search costs ~26 gathers of out_cap elements; a unique-index scatter
    # costs one ~(n*90ns) pass — cheaper once out_cap is a sizable
    # fraction of the batch (high-cardinality groupings).
    # break-even from the stated per-element costs: scatter ~90ns vs
    # gather ~10.7ns => 26 * out_cap * 10.7 > n * 90
    if out_cap * 26 * 10.7 > n * 90:
        stgt = jnp.where(boundary, jnp.minimum(csb - 1, out_cap), out_cap)
        raw = jnp.full((out_cap + 1,), n, jnp.int32).at[stgt].min(
            jnp.arange(n, dtype=jnp.int32))[:out_cap]
    else:
        raw = jnp.searchsorted(
            csb, jnp.arange(1, out_cap + 1, dtype=jnp.int32)).astype(jnp.int32)
    ends = jnp.clip(
        jnp.concatenate([raw[1:], jnp.full((1,), n, jnp.int32)]) - 1,
        0, max(n - 1, 0))
    srcpos = jnp.clip(raw, 0, max(n - 1, 0))
    gid = csb - 1                      # per-row group slot (dead rows get
    # the last group's id but every reducer masks them to the identity)
    tgt = jnp.where((gid >= 0) & (gid < out_cap), gid, out_cap)

    def span(cs):
        base = jnp.where(srcpos > 0, cs[jnp.clip(srcpos - 1, 0, max(n - 1, 0))],
                         jnp.zeros((), cs.dtype))
        return cs[ends] - base

    def seg_sum(masked):
        if masked.dtype == jnp.int64:
            lo = masked & jnp.int64(0xFFFFFFFF)     # [0, 2^32)
            hi = masked >> jnp.int64(32)            # arithmetic shift
            return (span(jnp.cumsum(hi)) << jnp.int64(32)) + span(jnp.cumsum(lo))
        if masked.dtype == jnp.float64:
            # floats cannot limb-split: a whole-batch prefix sum loses
            # precision proportional to the BATCH total (a small group's
            # span difference subtracts two near-equal ~1e12 prefixes), so
            # float sums pay the scatter — accumulation stays group-local,
            # matching per-group summation accuracy
            tbl = jnp.zeros((out_cap + 1,), jnp.float64).at[tgt].add(masked)
            return tbl[:out_cap]
        return span(jnp.cumsum(masked))

    def seg_minmax(filled, func, ident):
        tbl = jnp.full((out_cap + 1,), ident, dtype=filled.dtype)
        tbl = tbl.at[tgt].min(filled) if func == "min" else tbl.at[tgt].max(filled)
        return tbl[:out_cap]

    vals, valids = _run_aggs(aggs, sel_sorted, seg_sum, seg_minmax)
    return vals, valids, srcpos, total


def probe_sequence(h, M: int):
    """Double hashing: start slot from h, odd step from a derived second
    hash (odd steps visit every slot of a power-of-two table). Keeps probe
    chains ≈ 1/(1-load) instead of linear probing's clustered runs."""
    from greengage_tpu.ops.hashing import _fmix32

    slot = (h & jnp.uint32(M - 1)).astype(jnp.int32)
    h2 = _fmix32(h ^ jnp.uint32(0x85EBCA6B))
    step = ((h2 & jnp.uint32(M - 1)) | jnp.uint32(1)).astype(jnp.int32)
    return slot, step


def _seg_sum(vals, slots, M):
    return jnp.zeros((M + 1,), dtype=vals.dtype).at[slots].add(vals)[:M]


def aggregate(slots, M: int, aggs: list[AggSpec], sel):
    """aggregate() semantics per scatter slot (scalar aggregates use M=1)."""
    def seg_sum(masked):
        return _seg_sum(masked, slots, M)

    def seg_minmax(filled, func, ident):
        tbl = jnp.full((M + 1,), ident, dtype=filled.dtype)
        tbl = tbl.at[slots].min(filled) if func == "min" else tbl.at[slots].max(filled)
        return tbl[:M]

    return _run_aggs(aggs, sel, seg_sum, seg_minmax)
