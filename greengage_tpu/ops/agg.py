"""Vectorized hash aggregation — the execHHashagg.c analog, TPU-first.

Instead of a per-tuple spillable hash table (reference:
src/backend/executor/execHHashagg.c) we build a static power-of-two slot
table wholly on device:

  1. rows hash their group keys (ops/hashing spec) to a start slot
  2. P unrolled linear-probe rounds; each round, unresolved rows bid for
     their current slot with a scatter-min of row index, winners write their
     actual key values into the table, and every row resolves by *exact*
     key comparison against the table (null-safe) — no fingerprints, so no
     collision false-merges, ever
  3. aggregates reduce with segment_sum/min/max over resolved slots — MXU/
     VPU-friendly one-pass reductions

Rows that fail to resolve within P probes (table too small / pathological
clustering) raise an ``overflow`` flag; the executor re-runs at the next
table-size tier (the recompilation-tier strategy from SURVEY.md §7 "hard
parts" — the workfile-spill analog).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from greengage_tpu.ops import hashing

BIG = jnp.iinfo(jnp.int32).max


@dataclass
class KeySpec:
    values: jnp.ndarray
    valid: jnp.ndarray | None
    type: object            # T.SqlType
    hash_lut: jnp.ndarray | None = None  # TEXT: per-dict-entry hashes


@dataclass
class AggSpec:
    name: str
    func: str               # count_star | count | sum | min | max | avg
    values: jnp.ndarray | None
    valid: jnp.ndarray | None
    # DECIMAL inputs are scaled int64; avg must descale its float64 result
    # by 10^scale (sum/min/max stay in scaled-int domain, declared DECIMAL).
    decimal_scale: int = 0


def _null_eq(a, av, b, bv):
    """Grouping equality: NULL == NULL (SQL GROUP BY semantics)."""
    eq = a == b
    if av is None and bv is None:
        return eq
    av_ = av if av is not None else jnp.ones_like(eq)
    bv_ = bv if bv is not None else jnp.ones_like(eq)
    return (av_ & bv_ & eq) | (~av_ & ~bv_)


def build_slot_table(keys: list[KeySpec], sel, table_size: int, num_probes: int):
    """Assign each selected row a slot; rows with equal keys share a slot.

    Returns (final_slot int32 [n] with ``table_size`` for dead/unresolved
    rows, table_keys, table_key_valids, used bool[M], overflow bool scalar).
    """
    M = table_size
    assert M & (M - 1) == 0, "table size must be a power of two"
    n = sel.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)

    col_hashes = [
        hashing.column_hash(k.values, k.valid, k.type, text_lut=k.hash_lut) for k in keys
    ]
    h = hashing.row_hash(col_hashes)
    slot, step = probe_sequence(h, M)

    active = sel
    final_slot = jnp.full((n,), M, dtype=jnp.int32)
    used = jnp.zeros((M,), dtype=bool)
    tkeys = [jnp.zeros((M,), dtype=k.values.dtype) for k in keys]
    tvalids = [None if k.valid is None else jnp.zeros((M,), dtype=bool) for k in keys]

    for _ in range(num_probes):
        bids = jnp.full((M,), BIG, dtype=jnp.int32).at[slot].min(
            jnp.where(active, row_idx, BIG)
        )
        newly = (~used) & (bids < BIG)
        winner = jnp.clip(bids, 0, n - 1)
        for i, k in enumerate(keys):
            tkeys[i] = jnp.where(newly, k.values[winner], tkeys[i])
            if tvalids[i] is not None:
                tvalids[i] = jnp.where(newly, k.valid[winner], tvalids[i])
        used = used | newly
        # exact match against table contents at my current slot
        match = active & used[slot]
        for i, k in enumerate(keys):
            tv = tvalids[i][slot] if tvalids[i] is not None else None
            match = match & _null_eq(k.values, k.valid, tkeys[i][slot], tv)
        final_slot = jnp.where(match, slot, final_slot)
        active = active & ~match
        slot = (slot + step) & (M - 1)

    return final_slot, tkeys, tvalids, used, jnp.any(active)


# ---------------------------------------------------------------------------
# Dense path: small known key domains (TEXT dictionaries / BOOL)
#
# TPU scatters serialize on colliding indices, so the generic slot table
# costs ~70ns/row. When every group key has a finite known domain we skip
# hashing/probing entirely: gid = mixed-radix index over (code+1) digits
# (0 = NULL), and every aggregate is a fused masked reduction over a
# [rows, D] broadcast — one HBM pass, VPU-only, no scatter/gather.
# This is the Q1-class fast path; high-cardinality keys use the slot table.
# ---------------------------------------------------------------------------


def dense_gid(keys: list[KeySpec], domains: list[int], sel):
    """-> (gid int32[n] in [0, D), D). domains[i] = |dict_i| + 1 (NULL)."""
    gid = None
    for k, dom in zip(keys, domains):
        idx = k.values.astype(jnp.int32) + 1
        if k.valid is not None:
            idx = jnp.where(k.valid, idx, 0)
        gid = idx if gid is None else gid * jnp.int32(dom) + idx
    D = 1
    for dom in domains:
        D *= dom
    return jnp.where(sel, gid, jnp.int32(0)), D


def dense_decode_keys(keys: list[KeySpec], domains: list[int], D: int):
    """Reconstruct per-group key code arrays [D] (and NULL masks) from gid
    arithmetic — no gathers."""
    iota = jnp.arange(D, dtype=jnp.int32)
    out = []
    strides = []
    s = 1
    for dom in reversed(domains):
        strides.append(s)
        s *= dom
    strides = list(reversed(strides))
    for k, dom, st in zip(keys, domains, strides):
        idx = (iota // jnp.int32(st)) % jnp.int32(dom)
        code = (idx - 1).astype(k.values.dtype)
        valid = idx > 0
        out.append((code, valid))
    return out


def _masked_reduce(op, vals, gid, D, mask, ident):
    """One fused pass: reduce vals into D groups via broadcast-compare.
    XLA fuses the [n, D] compare+select into the reduction tiles."""
    sel2 = mask[:, None] & (gid[:, None] == jnp.arange(D, dtype=jnp.int32)[None, :])
    filled = jnp.where(sel2, vals[:, None], ident)
    return op(filled, axis=0)


def dense_aggregate(gid, D: int, aggs: list[AggSpec], sel):
    """aggregate() semantics over dense group ids (see aggregate)."""
    out_vals: dict[str, jnp.ndarray] = {}
    out_valid: dict[str, jnp.ndarray] = {}
    counts_cache: dict = {}
    iotaD = jnp.arange(D, dtype=jnp.int32)

    def live_count(spec):
        key = None if spec is None or spec.valid is None else id(spec.valid)
        if key not in counts_cache:
            lv = sel if spec is None or spec.valid is None else (sel & spec.valid)
            onehot = lv[:, None] & (gid[:, None] == iotaD[None, :])
            counts_cache[key] = jnp.sum(onehot.astype(jnp.int64), axis=0)
        return counts_cache[key]

    group_count = live_count(None)
    for spec in aggs:
        if spec.func == "count_star":
            out_vals[spec.name] = group_count
            out_valid[spec.name] = None
            continue
        lv = sel if spec.valid is None else sel & spec.valid
        if spec.func == "count":
            out_vals[spec.name] = live_count(spec)
            out_valid[spec.name] = None
            continue
        vals = spec.values
        if spec.func in ("sum", "avg"):
            acc = jnp.float64 if vals.dtype.kind == "f" else jnp.int64
            s = _masked_reduce(jnp.sum, vals.astype(acc), gid, D, lv, acc(0))
            cnt = live_count(spec)
            if spec.func == "sum":
                out_vals[spec.name] = s
                out_valid[spec.name] = cnt > 0
            else:
                denom = jnp.where(cnt == 0, jnp.int64(1), cnt).astype(jnp.float64)
                avg = s.astype(jnp.float64) / denom
                if spec.decimal_scale:
                    avg = avg / (10.0 ** spec.decimal_scale)
                out_vals[spec.name] = avg
                out_valid[spec.name] = cnt > 0
        elif spec.func in ("min", "max"):
            if vals.dtype.kind == "f":
                ident = jnp.array(jnp.inf if spec.func == "min" else -jnp.inf, vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                ident = jnp.array(info.max if spec.func == "min" else info.min, vals.dtype)
            op = jnp.min if spec.func == "min" else jnp.max
            out_vals[spec.name] = _masked_reduce(op, vals, gid, D, lv, ident)
            out_valid[spec.name] = live_count(spec) > 0
        else:
            raise NotImplementedError(spec.func)
    return out_vals, out_valid


def probe_sequence(h, M: int):
    """Double hashing: start slot from h, odd step from a derived second
    hash (odd steps visit every slot of a power-of-two table). Keeps probe
    chains ≈ 1/(1-load) instead of linear probing's clustered runs."""
    from greengage_tpu.ops.hashing import _fmix32

    slot = (h & jnp.uint32(M - 1)).astype(jnp.int32)
    h2 = _fmix32(h ^ jnp.uint32(0x85EBCA6B))
    step = ((h2 & jnp.uint32(M - 1)) | jnp.uint32(1)).astype(jnp.int32)
    return slot, step


def _seg_sum(vals, slots, M):
    return jnp.zeros((M + 1,), dtype=vals.dtype).at[slots].add(vals)[:M]


def aggregate(slots, M: int, aggs: list[AggSpec], sel):
    """Compute aggregates per slot. Returns ({name: values}, {name: valid})."""
    out_vals: dict[str, jnp.ndarray] = {}
    out_valid: dict[str, jnp.ndarray] = {}
    # memoize per-group live counts per distinct valid mask (shared by
    # count/sum-validity/avg/min/max for the same column's mask)
    counts_cache: dict[int, jnp.ndarray] = {}

    def live_valid(spec):
        v = sel
        if spec.valid is not None:
            v = v & spec.valid
        return v

    def live_count(spec):
        key = None if spec is None or spec.valid is None else id(spec.valid)
        if key not in counts_cache:
            lv = sel if spec is None else live_valid(spec)
            counts_cache[key] = _seg_sum(jnp.where(lv, jnp.int64(1), jnp.int64(0)), slots, M)
        return counts_cache[key]

    group_count = live_count(None)

    for spec in aggs:
        if spec.func == "count_star":
            out_vals[spec.name] = group_count
            out_valid[spec.name] = None
            continue
        lv = live_valid(spec)
        if spec.func == "count":
            out_vals[spec.name] = live_count(spec)
            out_valid[spec.name] = None
            continue
        vals = spec.values
        if spec.func in ("sum", "avg"):
            acc_dtype = jnp.float64 if vals.dtype.kind == "f" else jnp.int64
            s = _seg_sum(jnp.where(lv, vals.astype(acc_dtype), acc_dtype(0)), slots, M)
            cnt = live_count(spec)
            if spec.func == "sum":
                out_vals[spec.name] = s
                out_valid[spec.name] = cnt > 0   # SQL: sum of no rows is NULL
            else:
                denom = jnp.where(cnt == 0, jnp.int64(1), cnt).astype(jnp.float64)
                avg = s.astype(jnp.float64) / denom
                if spec.decimal_scale:
                    avg = avg / (10.0 ** spec.decimal_scale)
                out_vals[spec.name] = avg
                out_valid[spec.name] = cnt > 0
            continue
        if spec.func in ("min", "max"):
            if vals.dtype.kind == "f":
                ident = jnp.array(jnp.inf if spec.func == "min" else -jnp.inf, vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                ident = jnp.array(info.max if spec.func == "min" else info.min, vals.dtype)
            filled = jnp.where(lv, vals, ident)
            tbl = jnp.full((M + 1,), ident, dtype=vals.dtype)
            tbl = tbl.at[slots].min(filled) if spec.func == "min" else tbl.at[slots].max(filled)
            out_vals[spec.name] = tbl[:M]
            out_valid[spec.name] = live_count(spec) > 0
            continue
        raise NotImplementedError(spec.func)
    return out_vals, out_valid


def merge_partial(slots, M, partial_vals, partial_valids, funcs, sel):
    """Final phase of two-phase aggregation: combine partial states that were
    redistributed by group key (cdbgroup.c two-stage agg analog).

    partial state per original agg: count -> sum of counts; sum -> sum of
    sums; min/max -> min/max of partials; avg carries (sum, count) pairs —
    handled by the compiler as two partial columns.
    """
    out_vals, out_valid = {}, {}
    for name, func in funcs.items():
        vals = partial_vals[name]
        pv = partial_valids.get(name)
        lv = sel if pv is None else sel & pv
        if func in ("count", "count_star", "sum"):
            acc_dtype = jnp.float64 if vals.dtype.kind == "f" else jnp.int64
            s = _seg_sum(jnp.where(lv, vals.astype(acc_dtype), acc_dtype(0)), slots, M)
            out_vals[name] = s if func != "count" and func != "count_star" else s.astype(jnp.int64)
            if func == "sum":
                out_valid[name] = _seg_sum(jnp.where(lv, jnp.int64(1), jnp.int64(0)), slots, M) > 0
            else:
                out_valid[name] = None
        elif func in ("min", "max"):
            if vals.dtype.kind == "f":
                ident = jnp.array(jnp.inf if func == "min" else -jnp.inf, vals.dtype)
            else:
                info = jnp.iinfo(vals.dtype)
                ident = jnp.array(info.max if func == "min" else info.min, vals.dtype)
            filled = jnp.where(lv, vals, ident)
            tbl = jnp.full((M + 1,), ident, dtype=vals.dtype)
            tbl = tbl.at[slots].min(filled) if func == "min" else tbl.at[slots].max(filled)
            out_vals[name] = tbl[:M]
            out_valid[name] = _seg_sum(jnp.where(lv, jnp.int64(1), jnp.int64(0)), slots, M) > 0
        else:
            raise NotImplementedError(func)
    return out_vals, out_valid
