"""On-device distribution/group hashing — JAX mirror of native/ggcodec.cpp.

MUST remain bit-identical to greengage_tpu/storage/native.py (the spec's
reference implementation, itself mirrored by the C++ codec): fmix32 over the
32-bit halves of each 64-bit value, FNV-combine across columns, NULL column
contributes hash 0, placement = row_hash % numsegments. Tested against the
host implementation in tests/test_ops.py.

Reference parity: src/backend/cdb/cdbhash.c (makeCdbHash/cdbhash/
cdbhashreduce). We use modulo reduction everywhere (the reference's
"legacy mod" mode, cdblegacyhash.c) because jump-consistent-hash's
data-dependent loop is hostile to XLA; expansion therefore redistributes
fully (ALTER TABLE EXPAND TABLE analog always rewrites).
"""

from __future__ import annotations

import jax.numpy as jnp

from greengage_tpu import types as T

HASH_INIT = 0x9E3779B9
COMBINE_MUL = 0x01000193


def _fmix32(h):
    h = h.astype(jnp.uint32)
    h ^= h >> 16
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def hash_i64(vals, seed: int = 0):
    """uint32 hash of an int64-representable array."""
    u = vals.astype(jnp.int64).view(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
    h = jnp.uint32(seed) ^ jnp.uint32(HASH_INIT)
    h = _fmix32(h ^ lo)
    h = _fmix32(h ^ hi)
    return h


def hash_combine(acc, h):
    return _fmix32(acc.astype(jnp.uint32) * jnp.uint32(COMBINE_MUL) ^ h.astype(jnp.uint32))


def _canon_f64(arr):
    """hashfloat8 parity: -0.0 -> 0.0, all NaNs -> one pattern."""
    arr = jnp.where(arr == 0.0, 0.0, arr)
    arr = jnp.where(jnp.isnan(arr), jnp.float64(jnp.nan), arr)
    return arr


def column_hash(arr, valid, type_: T.SqlType, seed: int = 0, text_lut=None):
    """Per-column uint32 hash; NULL rows -> 0. TEXT uses the dictionary
    hash LUT (host-precomputed, one extra sentinel row for code -1)."""
    if type_.kind is T.Kind.TEXT:
        if text_lut is None:
            raise ValueError("TEXT hashing requires the dictionary hash LUT")
        h = text_lut[arr]
    elif type_.kind is T.Kind.FLOAT64:
        h = hash_i64(_canon_f64(arr).view(jnp.int64), seed)
    else:
        h = hash_i64(arr, seed)
    if valid is not None:
        h = jnp.where(valid, h, jnp.uint32(0))
    return h


def row_hash(col_hashes) -> jnp.ndarray:
    """Combine per-column hashes: acc = h0; acc = combine(acc, hi)."""
    acc = col_hashes[0]
    for h in col_hashes[1:]:
        acc = hash_combine(acc, h)
    return acc


def segment_of(rowhash, numsegments: int):
    return (rowhash % jnp.uint32(numsegments)).astype(jnp.int32)
