"""Vectorized hash join — nodeHashjoin.c reimagined for static shapes.

Build side inserts into the same exact-key slot table as ops/agg.py; probe
side walks the identical probe sequence and matches by exact key equality.
Output keeps the probe side's capacity: each probe row gains a ``matched``
flag and a gathered build-row index, so inner/left/semi/anti joins are all
selection-mask updates plus gathers — no dynamic-size compaction.

Duplicate build keys resolve to the same slot; the winner's row index is
stored and every non-winner build row reports ``dup`` (duplicate flag). The
planner only routes unique-key builds here (PK-FK joins, the dominant case);
duplicate builds use broadcast nested-loop fallback until a multi-match
kernel lands. Unresolved build rows (> num_probes chain) raise ``overflow``
for the executor's table-size retry tier.

SQL NULL semantics: a NULL join key equals nothing, so NULL-keyed rows on
either side simply never match (unlike GROUP BY's null-merging equality).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from greengage_tpu.ops import hashing
from greengage_tpu.ops.agg import BIG, KeySpec
from greengage_tpu.ops.agg import probe_sequence as agg_probe_sequence


@dataclass
class BuildTable:
    slot_keys: list[jnp.ndarray]
    slot_key_valids: list[jnp.ndarray | None]
    slot_row: jnp.ndarray      # build row index per slot
    used: jnp.ndarray
    overflow: jnp.ndarray      # bool scalar
    dup: jnp.ndarray           # bool scalar: build had duplicate keys
    size: int


def _key_hash(keys: list[KeySpec]):
    return hashing.row_hash(
        [hashing.column_hash(k.values, k.valid, k.type, text_lut=k.hash_lut) for k in keys]
    )


def _strict_eq(a, av, b, bv):
    """Join equality: NULL matches nothing."""
    eq = a == b
    if av is not None:
        eq = eq & av
    if bv is not None:
        eq = eq & bv
    return eq


def build(keys: list[KeySpec], sel, table_size: int, num_probes: int) -> BuildTable:
    M = table_size
    assert M & (M - 1) == 0
    n = sel.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)
    # NULL keys never participate (strict equality): drop them from the build
    for k in keys:
        if k.valid is not None:
            sel = sel & k.valid
    h = _key_hash(keys)
    slot, step = agg_probe_sequence(h, M)

    active = sel
    used = jnp.zeros((M,), dtype=bool)
    slot_row = jnp.zeros((M,), dtype=jnp.int32)
    tkeys = [jnp.zeros((M,), dtype=k.values.dtype) for k in keys]
    dup = jnp.zeros((), dtype=bool)

    for _ in range(num_probes):
        bids = jnp.full((M,), BIG, dtype=jnp.int32).at[slot].min(
            jnp.where(active, row_idx, BIG)
        )
        newly = (~used) & (bids < BIG)
        winner = jnp.clip(bids, 0, n - 1)
        for i, k in enumerate(keys):
            tkeys[i] = jnp.where(newly, k.values[winner], tkeys[i])
        slot_row = jnp.where(newly, winner, slot_row)
        used = used | newly
        match = active & used[slot]
        for i, k in enumerate(keys):
            match = match & (k.values == tkeys[i][slot])
        # a build row matching a slot stored for a *different* row = duplicate key
        dup = dup | jnp.any(match & (slot_row[slot] != row_idx))
        active = active & ~match
        slot = (slot + step) & (M - 1)

    return BuildTable(
        slot_keys=tkeys,
        slot_key_valids=[None] * len(keys),
        slot_row=slot_row,
        used=used,
        overflow=jnp.any(active),
        dup=dup,
        size=M,
    )


def probe(table: BuildTable, keys: list[KeySpec], sel, num_probes: int):
    """-> (matched bool[n], build_row int32[n]) over the probe batch."""
    M = table.size
    strict_sel = sel
    for k in keys:
        if k.valid is not None:
            strict_sel = strict_sel & k.valid
    h = _key_hash(keys)
    slot, step = agg_probe_sequence(h, M)

    matched = jnp.zeros_like(sel)
    build_row = jnp.zeros(sel.shape, dtype=jnp.int32)
    active = strict_sel
    for _ in range(num_probes):
        hit = active & table.used[slot]
        for i, k in enumerate(keys):
            hit = hit & (k.values == table.slot_keys[i][slot])
        matched = matched | hit
        build_row = jnp.where(hit, table.slot_row[slot], build_row)
        active = active & ~hit
        slot = (slot + step) & (M - 1)
    return matched, build_row


def gather_build_columns(build_cols: dict, build_valids: dict, build_row, matched):
    """Pull build-side columns across to probe-side capacity. Unmatched rows
    get valid=False (supports LEFT OUTER null-extension for free)."""
    out_cols, out_valids = {}, {}
    for name, arr in build_cols.items():
        out_cols[name] = arr[build_row]
        v = build_valids.get(name)
        gv = v[build_row] if v is not None else jnp.ones_like(matched)
        out_valids[name] = gv & matched
    return out_cols, out_valids
