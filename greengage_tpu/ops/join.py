"""Vectorized hash join — nodeHashjoin.c reimagined for static shapes.

Sort-based build (round-2 redesign): build rows are sorted ONCE by
(bucket, exact key columns) with ``lax.sort``'s multi-operand lexicographic
mode, so rows with equal keys form contiguous *runs* inside their hash
bucket. The table is then just the sorted arrays plus CSR bucket offsets:

  build = 1 stable sort + 1 scatter-add (bucket counts) + cumulative scans
  probe = hop run-head to run-head inside the bucket (dynamic-trip
          ``while_loop``; each hop is one gather per key column)

This replaces the round-1 open-addressing claim loop whose per-round
full-table scatters cost ~30s at 15M build rows on v5e; the sort build is
two orders of magnitude cheaper and needs no slot-claim conflict rounds at
all. Duplicate build keys are first-class: a probe hit lands on its run's
head and reads the run length, so unique joins (winner = first build row),
multi-match CSR expansion, and duplicate detection (any run length > 1)
all fall out of the same structure.

Output keeps the probe side's capacity: each probe row gains a ``matched``
flag and a gathered build-row index, so inner/left/semi/anti joins are all
selection-mask updates plus gathers — no dynamic-size compaction.

SQL NULL semantics: a NULL join key equals nothing, so NULL-keyed rows on
either side never participate (they sort to the dead tail past every live
bucket). Float keys are canonicalized (-0.0 -> 0.0) before sorting so SQL
equality matches run grouping; NaN != NaN falls out of IEEE compare.

Reference parity: src/backend/executor/nodeHashjoin.c + nodeHash.c roles
(hash build/probe, duplicate chains); the CSR expansion stands in for the
dynamic output batching under XLA's static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from greengage_tpu.ops import hashing
from greengage_tpu.ops.agg import BIG, KeySpec


def _canon_values(k: KeySpec):
    """Key values under SQL equality: canonicalize float zeros."""
    v = k.values
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(v == 0.0, jnp.zeros((), v.dtype), v)
    return v


def _bucket_hash(keys: list[KeySpec]) -> jnp.ndarray:
    """uint32 bucket hash over the key columns.

    Joins only need build and probe to agree (probe TEXT codes are already
    translated into the build's code space by the binder), so every column
    — TEXT codes included — hashes as its integer representation; no
    dictionary LUT is needed here, unlike distribution hashing.
    """
    hs = []
    for k in keys:
        v = _canon_values(k)
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = v.view(jnp.int64 if v.dtype == jnp.float64 else jnp.int32)
        hs.append(hashing.hash_i64(v))
    return hashing.row_hash(hs)


def join_pack_bits(bounds: list | None) -> int | None:
    """Total bits to pack join-key tuples with per-key (lo, hi) integer
    bounds. NULL keys never participate in joins (strict selection), so no
    NULL slot is reserved — unlike agg.pack_bits. None = not packable."""
    if not bounds or any(b is None for b in bounds):
        return None
    total = 0
    for lo, hi in bounds:
        span = int(hi) - int(lo) + 1
        if span <= 0:
            return None
        total += max((span - 1).bit_length(), 1)
        if total > 64:
            return None
    return total


def pack_join_keys(keys: list[KeySpec], bounds: list):
    """Pack key columns into one uint32/uint64 word per row using the
    BUILD side's ANALYZE bounds. -> (word, in_bounds): rows whose values
    fall outside the bounds get in_bounds=False — on the build side that
    is a stats-staleness violation (caller flags + retries unpacked); on
    the probe side such a row simply cannot match any build key.

    Why: the probe walk gathers one key column per hop per key — packing
    makes that ONE u32 gather (measured 64ms vs 136ms per 6M-row gather
    for i32 vs i64), and the build sort drops to a single key operand."""
    total = join_pack_bits(bounds)
    dtype = jnp.uint32 if total <= 32 else jnp.uint64
    n = keys[0].values.shape[0]
    word = jnp.zeros((n,), dtype)
    in_bounds = jnp.ones((n,), bool)
    for k, (lo, hi) in zip(keys, bounds):
        span = int(hi) - int(lo) + 1
        width = max((span - 1).bit_length(), 1)
        v = _canon_values(k).astype(jnp.int64)
        ok = (v >= lo) & (v <= hi)
        in_bounds = in_bounds & ok
        field = jnp.where(ok, v - jnp.int64(lo), 0).astype(dtype)
        word = (word << dtype(width)) | field
    return word, in_bounds


@dataclass
class SortTable:
    """Sorted-run join table (see module docstring).

    Arrays live at *sorted position* granularity except ``starts``/
    ``counts`` (bucket granularity). ``next_head[i]`` is the smallest
    run-head position >= i (BIG past the last run) — the probe walk's hop
    pointer. ``n_live`` is the number of participating build rows (the dead
    tail starts there)."""

    keys_sorted: list[jnp.ndarray]
    rows_sorted: jnp.ndarray       # int32 [n] build row index per position
    next_head: jnp.ndarray        # int32 [n]
    starts: jnp.ndarray            # int32 [M] first position of bucket
    counts: jnp.ndarray            # int32 [M] live rows in bucket
    n_live: jnp.ndarray            # int32 scalar
    overflow: jnp.ndarray          # bool scalar: probe walk bound exceeded
    dup: jnp.ndarray               # bool scalar: duplicate build keys
    size: int
    # packed mode: keys_sorted is ONE u32/u64 word column; the probe must
    # apply the same packing (bounds) — build-side out-of-bounds values
    # raise pack_viol (stale stats -> caller re-runs unpacked)
    bounds: list | None = None
    pack_viol: jnp.ndarray | None = None

    @property
    def base(self) -> "SortTable":
        # multi-match call sites read table.base.overflow; the sorted table
        # serves both roles, so base is identity
        return self


def build(keys: list[KeySpec], sel, table_size: int, num_probes: int,
          key_bounds: list | None = None) -> SortTable:
    """Build the sorted-run table. ``num_probes`` is unused at build time
    (kept for call-site compatibility; the probe walk takes its own bound).
    ``key_bounds`` (build-side ANALYZE (lo, hi) per key) switches to the
    packed single-word key representation."""
    from jax import lax

    M = table_size
    assert M & (M - 1) == 0
    n = sel.shape[0]
    strict = sel
    for k in keys:
        if k.valid is not None:
            strict = strict & k.valid   # NULL keys never participate
    h = _bucket_hash(keys)
    pack_viol = None
    bounds = None
    if key_bounds is not None and join_pack_bits(key_bounds) is not None:
        word, in_b = pack_join_keys(keys, key_bounds)
        pack_viol = jnp.any(strict & ~in_b)
        # keep the table well-formed even when the flag fires (the run's
        # result is discarded): out-of-bounds rows drop from the table
        strict = strict & in_b
        kvals = [word]
        bounds = key_bounds
    else:
        kvals = [_canon_values(k) for k in keys]
    slot = jnp.where(strict, (h & jnp.uint32(M - 1)).astype(jnp.int32), M)
    row_idx = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = lax.sort(
        tuple([slot] + kvals + [row_idx]), num_keys=1 + len(kvals),
        is_stable=True)
    slot_s = sorted_ops[0]
    keys_s = list(sorted_ops[1:-1])
    rows_s = sorted_ops[-1]
    live_s = slot_s < M

    counts = jnp.zeros((M + 1,), jnp.int32).at[slot].add(
        jnp.where(strict, 1, 0))[:M]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])

    # run heads: first position of each contiguous equal-key run. A bucket
    # boundary always starts a run (equal keys always share a bucket).
    same_prev = slot_s[1:] == slot_s[:-1]
    for ks in keys_s:
        same_prev = same_prev & (ks[1:] == ks[:-1])
    head = jnp.concatenate([jnp.ones((min(n, 1),), bool), ~same_prev]) \
        if n > 1 else jnp.ones((n,), bool)
    head = head & live_s
    dup = jnp.any(live_s & ~head)

    next_head = lax.cummin(
        jnp.where(head, jnp.arange(n, dtype=jnp.int32), BIG), axis=0,
        reverse=True)
    return SortTable(
        keys_sorted=keys_s, rows_sorted=rows_s, next_head=next_head,
        starts=starts, counts=counts,
        n_live=jnp.sum(strict.astype(jnp.int32)),
        overflow=jnp.zeros((), bool), dup=dup, size=M,
        bounds=bounds, pack_viol=pack_viol)


def _walk(table: SortTable, keys: list[KeySpec], sel, num_probes: int):
    """Hop the probe's bucket run-head to run-head until its key's run is
    found or the bucket is exhausted. -> (matched, pos, run_count, overflow):
    pos is the run head's sorted position, run_count its length."""
    from jax import lax

    strict = sel
    for k in keys:
        if k.valid is not None:
            strict = strict & k.valid
    h = _bucket_hash(keys)
    slot = (h & jnp.uint32(table.size - 1)).astype(jnp.int32)
    start = table.starts[slot]
    end = start + table.counts[slot]
    if table.bounds is not None:
        word, in_b = pack_join_keys(keys, table.bounds)
        # an out-of-bounds probe key cannot equal any (in-bounds) build key
        strict = strict & in_b
        kvals = [word]
    else:
        kvals = [_canon_values(k) for k in keys]
    n = table.rows_sorted.shape[0]
    npos = jnp.int32(n)

    def cond(st):
        return jnp.any(st[1]) & (st[4] < num_probes)

    def body(st):
        pos, active, matched, mpos, i = st
        safe = jnp.clip(pos, 0, n - 1)
        hit = active
        for kv, ks in zip(kvals, table.keys_sorted):
            hit = hit & (kv == ks[safe])
        matched = matched | hit
        mpos = jnp.where(hit, safe, mpos)
        # hop to the next run head in this bucket
        nxt = jnp.where(pos + 1 < npos,
                        table.next_head[jnp.clip(pos + 1, 0, n - 1)], BIG)
        active = active & ~hit & (nxt < end)
        return (jnp.where(active, nxt, pos), active, matched, mpos, i + 1)

    init = (start, strict & (table.counts[slot] > 0),
            jnp.zeros_like(sel), jnp.zeros(sel.shape, jnp.int32), jnp.int32(0))
    _, active, matched, mpos, _ = lax.while_loop(cond, body, init)
    safe = jnp.clip(mpos, 0, n - 1)
    nxt = jnp.where(mpos + 1 < npos,
                    table.next_head[jnp.clip(mpos + 1, 0, n - 1)], BIG)
    run_end = jnp.minimum(jnp.minimum(nxt, end), table.n_live)
    run_count = jnp.where(matched, run_end - safe, 0)
    return matched, safe, run_count, jnp.any(active)


def probe(table: SortTable, keys: list[KeySpec], sel, num_probes: int):
    """-> (matched bool[n], build_row int32[n], walk_overflow bool scalar)
    over the probe batch. Duplicate build keys resolve to the run head =
    smallest build row index (the stable sort preserves row order within a
    run). walk_overflow means the hop bound was hit with probes still
    active — the caller must OR it into its overflow flag so the executor
    retries at the next tier (bigger table, higher bound)."""
    matched, pos, _, ov = _walk(table, keys, sel, num_probes)
    return matched, jnp.where(matched, table.rows_sorted[pos], 0), ov


# ---------------------------------------------------------------------------
# Multi-match join: duplicate build keys via the runs themselves
#
# A probe hit knows its run's start position and length, so the output
# expands via prefix sums + searchsorted over a static output capacity —
# output row j maps to (probe_row[j], build_row[j]); an overflow flag plus
# the exact total cardinality feed the executor's tier retry, standing in
# for nodeHashjoin's dynamic batching under XLA's static shapes.
# ---------------------------------------------------------------------------


def build_multi(keys: list[KeySpec], sel, table_size: int, num_probes: int,
                key_bounds: list | None = None) -> SortTable:
    return build(keys, sel, table_size, num_probes, key_bounds)


def probe_multi(table: SortTable, keys: list[KeySpec], sel, num_probes: int,
                out_cap: int, left_outer: bool = False):
    """-> (present[K], probe_row[K], build_row[K], matched[K], expand_ov,
    walk_ov, total) where total is the exact output cardinality — the
    executor uses it to size the retry capacity when expand_ov fires.
    walk_ov must feed the TABLE-side overflow flag (grows M/hop bound at
    the next tier), NOT the expansion flag: the expansion flag's retry
    hint sizes out_cap from `total`, which is an UNDERCOUNT when the walk
    gave up early.

    left_outer: unmatched probe rows still emit one output row with
    matched=False (NULL-extended build side downstream)."""
    matched, pos, run_count, walk_ov = _walk(table, keys, sel, num_probes)
    count = run_count
    if left_outer:
        count = jnp.where(sel & ~matched, 1, count)
    cum = jnp.cumsum(count.astype(jnp.int64))
    total = cum[-1] if count.shape[0] else jnp.int64(0)
    overflow = total > out_cap
    j = jnp.arange(out_cap, dtype=jnp.int64)
    probe_row = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    pr = jnp.clip(probe_row, 0, count.shape[0] - 1)
    prev = jnp.where(pr > 0, cum[pr - 1], 0)
    ordinal = (j - prev).astype(jnp.int32)
    present = j < total
    m_at = matched[pr]
    n = table.rows_sorted.shape[0]
    build_row = table.rows_sorted[
        jnp.clip(pos[pr] + ordinal, 0, n - 1)]
    build_row = jnp.where(m_at, build_row, 0)
    return present, pr, build_row, m_at & present, overflow, walk_ov, total


def gather_build_columns(build_cols: dict, build_valids: dict, build_row, matched):
    """Pull build-side columns across to probe-side capacity. Unmatched rows
    get valid=False (supports LEFT OUTER null-extension for free)."""
    out_cols, out_valids = {}, {}
    for name, arr in build_cols.items():
        out_cols[name] = arr[build_row]
        v = build_valids.get(name)
        gv = v[build_row] if v is not None else jnp.ones_like(matched)
        out_valids[name] = gv & matched
    return out_cols, out_valids


# ---------------------------------------------------------------------------
# Direct-addressed join: dense integer build keys (the TPC-H PK-FK case)
#
# When ANALYZE shows the build key's domain [min, max] is comparable to the
# build row count (surrogate/sequence keys: orderkey, custkey, ...), the
# hash table degenerates to a dense array indexed by (key - min): build is
# ONE scatter, probe is ONE gather — measured on v5e, even the sort build
# costs ~1s at 15M rows while this whole join runs in ~2 passes of memory
# bandwidth. Unique-key builds only (the dup flag reports violations for
# the executor's re-plan).
# ---------------------------------------------------------------------------


@dataclass
class DirectTable:
    slot_row: jnp.ndarray
    used: jnp.ndarray
    overflow: jnp.ndarray
    dup: jnp.ndarray
    size: int


def build_direct(key: KeySpec, sel, lo: int, domain: int) -> DirectTable:
    """Dense build table over key values in [lo, lo+domain)."""
    v = key.values.astype(jnp.int64) - jnp.int64(lo)
    strict = sel
    if key.valid is not None:
        strict = strict & key.valid
    in_dom = strict & (v >= 0) & (v < domain)
    idx = jnp.where(in_dom, v, domain).astype(jnp.int64)
    n = sel.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)
    slot_row = jnp.full((domain + 1,), -1, jnp.int32).at[idx].max(
        jnp.where(in_dom, row_idx, -1))
    used = slot_row[:domain] >= 0
    # duplicates: two build rows claimed the same slot -> counts > 1
    counts = jnp.zeros((domain + 1,), jnp.int32).at[idx].add(
        jnp.where(in_dom, 1, 0))
    dup = jnp.any(counts[:domain] > 1)
    # out-of-domain LIVE build keys cannot be represented -> overflow
    # (executor retries; the planner widens the domain from fresh stats)
    overflow = jnp.any(strict & ~in_dom)
    return DirectTable(
        slot_row=slot_row[:domain], used=used, overflow=overflow, dup=dup,
        size=domain)


def probe_direct(table: DirectTable, key: KeySpec, sel, lo: int):
    """-> (matched, build_row) — one gather, no walk, no key re-compare
    (slot index IS the key)."""
    v = key.values.astype(jnp.int64) - jnp.int64(lo)
    strict = sel
    if key.valid is not None:
        strict = strict & key.valid
    in_dom = strict & (v >= 0) & (v < table.size)
    idx = jnp.where(in_dom, v, 0).astype(jnp.int64)
    row = table.slot_row[idx]
    matched = in_dom & (row >= 0)
    return matched, jnp.where(matched, row, 0)
