"""Vectorized hash join — nodeHashjoin.c reimagined for static shapes.

Build side inserts into the same exact-key slot table as ops/agg.py; probe
side walks the identical probe sequence and matches by exact key equality.
Output keeps the probe side's capacity: each probe row gains a ``matched``
flag and a gathered build-row index, so inner/left/semi/anti joins are all
selection-mask updates plus gathers — no dynamic-size compaction.

Duplicate build keys resolve to the same slot; the winner's row index is
stored and every non-winner build row reports ``dup`` (duplicate flag). The
planner only routes unique-key builds here (PK-FK joins, the dominant case);
duplicate builds use broadcast nested-loop fallback until a multi-match
kernel lands. Unresolved build rows (> num_probes chain) raise ``overflow``
for the executor's table-size retry tier.

SQL NULL semantics: a NULL join key equals nothing, so NULL-keyed rows on
either side simply never match (unlike GROUP BY's null-merging equality).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from greengage_tpu.ops import hashing
from greengage_tpu.ops.agg import BIG, KeySpec
from greengage_tpu.ops.agg import probe_sequence as agg_probe_sequence


@dataclass
class BuildTable:
    slot_keys: list[jnp.ndarray]
    slot_key_valids: list[jnp.ndarray | None]
    slot_row: jnp.ndarray      # build row index per slot
    used: jnp.ndarray
    overflow: jnp.ndarray      # bool scalar
    dup: jnp.ndarray           # bool scalar: build had duplicate keys
    size: int


def _key_hash(keys: list[KeySpec]):
    return hashing.row_hash(
        [hashing.column_hash(k.values, k.valid, k.type, text_lut=k.hash_lut) for k in keys]
    )


def _strict_eq(a, av, b, bv):
    """Join equality: NULL matches nothing."""
    eq = a == b
    if av is not None:
        eq = eq & av
    if bv is not None:
        eq = eq & bv
    return eq


def _claim(keys: list[KeySpec], sel, table_size: int, num_probes: int):
    """Shared open-addressing claim/resolve loop (build side).

    A ``lax.while_loop`` with a dynamic trip count: iterations run only as
    deep as the worst probe chain actually is (typically 2-4 at load 1/3),
    not a statically unrolled worst case — on TPU every round costs
    full-batch gathers/scatters, and unrolled rounds also bloat XLA compile
    time. ``num_probes`` is the chain-length BOUND; rows still active at
    the bound raise ``overflow`` for the executor's table-size retry tier.

    -> (tkeys, slot_row, used, overflow, dup, final_slot, strict): every
    strictly-selected build row resolves to the slot holding its key;
    final_slot == table_size marks dead/unresolved rows."""
    from jax import lax

    M = table_size
    assert M & (M - 1) == 0
    n = sel.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)
    strict = sel
    for k in keys:
        if k.valid is not None:
            strict = strict & k.valid   # NULL keys never participate
    h = _key_hash(keys)
    slot0, step = agg_probe_sequence(h, M)
    kvals = tuple(k.values for k in keys)

    def cond(st):
        return jnp.any(st[1]) & (st[7] < num_probes)

    def body(st):
        slot, active, used, slot_row, tkeys, final_slot, dup, i = st
        bids = jnp.full((M,), BIG, dtype=jnp.int32).at[slot].min(
            jnp.where(active, row_idx, BIG)
        )
        newly = (~used) & (bids < BIG)
        winner = jnp.clip(bids, 0, n - 1)
        tkeys = tuple(jnp.where(newly, kv[winner], tk)
                      for kv, tk in zip(kvals, tkeys))
        slot_row = jnp.where(newly, winner, slot_row)
        used = used | newly
        match = active & used[slot]
        for kv, tk in zip(kvals, tkeys):
            match = match & (kv == tk[slot])
        # a row matching a slot stored for a *different* row = duplicate key
        dup = dup | jnp.any(match & (slot_row[slot] != row_idx))
        final_slot = jnp.where(match, slot, final_slot)
        active = active & ~match
        slot = (slot + step) & (M - 1)
        return (slot, active, used, slot_row, tkeys, final_slot, dup, i + 1)

    init = (slot0, strict, jnp.zeros((M,), bool), jnp.zeros((M,), jnp.int32),
            tuple(jnp.zeros((M,), dtype=k.values.dtype) for k in keys),
            jnp.full((n,), M, jnp.int32), jnp.zeros((), bool), jnp.int32(0))
    _, active, used, slot_row, tkeys, final_slot, dup, _ = lax.while_loop(
        cond, body, init)
    return list(tkeys), slot_row, used, jnp.any(active), dup, final_slot, strict


def _walk(used, slot_keys, M, keys: list[KeySpec], sel, num_probes: int):
    """Shared probe walk (dynamic-trip while_loop, see _claim).

    Termination: a probe row stops at its key's slot (hit) or at an empty
    slot (key absent from the build). -> (matched, slot_of) per row."""
    from jax import lax

    strict = sel
    for k in keys:
        if k.valid is not None:
            strict = strict & k.valid
    h = _key_hash(keys)
    slot0, step = agg_probe_sequence(h, M)
    kvals = tuple(k.values for k in keys)
    skeys = tuple(slot_keys)

    def cond(st):
        return jnp.any(st[1]) & (st[4] < num_probes)

    def body(st):
        slot, active, matched, slot_of, i = st
        occupied = used[slot]
        hit = active & occupied
        for kv, tk in zip(kvals, skeys):
            hit = hit & (kv == tk[slot])
        matched = matched | hit
        slot_of = jnp.where(hit, slot, slot_of)
        # stop on hit OR on an empty slot (absent key)
        active = active & ~hit & occupied
        slot = (slot + step) & (M - 1)
        return (slot, active, matched, slot_of, i + 1)

    init = (slot0, strict, jnp.zeros_like(sel),
            jnp.zeros(sel.shape, jnp.int32), jnp.int32(0))
    _, _, matched, slot_of, _ = lax.while_loop(cond, body, init)
    return matched, slot_of


def build(keys: list[KeySpec], sel, table_size: int, num_probes: int) -> BuildTable:
    tkeys, slot_row, used, overflow, dup, _, _ = _claim(keys, sel, table_size, num_probes)
    return BuildTable(
        slot_keys=tkeys,
        slot_key_valids=[None] * len(keys),
        slot_row=slot_row,
        used=used,
        overflow=overflow,
        dup=dup,
        size=table_size,
    )


def probe(table: BuildTable, keys: list[KeySpec], sel, num_probes: int):
    """-> (matched bool[n], build_row int32[n]) over the probe batch."""
    matched, slot_of = _walk(table.used, table.slot_keys, table.size, keys, sel,
                             num_probes)
    return matched, jnp.where(matched, table.slot_row[slot_of], 0)


# ---------------------------------------------------------------------------
# Multi-match join: duplicate build keys via CSR expansion
#
# Build groups rows by key into the slot table (winner row stored), then
# lays all build rows out in slot order (CSR): counts[slot], starts[slot],
# rows_sorted[]. Probe rows find their slot (exact key match), read the
# match count, and the output expands via prefix sums + searchsorted —
# output row j maps to (probe_row[j], build_row[j]). Static output capacity
# with an overflow flag feeds the executor's tier retry, standing in for
# nodeHashjoin's dynamic batching (reference: src/backend/executor/
# nodeHashjoin.c) under XLA's static shapes.
# ---------------------------------------------------------------------------


@dataclass
class MultiTable:
    base: BuildTable
    counts: jnp.ndarray        # matches per slot [M]
    starts: jnp.ndarray        # CSR offsets [M]
    rows_sorted: jnp.ndarray   # build row indices grouped by slot [n_build]


def build_multi(keys: list[KeySpec], sel, table_size: int, num_probes: int) -> MultiTable:
    M = table_size
    tkeys, slot_row, used, overflow, dup, final_slot, strict = _claim(
        keys, sel, M, num_probes)
    counts = jnp.zeros((M + 1,), dtype=jnp.int32).at[final_slot].add(
        jnp.where(strict, 1, 0))[:M]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    order = jnp.argsort(final_slot, stable=True).astype(jnp.int32)
    base = BuildTable(tkeys, [None] * len(keys), slot_row, used, overflow,
                      dup, M)
    return MultiTable(base, counts, starts, order)


def probe_multi(table: MultiTable, keys: list[KeySpec], sel, num_probes: int,
                out_cap: int, left_outer: bool = False):
    """-> (present[K], probe_row[K], build_row[K], matched[K], overflow,
    total) where total is the exact output cardinality — the executor uses
    it to size the retry capacity when overflow fires.

    left_outer: unmatched probe rows still emit one output row with
    matched=False (NULL-extended build side downstream)."""
    matched, slot_of = _probe_slots(table, keys, sel, num_probes)
    count = jnp.where(matched, table.counts[slot_of], 0)
    if left_outer:
        count = jnp.where(sel & ~matched, 1, count)
    cum = jnp.cumsum(count.astype(jnp.int64))
    total = cum[-1] if count.shape[0] else jnp.int64(0)
    overflow = total > out_cap
    j = jnp.arange(out_cap, dtype=jnp.int64)
    probe_row = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    pr = jnp.clip(probe_row, 0, count.shape[0] - 1)
    prev = jnp.where(pr > 0, cum[pr - 1], 0)
    ordinal = (j - prev).astype(jnp.int32)
    present = j < total
    m_at = matched[pr]
    slot_at = slot_of[pr]
    build_row = table.rows_sorted[
        jnp.clip(table.starts[slot_at] + ordinal, 0, table.rows_sorted.shape[0] - 1)]
    build_row = jnp.where(m_at, build_row, 0)
    return present, pr, build_row, m_at & present, overflow, total


def _probe_slots(table: MultiTable, keys: list[KeySpec], sel, num_probes: int):
    return _walk(table.base.used, table.base.slot_keys, table.base.size, keys,
                 sel, num_probes)


def gather_build_columns(build_cols: dict, build_valids: dict, build_row, matched):
    """Pull build-side columns across to probe-side capacity. Unmatched rows
    get valid=False (supports LEFT OUTER null-extension for free)."""
    out_cols, out_valids = {}, {}
    for name, arr in build_cols.items():
        out_cols[name] = arr[build_row]
        v = build_valids.get(name)
        gv = v[build_row] if v is not None else jnp.ones_like(matched)
        out_valids[name] = gv & matched
    return out_cols, out_valids


# ---------------------------------------------------------------------------
# Direct-addressed join: dense integer build keys (the TPC-H PK-FK case)
#
# When ANALYZE shows the build key's domain [min, max] is comparable to the
# build row count (surrogate/sequence keys: orderkey, custkey, ...), the
# hash table degenerates to a dense array indexed by (key - min): build is
# ONE scatter, probe is ONE gather — measured on v5e, the iterative
# open-addressing build alone costs ~30s at 15M rows while this whole join
# runs in ~2 passes of memory bandwidth. Unique-key builds only (the dup
# flag reports violations for the executor's re-plan).
# ---------------------------------------------------------------------------


def build_direct(key: KeySpec, sel, lo: int, domain: int) -> BuildTable:
    """Dense build table over key values in [lo, lo+domain)."""
    v = key.values.astype(jnp.int64) - jnp.int64(lo)
    strict = sel
    if key.valid is not None:
        strict = strict & key.valid
    in_dom = strict & (v >= 0) & (v < domain)
    idx = jnp.where(in_dom, v, domain).astype(jnp.int64)
    n = sel.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)
    slot_row = jnp.full((domain + 1,), -1, jnp.int32).at[idx].max(
        jnp.where(in_dom, row_idx, -1))
    used = slot_row[:domain] >= 0
    # duplicates: two build rows claimed the same slot -> counts > 1
    counts = jnp.zeros((domain + 1,), jnp.int32).at[idx].add(
        jnp.where(in_dom, 1, 0))
    dup = jnp.any(counts[:domain] > 1)
    # out-of-domain LIVE build keys cannot be represented -> overflow
    # (executor retries; the planner widens the domain from fresh stats)
    overflow = jnp.any(strict & ~in_dom)
    return BuildTable(
        slot_keys=[], slot_key_valids=[], slot_row=slot_row[:domain],
        used=used, overflow=overflow, dup=dup, size=domain)


def probe_direct(table: BuildTable, key: KeySpec, sel, lo: int):
    """-> (matched, build_row) — one gather, no walk, no key re-compare
    (slot index IS the key)."""
    v = key.values.astype(jnp.int64) - jnp.int64(lo)
    strict = sel
    if key.valid is not None:
        strict = strict & key.valid
    in_dom = strict & (v >= 0) & (v < table.size)
    idx = jnp.where(in_dom, v, 0).astype(jnp.int64)
    row = table.slot_row[idx]
    matched = in_dom & (row >= 0)
    return matched, jnp.where(matched, row, 0)
