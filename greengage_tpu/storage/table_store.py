"""TableStore: hash-distributed columnar tables on disk.

Reference parity: the AOCS access method + cdbhash placement + appendonly
writer (src/backend/access/aocs/aocsam.c, src/backend/cdb/cdbhash.c,
appendonlywriter.c). Each table is stored as per-segment, per-column block
files; every INSERT/COPY appends new segment files and publishes them with
one manifest commit (snapshot-isolated, see manifest.py).

Placement spec (must match ops/hashing.py on device):
  col_hash = fmix32-based hash of the 64-bit value (NULL -> 0)
  row_hash = col_hash[0], then combine(acc, col_hash[i]) for the rest
  segment  = row_hash % numsegments     (RANDOM: round-robin; REPLICATED: all)
TEXT columns hash their utf-8 bytes (via the dictionary hash LUT), never the
dictionary code, so placement is stable across dictionary growth.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import uuid
import datetime
import hashlib
import operator
import time as _time

import numpy as np

from greengage_tpu import types as T
from greengage_tpu.catalog import Catalog, PolicyKind, TableSchema
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage import native
from greengage_tpu.storage.blockcache import MISS, CacheRegistry
from greengage_tpu.storage.blockfile import (fsync_dir, read_column_file,
                                             verify_column_file,
                                             write_column_file)
from greengage_tpu.storage.corruption import CorruptionError
from greengage_tpu.storage.dictionary import Dictionary
from greengage_tpu.storage.manifest import IntentConflict, Manifest


class _RawChunk:
    """One segment's raw TEXT column: per-row END offsets + validity, with
    the byte blob loaded LAZILY — scans/ANALYZE only need offsets/validity
    (small files); predicates and projections pull the blob on demand.

    ``blob_paths`` are manifest relpaths when ``reader`` is given (the
    store's checked, self-healing read), else filesystem paths."""

    def __init__(self, ends: np.ndarray, valid: np.ndarray | None,
                 blob_paths: list[str], reader=None):
        self.ends = ends
        self.valid = valid
        self._blob_paths = blob_paths
        self._reader = reader
        self._strs: list[str] | None = None

    def __len__(self):
        return len(self.ends)

    def blob(self) -> np.ndarray:
        """Concatenated utf-8 byte blob across this segment's files."""
        read = self._reader or read_column_file
        blobs = [read(p).astype(np.uint8) for p in self._blob_paths]
        return np.concatenate(blobs) if blobs else np.zeros(0, np.uint8)

    def strings(self) -> list[str]:
        if self._strs is None:
            b = self.blob().tobytes()
            starts = np.concatenate([np.zeros(1, np.int64), self.ends[:-1]]) \
                if len(self.ends) else np.zeros(0, np.int64)
            self._strs = [b[s:e].decode("utf-8")
                          for s, e in zip(starts, self.ends)]
        return self._strs


def merge_segfile_records(tx: dict, table: str, records: list) -> None:
    """Merge staged-file records [(seg, [rel files], nrows)] into a
    manifest transaction (idempotent re-apply for optimistic write retry)."""
    tmeta = tx["tables"].setdefault(table, {"segfiles": {}, "nrows": {}})
    for seg, rels, n in records:
        tmeta["segfiles"].setdefault(str(seg), []).extend(rels)
        tmeta["nrows"][str(seg)] = tmeta["nrows"].get(str(seg), 0) + n


_MIRROR_MAP_CACHE: dict = {}   # root -> (mtime, {content: dir})
# read-path self-heal resolves mirror roots from staging-pool threads
# while FTS promotion re-reads the operator map (gg check races)
_mirror_map_mu = threading.Lock()


def mirror_root(root: str, content: int) -> str:
    """Directory tree holding content ``content``'s replicated files (the
    mirror segment's data directory). Default: <root>/mirror/content<k>.
    An operator-placed ``<root>/mirror_roots.json`` overrides per content
    with ABSOLUTE paths on other disks/hosts (`gg mirrorroots --roots`) —
    the cross-host spread placement of gpaddmirrors/gpinitsystem, so a
    lost data disk cannot take a content's primary AND mirror together
    (gp_segment_configuration hostname/address separation)."""
    mp = os.path.join(root, "mirror_roots.json")
    try:
        mtime = os.stat(mp).st_mtime_ns
        with _mirror_map_mu:
            cached = _MIRROR_MAP_CACHE.get(root)
            if cached is None or cached[0] != mtime:
                with open(mp) as f:
                    cached = _MIRROR_MAP_CACHE[root] = (mtime, json.load(f))
        override = cached[1].get(str(content))
        if override:
            return os.path.join(override, f"content{content}")
    except OSError:
        with _mirror_map_mu:
            _MIRROR_MAP_CACHE.pop(root, None)
    except ValueError:
        # malformed operator edit: fall back to the default placement
        # rather than taking down every mirror-maintenance path
        with _mirror_map_mu:
            _MIRROR_MAP_CACHE.pop(root, None)
    return os.path.join(root, "mirror", f"content{content}")


# fixed-width device prefix for raw TEXT predicates (raw_prefix below):
# enough for TPC-H comment/name prefixes; longer literals fall back to the
# host path
RAW_PREFIX_BYTES = 32
RAW_PREFIX_WORDS = RAW_PREFIX_BYTES // 8
# wide byte window for GENERAL device LIKE (contains/suffix/multi-part):
# covers every TPC-H comment-class column; columns with longer rows fall
# back to the host path (decidability needs the whole string on device)
RAW_WIDE_BYTES = 128
RAW_WIDE_WORDS = RAW_WIDE_BYTES // 8


def _as_i64(arr: np.ndarray) -> np.ndarray:
    """Reinterpret a column's device dtype as int64 for hashing.

    float64 keys are canonicalized first (-0.0 -> 0.0, all NaNs -> one NaN
    bit pattern) so SQL-equal values co-locate — the hashfloat8 parity rule.
    """
    if arr.dtype == np.float64:
        arr = np.where(arr == 0.0, 0.0, arr)
        arr = np.where(np.isnan(arr), np.nan, arr)
        return arr.view(np.int64)
    return arr.astype(np.int64)


class TableStore:
    def __init__(self, root: str, catalog: Catalog):
        self.root = root
        self.catalog = catalog
        self.manifest = Manifest(root)
        # wired by the session after construction: the settings registry
        # (storage_autorepair) and the cluster logger (repair/quarantine
        # events); both optional so bare TableStore use keeps defaults
        self.settings = None
        self.log = None
        self._dicts: dict[tuple[str, str], Dictionary] = {}
        # in-memory dictionaries for string-function results over
        # dictionary columns (("@expr", sha) refs); deterministic content
        # hash keys them so concurrent binders and multihost lockstep
        # binding agree without persistence
        self._derived: dict[tuple[str, str], Dictionary] = {}
        # every read-path cache lives in ONE byte-accounted LRU registry
        # (storage/blockcache.py, the bufmgr analog): shared budget
        # (scan_cache_limit_mb), global recency eviction, manifest-version
        # invalidation, hit/miss/evict counters. Thread-safe — the
        # executor's staging pool reads through these concurrently.
        self.blockcache = CacheRegistry()
        # decoded block files: (table, rel, block_indices|None) -> ndarray
        self._block_cache = self.blockcache.cache("blocks")
        # parsed + verified footers: (table, rel) -> footer dict
        self._footer_cache = self.blockcache.cache("footers")
        self._raw_cache = self.blockcache.cache("raw")
        # (table, col, seg, version) -> RawChunk
        self._hp_cache = self.blockcache.cache("hostpred")
        # (table, seg, name, version) -> result
        # transient per-version dictionaries over raw columns (group/sort/
        # join keys on raw TEXT): ref registry + per-segment code arrays
        self._rawdict_refs: dict = {}   # (table, col, version) -> ref
        self._rawcode_cache = self.blockcache.cache("rawcode")
        # (storage, seg, col, version) -> (codes, valid)
        # deletion-bitmap keep masks (visimap analog): (table, seg, version)
        # -> bool[manifest nrows] keep mask, or None when nothing deleted
        self._delmask_cache = self.blockcache.cache("delmask")
        # packed fixed-width prefixes of raw TEXT columns for DEVICE
        # predicates: (table, col, seg, version) -> (words[n,K] int64,
        # lengths[n] int32)
        self._rawprefix_cache = self.blockcache.cache("rawprefix")
        # dictionary load/build serialization: concurrent staging threads
        # must agree on ONE code space (raw_dictionary assigns first-seen
        # codes; two racing builders would mint divergent codes)
        self._dict_lock = threading.RLock()
        # read-path self-heal under concurrency: per-(table, rel) repair
        # locks + a repair generation, so parallel readers tripping the
        # same bad file repair-or-quarantine it exactly once
        self._repair_mu = threading.Lock()
        self._repair_locks: dict = {}
        self._repair_gen: dict = {}
        self._tl = threading.local()   # per-thread last_prune

    # ---- per-content data roots (mirror failover) ----------------------
    def data_root(self, content: int) -> str:
        """Directory holding content ``content``'s segment files. Normally
        <root>/data; while a promoted mirror is acting primary for this
        content, its mirror tree — so every read AND write lands on the
        surviving copy after failover (runtime/replication.py)."""
        segs = getattr(self.catalog, "segments", None)
        if segs is not None:
            acting = segs.acting_primary(content)
            if acting is not None and acting.preferred_role.value == "m":
                return mirror_root(self.root, content)
        return os.path.join(self.root, "data")

    @staticmethod
    def rel_content(rel: str) -> int:
        """Content id encoded in a manifest relpath ('seg<k>/<file>')."""
        return int(rel.split(os.sep, 1)[0][3:])

    def seg_file_path(self, table: str, rel: str) -> str:
        """rel is 'seg<k>/<file>' as stored in the manifest."""
        return os.path.join(self.data_root(self.rel_content(rel)), table, rel)

    def storage_ok(self, content: int) -> bool:
        """Every manifest-referenced file of this content is present on its
        acting root (the FTS storage-health probe). Quarantine RENAMES bad
        files out of the tree, so an unrepairable corruption fails this
        probe and FTS failover takes over."""
        snap = self.manifest.snapshot()
        root = self.data_root(content)
        for tname, tmeta in snap.get("tables", {}).items():
            for rel in tmeta.get("segfiles", {}).get(str(content), []):
                if not os.path.exists(os.path.join(root, tname, rel)):
                    return False
        return True

    # ---- corruption handling: self-heal, quarantine, checked reads -----
    # The storage-side twin of gang recovery (docs/ROBUSTNESS.md): committed
    # block files are immutable and (with mirrors) exist twice, so a read
    # that trips a frame/footer checksum repairs from the IN-SYNC standby
    # tree and retries ONCE; a file with no healthy copy is renamed into
    # <root>/.quarantine/ with a JSON sidecar, which fails storage_ok and
    # hands the content to FTS failover. Reference: AO block checksums +
    # gprecoverseg full recovery (cdbappendonlystorageformat.c).

    def _log_event(self, severity: str, message: str) -> None:
        log = getattr(self, "log", None)
        if log is not None:
            try:
                log.log(severity, "storage", message)
            except Exception:
                pass   # observability must never fail the read

    def standby_root(self, content: int) -> str | None:
        """The tree holding the OTHER copy of this content's files (mirror
        tree while the preferred primary acts; data tree after failover).
        None when the content has no mirror pair."""
        segs = getattr(self.catalog, "segments", None)
        if segs is None:
            return None
        from greengage_tpu.catalog.segments import SegmentRole

        try:
            segs.entry(content, SegmentRole.MIRROR)
        except KeyError:
            return None
        data = os.path.join(self.root, "data")
        if os.path.normpath(self.data_root(content)) == os.path.normpath(data):
            return mirror_root(self.root, content)
        return data

    def repair_file(self, table: str, content: int, rel: str,
                    path: str) -> bool:
        """Copy ``rel`` from the in-sync standby tree over the bad acting
        copy (fsynced), then re-verify EVERY frame of the repaired file.
        False when no trustworthy standby copy exists (no mirror, stale
        sync marker, or the file is absent there); raises CorruptionError
        when the standby copy is itself corrupt."""
        from greengage_tpu.runtime.replication import copy_durable, tree_version

        standby = self.standby_root(content)
        if standby is None:
            return False
        if tree_version(standby, content) != self.manifest.snapshot().get(
                "version", 0):
            return False   # stale standby: copying could resurrect old data
        src = os.path.join(standby, table, rel)
        if not os.path.exists(src):
            return False
        faults.check("repair_copy", segment=content)
        # inject=False: repair judges the REAL bytes of both copies — an
        # armed read-time fault must not condemn healthy files
        verify_column_file(src, inject=False)   # corrupt standby raises
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # tmp is repairer-unique: concurrent readers racing the same bad
        # file must not interleave writes into one tmp (each atomic
        # replace then publishes a complete, re-verified copy)
        copy_durable(src, path, tmp=f"{path}.repair.{uuid.uuid4().hex[:8]}")
        verify_column_file(path, inject=False)  # repaired copy must be clean
        self._drop_bidx(path)     # sidecar may index the bad bytes
        return True

    def _drop_bidx(self, path: str) -> None:
        if path.endswith(".ggb"):
            try:
                os.remove(path[: -len(".ggb")] + ".bidx.npz")
            except OSError:
                pass

    def quarantine_file(self, path: str, err: CorruptionError) -> str | None:
        """Rename a bad file into <root>/.quarantine/ with a JSON sidecar
        recording the cause — preserved for forensics, and its absence
        fails storage_ok so FTS can fail the segment over."""

        qdir = os.path.join(self.root, ".quarantine")
        os.makedirs(qdir, exist_ok=True)
        qname = f"{uuid.uuid4().hex[:8]}.{os.path.basename(path)}"
        qpath: str | None = os.path.join(qdir, qname)
        try:
            os.replace(path, qpath)
        except OSError:
            try:
                shutil.move(path, qpath)   # mirror roots may be other disks
            except OSError:
                qpath = None   # cannot move (already gone?): sidecar only
        self._drop_bidx(path)
        sidecar = dict(err.to_dict(),
                       quarantined_from=path, quarantined_to=qpath,
                       time=datetime.datetime.now(datetime.timezone.utc)
                       .isoformat(timespec="seconds"))
        try:
            with open(os.path.join(qdir, qname + ".json"), "w") as f:
                json.dump(sidecar, f, indent=1)
        except OSError:
            pass
        counters.inc("storage_quarantine")
        self._log_event("ERROR",
                        f"quarantined {path} -> {qpath}: {err.cause} "
                        f"({err.message})")
        return qpath

    def handle_corruption(self, table: str, content: int, rel: str,
                          path: str, err: CorruptionError) -> None:
        """Decide repair vs quarantine for one located corruption. Returns
        after a verified repair; otherwise quarantines the acting file
        (and a corrupt standby copy, so nothing ever trusts it) and
        re-raises the typed error."""
        settings = getattr(self, "settings", None)
        autorepair = settings is None or getattr(settings,
                                                 "storage_autorepair", True)
        if autorepair:
            try:
                if self.repair_file(table, content, rel, path):
                    counters.inc("storage_repair")
                    self._mark_rel_changed(table, rel)
                    self._log_event(
                        "WARNING",
                        f"repaired {table}/{rel} (content {content}) from "
                        f"standby tree after {err.cause}")
                    return
            except FaultError:
                pass   # injected repair_copy failure: fall through
            except CorruptionError as e2:   # before OSError: its subclass
                # both copies corrupt: quarantine the standby copy too so
                # rebuild/promotion never trusts it (unless the failure
                # was the post-repair re-verify of the ACTING file, which
                # the fall-through below already quarantines once)
                spath = getattr(e2, "path", None)
                if spath and spath != path and os.path.exists(spath):
                    self.quarantine_file(
                        spath, e2.locate(table=table, content=content,
                                         relpath=rel))
            except OSError:
                # EIO/ENOSPC mid-copy or mid-verify: a failed repair, not
                # a new error class — the detected-bad file must still
                # quarantine (and fail storage_ok) below
                pass
        if err.cause != "missing":
            self.quarantine_file(path, err)
            self._mark_rel_changed(table, rel)
        raise err

    # -- repair concurrency helpers --------------------------------------
    def _repair_lock_for(self, table: str, rel: str) -> threading.Lock:
        with self._repair_mu:
            lk = self._repair_locks.get((table, rel))
            if lk is None:
                lk = self._repair_locks[(table, rel)] = threading.Lock()
            return lk

    def _mark_rel_changed(self, table: str, rel: str) -> None:
        """A repair or quarantine replaced/removed this rel's bytes: bump
        the repair generation (waiting readers re-judge the NEW bytes
        instead of acting on a stale failure) and drop cached blocks."""
        with self._repair_mu:
            self._repair_gen[(table, rel)] = \
                self._repair_gen.get((table, rel), 0) + 1
        self._block_cache.drop(lambda k: k[0] == table and k[1] == rel)
        self._footer_cache.pop((table, rel), None)

    def _read_checked(self, table: str, rel: str, reader):
        """Run ``reader(path)`` with read-path self-heal: corruption (or a
        vanished manifest-referenced file) repairs from the standby tree
        and retries ONCE; unrepairable damage quarantines and raises.

        Concurrency contract (the staging thread pool reads through this):
        parallel readers tripping the same bad file serialize on a per-rel
        lock and repair-or-quarantine EXACTLY once — a reader that waited
        out another thread's repair re-reads the healed bytes instead of
        double-repairing, and one that waited out a quarantine surfaces
        'missing' instead of double-quarantining."""
        content = self.rel_content(rel)
        path = self.seg_file_path(table, rel)
        with self._repair_mu:
            gen0 = self._repair_gen.get((table, rel), 0)
        try:
            return reader(path, content)
        except FileNotFoundError:
            err = CorruptionError(
                "missing", "manifest-referenced file is missing", path=path)
        except CorruptionError as e:
            err = e
        with self._repair_lock_for(table, rel):
            with self._repair_mu:
                changed = self._repair_gen.get((table, rel), 0) != gen0
            if changed:
                # another thread already repaired (or quarantined) this
                # file while we waited: judge the CURRENT bytes
                try:
                    return reader(path, content)
                except FileNotFoundError:
                    err = CorruptionError(
                        "missing", "manifest-referenced file is missing",
                        path=path)
                except CorruptionError as e:
                    err = e
            err.locate(table=table, content=content, relpath=rel)
            self.handle_corruption(table, content, rel, path, err)
            return reader(path, content)

    def read_file(self, table: str, rel: str,
                  block_indices: list[int] | None = None,
                  out: np.ndarray | None = None) -> np.ndarray:
        """Checked read of one manifest-referenced block file, served from
        the byte-accounted block cache when resident (committed block
        files are immutable; repair/quarantine invalidates explicitly).
        Cache misses count scan_files_read / scan_bytes_decoded.

        ``out``: optional preallocated destination (a staging-buffer slot)
        the frames decode straight into on a miss — the cached value is
        then a view of it, and the caller skips its own copy. Cache hits
        ignore ``out`` (the caller copies from the returned array)."""
        key = (table, rel,
               None if block_indices is None else tuple(block_indices))
        hit = self._block_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        arr = self._read_checked(
            table, rel,
            lambda p, c: read_column_file(p, block_indices, segment=c,
                                          out=out))
        counters.inc("scan_files_read")
        counters.inc("scan_bytes_decoded", int(arr.nbytes))
        # a dest-decoded result is a VIEW of the caller's staging buffer,
        # whose memory stays pinned until the buffer's LAST view evicts:
        # charge the full padded slot we were handed (the per-segment
        # views of one buffer then sum to its true footprint), never just
        # the view's own rows
        nb = arr.nbytes
        if out is not None and getattr(arr, "base", None) is not None:
            nb = max(nb, out.nbytes)
        self._block_cache.put(key, arr, nbytes=nb)
        return arr

    def read_footer_checked(self, table: str, rel: str) -> dict:
        from greengage_tpu.storage.blockfile import read_footer

        hit = self._footer_cache.get((table, rel), MISS)
        if hit is not MISS:
            return hit
        footer = self._read_checked(table, rel, lambda p, c: read_footer(p))
        self._footer_cache.put((table, rel), footer, nbytes=512)
        return footer

    # ---- dictionaries --------------------------------------------------
    def dictionary(self, table: str, col: str) -> Dictionary:
        if table == "@rawdict":
            # transient raw-TEXT dicts are bounded-evicted; a cached plan
            # may still hold an evicted ref — rebuild from the key, which
            # encodes parent:col:version (exactly raw_dictionary's
            # inputs). Probe and fetch under _dict_lock: raw_dictionary's
            # >16 transient bound evicts CONCURRENTLY from staging-pool
            # threads, and an unlocked membership test could pass right
            # before the eviction lands (gg check races).
            with self._dict_lock:
                hit = self._derived.get((table, col))
            if hit is None:
                parent, rcol, ver = col.rsplit(":", 2)
                snap = self.manifest.snapshot()
                if snap.get("version", 0) != int(ver):
                    raise KeyError(
                        f"raw dictionary {col} evicted and manifest moved to "
                        f"v{snap.get('version', 0)}; plan cache is stale")
                self.raw_dictionary(parent, rcol, snap)
                with self._dict_lock:
                    hit = self._derived[(table, col)]
            return hit
        if table == "@expr":
            with self._dict_lock:
                return self._derived[(table, col)]
        # partition children share the PARENT's dictionary: one code space
        # per logical table, so codes compare/join across partitions
        table = table.split("#", 1)[0]
        key = (table, col)
        # unlocked fast-path probe, double-checked under the lock below:
        # a persisted dict is immutable once loaded and evicted only by
        # DROP/recreate DDL (_invalidate_dicts), so a hit is always a
        # valid value for any scan that began before the drop, and a
        # stale miss only costs the locked re-probe — the per-scan hot
        # path skips the mutex
        d = self._dicts.get(key)   # gg:ok(races)
        if d is None:
            with self._dict_lock:   # one load per dict under parallel staging
                d = self._dicts.get(key)
                if d is None:
                    d = self._dicts[key] = Dictionary.load(
                        self._dict_path(table, col))
        return d

    def derived_dictionary(self, values: list[str]) -> tuple[str, str]:
        """Register (or reuse) an in-memory dictionary for a string-function
        result; -> ("@expr", sha1) ref usable wherever a (table, col)
        dict_ref is (hash LUTs, sort ranks, result decode)."""

        h = hashlib.sha1("\x00".join(values).encode()).hexdigest()[:16]
        ref = ("@expr", h)
        with self._dict_lock:
            if ref not in self._derived:
                self._derived[ref] = Dictionary(list(values))
        return ref

    def raw_dictionary(self, table: str, col: str, snapshot=None) -> tuple:
        """Transient dictionary over a raw TEXT column's live strings —
        one first-seen code space across all segments (and partition
        children), cached per manifest version. Lets raw columns flow
        through every dictionary-based path (GROUP BY hashing, sort rank
        LUTs, join translation, result decode) at O(rows) host cost,
        without persisting a dictionary that high-NDV data would bloat.
        -> ("@rawdict", key) usable as a dict_ref."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        parent = table.split("#", 1)[0]
        key = (parent, col, version)
        with self._dict_lock:
            # serialized: two staging threads racing this build would mint
            # DIVERGENT first-seen code spaces for the same column
            hit = self._rawdict_refs.get(key)
            if hit is not None:
                return hit
            schema = self.catalog.get(parent)
            d = Dictionary()
            nseg = schema.policy.numsegments
            for storage in schema.storage_tables():
                for seg in range(nseg):
                    chunk = self.raw_chunk(storage, seg, col, snap)
                    codes = d.encode(chunk.strings())
                    self._rawcode_cache.put(
                        (storage, seg, col, version),
                        (codes.astype(np.int32), chunk.valid),
                        version=version)
            ref = ("@rawdict", f"{parent}:{col}:{version}")
            self._derived[ref] = d
            self._rawdict_refs[key] = ref
            if len(self._rawdict_refs) > 16:   # bound transient memory
                old_key = next(iter(self._rawdict_refs))  # (parent, col, ver)
                old_ref = self._rawdict_refs.pop(old_key)
                self._derived.pop(old_ref, None)
                self._rawcode_cache.drop(
                    lambda k: k[0].split("#", 1)[0] == old_key[0]
                    and k[2] == old_key[1] and k[3] == old_key[2])
            return ref

    def raw_codes(self, table: str, seg: int, col: str, snapshot=None):
        """-> (int32 codes, valid|None) for one segment of a raw column
        under the transient dictionary (staged as an '@rc:' column)."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = (table, seg, col, version)
        hit = self._rawcode_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        ref = self.raw_dictionary(table, col, snap)
        hit = self._rawcode_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        # the code entry was byte-evicted while its dictionary survived:
        # re-encode just this segment (every string already has a code, so
        # encode() cannot grow the dictionary here)
        with self._dict_lock:
            d = self._derived.get(ref)
            if d is None:
                # the >16 transient-dict bound evicted OUR ref between
                # raw_dictionary() returning and this lock: rebuild (the
                # registry miss makes raw_dictionary re-encode every
                # segment, repopulating the code cache too)
                self._rawdict_refs.pop(
                    (table.split("#", 1)[0], col, version), None)
                ref = self.raw_dictionary(table, col, snap)
                d = self._derived[ref]
            chunk = self.raw_chunk(table, seg, col, snap)
            res = (d.encode(chunk.strings()).astype(np.int32), chunk.valid)
            self._rawcode_cache.put(key, res, version=version)
            return res

    def _dict_path(self, table: str, col: str) -> str:
        table = table.split("#", 1)[0]
        return os.path.join(self.root, "data", table, f"dict_{col}.json")

    # ---- placement -----------------------------------------------------
    def row_hashes(self, schema: TableSchema, cols: dict[str, np.ndarray],
                   valids: dict[str, np.ndarray | None], keys: tuple[str, ...]) -> np.ndarray:
        acc = None
        for k in keys:
            c = schema.column(k)
            arr = cols[k]
            if c.type.kind is T.Kind.TEXT:
                lut = self.dictionary(schema.name, k).hashes()
                h = lut[arr] if len(lut) else np.zeros(len(arr), dtype=np.uint32)
            else:
                h = native.hash_i64(_as_i64(arr))
            v = valids.get(k)
            if v is not None:
                h = np.where(v, h, np.uint32(0))
            acc = h if acc is None else native.hash_combine(acc, h)
        return acc

    def segment_for_values(self, schema: TableSchema, values: dict) -> int:
        """The one segment owning rows whose distribution keys equal
        ``values`` (storage representation: TEXT = dictionary code, absent
        string = -1 which hits the sentinel hash row). Direct-dispatch's
        hash computation (cdbtargeteddispatch.c analog), bit-identical to
        placement."""
        cols = {}
        valids = {}
        for k in schema.policy.keys:
            v = values[k]
            c = schema.column(k)
            if v is None:
                cols[k] = np.zeros(1, dtype=np.int64)
                valids[k] = np.zeros(1, dtype=bool)
            elif c.type.kind is T.Kind.TEXT:
                cols[k] = np.array([v], dtype=np.int32)
            else:
                cols[k] = np.array([v], dtype=c.type.np_dtype)
        rh = self.row_hashes(schema, cols, valids, schema.policy.keys)
        return int(rh[0] % np.uint32(schema.policy.numsegments))

    def _placement(self, schema: TableSchema, cols, valids, nrows: int, row_offset: int) -> np.ndarray:
        pol = schema.policy
        nseg = pol.numsegments
        if pol.kind is PolicyKind.HASH:
            rh = self.row_hashes(schema, cols, valids, pol.keys)
            return (rh % np.uint32(nseg)).astype(np.int32)
        if pol.kind is PolicyKind.RANDOM:
            return ((np.arange(nrows, dtype=np.int64) + row_offset) % nseg).astype(np.int32)
        raise AssertionError("REPLICATED handled by caller")

    # ---- write path ----------------------------------------------------
    def insert(self, table: str, columns: dict[str, list | np.ndarray],
               valids: dict[str, np.ndarray] | None = None, tx: dict | None = None,
               stream_marks: dict[str, int] | None = None) -> int:
        """Append rows; returns row count. Encodes TEXT, places rows onto
        segments, writes per-segment column files, commits the manifest
        (or stages into an open tx for DTM-lite two-phase commit).
        ``stream_marks`` ({stream_id: batch_seq}) rides an ingest
        micro-batch's commit record as the stream's durable resume
        watermark (forces the write-intent path)."""
        schema = self.catalog.get(table)
        valids = dict(valids or {})
        for c in schema.columns:
            v = valids.get(c.name)
            if not c.nullable and v is not None and not np.all(v):
                raise ValueError(
                    f'null value in column "{c.name}" violates not-null constraint')
        nrows = None
        enc: dict[str, np.ndarray] = {}
        raw_strs: dict[str, np.ndarray] = {}   # raw-encoded TEXT columns
        dict_sizes = {c.name: len(self.dictionary(table, c.name))
                      for c in schema.columns
                      if c.type.kind is T.Kind.TEXT and c.encoding != "raw"}
        for c in schema.columns:
            if c.name not in columns:
                raise ValueError(f"missing column {c.name}")
            raw = columns[c.name]
            if c.type.kind is T.Kind.TEXT:
                c = self._resolve_text_encoding(schema, c, raw)
                if c.encoding == "raw":
                    vals = (raw.decode() if isinstance(raw, T.Coded)
                            else np.asarray(raw, dtype=object))
                    raw_strs[c.name] = vals
                    # placeholder for ragged checks; never hashed (raw
                    # distribution keys are rejected in _resolve)
                    arr = np.zeros(len(vals), dtype=np.int64)
                    enc[c.name] = arr
                    nrows = len(arr) if nrows is None else nrows
                    if len(arr) != nrows:
                        raise ValueError("ragged insert")
                    continue
                d = self.dictionary(table, c.name)
                vmask = valids.get(c.name)
                if isinstance(raw, T.Coded):
                    arr = d.encode_coded(list(raw.vocab), raw.codes)
                    if vmask is not None:
                        arr = np.where(vmask, arr, d.encode([""])[0])
                elif vmask is None:
                    arr = d.encode(list(raw))
                else:
                    strs = ["" if not ok else s for s, ok in zip(raw, vmask)]
                    arr = d.encode(strs)
            elif c.type.kind is T.Kind.DECIMAL and not isinstance(raw, np.ndarray):
                arr = np.array([T.decimal_to_int(v, c.type.scale) for v in raw], dtype=np.int64)
            elif c.type.kind is T.Kind.DATE and not isinstance(raw, np.ndarray):
                arr = np.array([T.date_to_days(v) for v in raw], dtype=np.int32)
            else:
                arr = np.asarray(raw, dtype=c.type.np_dtype)
            enc[c.name] = arr
            nrows = len(arr) if nrows is None else nrows
            if len(arr) != nrows:
                raise ValueError("ragged insert")

        return self._append_encoded(table, schema, enc, valids, raw_strs,
                                    tx, dict_sizes, stream_marks=stream_marks)

    def _append_encoded(self, table, schema, enc, valids, raw_strs, tx,
                        dict_sizes, stream_marks=None) -> int:
        """Shared append tail of insert()/insert_encoded(): placement,
        segfile write, manifest merge (with the optimistic CAS retry)."""
        nrows = len(next(iter(enc.values()))) if enc else 0
        own_tx = tx is None
        if own_tx:
            tx = self.manifest.begin()
        tmeta = tx["tables"].setdefault(table, {"segfiles": {}, "nrows": {}})
        # tx-unique file id: concurrent writers can never clobber each other's
        # staged files; the losing writer's orphans are unreachable via the
        # manifest (appendonlywriter segfile-concurrency analog).
        fileno = uuid.uuid4().hex[:12]

        nseg = schema.policy.numsegments
        total_existing = sum(tmeta["nrows"].get(str(s), 0) for s in range(nseg))
        if schema.policy.kind is PolicyKind.REPLICATED:
            seg_rows = [np.arange(nrows)] * nseg
        else:
            seg_of = self._placement(schema, enc, valids, nrows, total_existing)
            seg_rows = [np.nonzero(seg_of == s)[0] for s in range(nseg)]

        records = self._write_segfiles(schema, table, tmeta, enc, valids,
                                       seg_rows, fileno, raw_strs=raw_strs)

        if own_tx:
            # Ordering: stage files -> prepare_delta (the PER-TABLE
            # sequence CAS — appenders to different tables never contend)
            # -> persist dictionaries (fsynced; superset-safe) -> commit
            # (one fsynced commit-log line). A concurrent SAME-TABLE CAS
            # conflict RETRIES against the fresh snapshot: the staged
            # files are tx-unique and remain valid, so only the manifest
            # record needs re-merging (the appendonly writer's
            # segfile-concurrency model — writers never block readers and
            # autocommit writers serialize optimistically). Each retry is
            # counted in manifest_cas_retry_total (zero for cross-table
            # workloads by construction).

            from greengage_tpu.runtime.logger import counters as _counters

            # a CROSS-PROCESS retry is only safe when this insert assigned
            # no new dictionary codes: a concurrent writer in another
            # process may have claimed the same codes for different words
            # (in-process writers share Dictionary objects and serialize on
            # the session write lock, so they never hit this)
            dict_grew = any(
                len(self.dictionary(table, n)) != sz
                for n, sz in dict_sizes.items())
            if not dict_grew and (stream_marks is not None
                                  or self._use_write_intents()):
                # WRITE-INTENT fast path (autocommit appends): a txid-named
                # intent + one merge line carrying these records — no
                # per-table claim, so N same-table appenders commit with
                # ZERO retries (manifest_cas_retry_total stays flat by
                # construction). Gated on `not dict_grew`: an insert that
                # assigned new dictionary codes must keep the per-table
                # CAS, whose conflict is the only cross-process signal
                # that another writer may hold the same codes.
                self.flush_dicts(table)
                ihandle = self.manifest.stage_intent(
                    table, records, streams=stream_marks)
                try:
                    self.manifest.commit_intent(ihandle)
                except BaseException:
                    self.manifest.abort_intent(ihandle)
                    raise
                self.maybe_fold_manifest()
                return nrows
            def _fold_stream_marks(tx_):
                # Dictionary growth forces a streamed micro-batch onto
                # the CAS path; the full-state line it stages must still
                # carry the stream's resume watermark — otherwise the
                # rows commit but the durable watermark never advances,
                # and after kill-9 the client resumes from a stale seq
                # and replays already-durable batches (double-apply).
                if not stream_marks:
                    return
                state = tx_["tables"].setdefault(
                    table, {"segfiles": {}, "nrows": {}})
                marks = state.setdefault("streams", {})
                for sid, sq in stream_marks.items():
                    marks[str(sid)] = max(int(marks.get(str(sid), 0)),
                                          int(sq))

            _fold_stream_marks(tx)
            last = None
            for attempt in range(20):
                try:
                    handle = self.manifest.prepare_delta(tx, [table])
                    break
                except RuntimeError as e:
                    last = e
                    if dict_grew:
                        self._invalidate_dicts(table)
                        raise
                    _counters.inc("manifest_cas_retry_total")
                    _time.sleep(0.01 * (attempt + 1))
                    tx = self.manifest.begin()
                    merge_segfile_records(tx, table, records)
                    _fold_stream_marks(tx)
            else:
                self._invalidate_dicts(table)
                raise RuntimeError(
                    f"write-write conflict persisted after retries: {last}")
            self.flush_dicts(table)
            try:
                self.manifest.commit_delta(handle)
            except BaseException:
                self.manifest.abort_delta(handle)
                raise
            self.maybe_fold_manifest()
        else:
            # DTM-managed tx: the caller drives prepare/commit and must call
            # flush_dicts(table) between those phases (see runtime/dtm.py).
            pass
        return nrows

    def _use_write_intents(self) -> bool:
        """GUC gate for the intent append path (write_intents_enabled,
        default on). self.settings is None for bare TableStore uses
        (tools, unit tests) — those default on too."""
        return bool(getattr(self.settings, "write_intents_enabled", True))

    def _resolve_text_encoding(self, schema, col, raw_values):
        """First-insert decision for TEXT encoding="auto": high-NDV columns
        go raw (byte blob + offsets; arbitrary-cardinality strings, the
        varlena analog), low-NDV go dict. Distribution keys are always dict
        (placement hashes string bytes via the dictionary LUT)."""
        if col.encoding != "auto":
            return col
        from greengage_tpu.catalog.schema import Column

        if col.name in schema.policy.keys or isinstance(raw_values, T.Coded):
            mode = "dict"
        else:
            sample = list(raw_values[:100_000])
            mode = ("raw" if len(sample) >= 4096
                    and len(set(sample)) > 0.5 * len(sample) else "dict")
        new = Column(col.name, col.type, col.nullable, mode)
        schema.columns[[c.name for c in schema.columns].index(col.name)] = new
        self.catalog._save()
        return new

    def raw_column_names(self, table: str) -> set:
        return {c.name for c in self.catalog.get(table).columns
                if c.type.kind is T.Kind.TEXT and c.encoding == "raw"}

    def has_raw_columns(self, table: str) -> bool:
        return bool(self.raw_column_names(table))

    def flush_dicts(self, table: str) -> None:
        schema = self.catalog.get(table)
        table = table.split("#", 1)[0]   # children share the parent dict
        for c in schema.columns:
            if c.type.kind is not T.Kind.TEXT:
                continue
            with self._dict_lock:   # loaders insert from staging threads
                d = self._dicts.get((table, c.name))
            if d is not None:
                os.makedirs(os.path.join(self.root, "data", table),
                            exist_ok=True)
                d.save(self._dict_path(table, c.name))

    def _invalidate_dicts(self, table: str) -> None:
        table = table.split("#", 1)[0]
        with self._dict_lock:   # staging threads load dicts concurrently
            for key in [k for k in self._dicts if k[0] == table]:
                del self._dicts[key]

    def _invalidate_dicts_all(self) -> None:
        with self._dict_lock:
            self._dicts.clear()

    # ---- read path -----------------------------------------------------
    @property
    def last_prune(self):
        """(blocks kept, blocks total) of THIS THREAD's last read — the
        staging pool runs read_segment concurrently, so the stat is
        thread-local; each worker reads its own right after its read."""
        return getattr(self._tl, "last_prune", None)

    @last_prune.setter
    def last_prune(self, value) -> None:
        self._tl.last_prune = value

    def block_index(self, base: str, rel: str, table: str | None = None):
        """Per-segfile block-value index (the btree/bitmap AM analog for
        append-only block storage): sorted (value, block) pairs, deduped
        per block, as a rebuildable .bidx.npz sidecar next to the data
        file. An equality probe binary-searches the values and returns
        exactly the blocks containing the key — block-selective scans on
        UNCLUSTERED data, where zone maps (which need clustering) keep
        everything. Low-NDV columns degenerate to few (value, block)
        runs — the bitmap-index shape; high-NDV to a dense sorted list —
        the btree shape. Sidecars are derived data: built lazily, not in
        the manifest, reaped with their data file. ``table`` (the storage
        table owning ``rel``) enables checked self-healing reads."""
        from greengage_tpu.storage.blockfile import (read_column_file,
                                                     read_footer)

        path = os.path.join(base, rel)
        sidecar = path[:-len(".ggb")] + ".bidx.npz"
        try:
            if os.path.getmtime(sidecar) >= os.path.getmtime(path):
                with np.load(sidecar) as z:
                    return z["values"], z["blocks"]
        except (OSError, ValueError, KeyError):
            pass
        if table is not None:
            footer = self.read_footer_checked(table, rel)
            data = self.read_file(table, rel)
        else:
            footer = read_footer(path)
            data = read_column_file(path)
        vals_parts, blk_parts = [], []
        row = 0
        for i, b in enumerate(footer["blocks"]):
            u = np.unique(data[row:row + b["nrows"]])
            vals_parts.append(u)
            blk_parts.append(np.full(len(u), i, np.int32))
            row += b["nrows"]
        values = (np.concatenate(vals_parts) if vals_parts
                  else np.empty(0, data.dtype))
        blocks = (np.concatenate(blk_parts) if blk_parts
                  else np.empty(0, np.int32))
        order = np.argsort(values, kind="stable")
        values, blocks = values[order], blocks[order]
        try:
            fd, tmp = tempfile.mkstemp(dir=base, prefix=".bidx",
                                       suffix=".npz")
            os.close(fd)
            np.savez(tmp, values=values, blocks=blocks)
            os.replace(tmp, sidecar)
        except OSError:
            pass   # cache write failure: the in-memory index still serves
        return values, blocks

    @staticmethod
    def _index_blocks_for(values, blocks, op, val) -> set:
        """Blocks containing any value satisfying ``op val``: equality is
        the point probe, range ops slice the sorted value run — the btree
        range-scan analog (nbtsearch.c _bt_first) over block addresses.
        On unclustered data a wide range keeps most blocks (honest); a
        selective range keeps only the blocks its few values live in."""
        if op == "=":
            lo = np.searchsorted(values, val, side="left")
            hi = np.searchsorted(values, val, side="right")
        elif op == "<":
            lo, hi = 0, np.searchsorted(values, val, side="left")
        elif op == "<=":
            lo, hi = 0, np.searchsorted(values, val, side="right")
        elif op == ">":
            lo, hi = np.searchsorted(values, val, side="right"), len(values)
        elif op == ">=":
            lo, hi = np.searchsorted(values, val, side="left"), len(values)
        else:
            return set(blocks.tolist())
        return set(blocks[lo:hi].tolist())

    def _kept_blocks(self, table, files, base, prune, indexed_cols=frozenset()):
        """Per data-fileno block keep-list: a block survives only if EVERY
        pushed predicate could match its zone map [zmin, zmax] AND, for
        equality predicates on indexed columns, the block index says the
        key is present. -> ({fileno: [block idx]}, kept, total); filenos
        absent from the dict keep all blocks."""
        keep: dict[str, list[int]] = {}
        kept = total = 0
        by_fileno_nblocks: dict[str, int] = {}
        by_col = {}
        for col, op, val in prune:
            by_col.setdefault(col, []).append((op, val))
        for rel in files:   # one footer read per relevant file
            fn = os.path.basename(rel)
            parts = fn.split(".")
            if len(parts) != 3 or not fn.endswith(".ggb"):
                continue   # data files only: <col>.<fileno>.ggb
            col, fileno = parts[0], parts[1]
            preds = by_col.get(col)
            if not preds:
                continue
            blocks = self.read_footer_checked(table, rel)["blocks"]
            by_fileno_nblocks[fileno] = len(blocks)
            idx_keep: set | None = None
            if col in indexed_cols and preds:
                vals, blks = self.block_index(base, rel, table=table)
                for op, v in preds:
                    hit = self._index_blocks_for(vals, blks, op, v)
                    idx_keep = hit if idx_keep is None else idx_keep & hit
            ok = []
            for i, b in enumerate(blocks):
                if idx_keep is not None and i not in idx_keep:
                    continue
                if "zmin" not in b:
                    ok.append(i)
                    continue
                lo, hi = b["zmin"], b["zmax"]
                good = True
                for op, val in preds:
                    if not ((op == "=" and lo <= val <= hi)
                            or (op == "<" and lo < val)
                            or (op == "<=" and lo <= val)
                            or (op == ">" and hi > val)
                            or (op == ">=" and hi >= val)):
                        good = False
                        break
                if good:
                    ok.append(i)
            prev = keep.get(fileno)
            if prev is None:
                keep[fileno] = ok
            else:
                prev_set = set(prev)
                keep[fileno] = [i for i in ok if i in prev_set]
        for fileno, ok in keep.items():
            total += by_fileno_nblocks.get(fileno, 0)
            kept += len(ok)
        return keep, kept, total

    def read_segment(self, table: str, seg: int, columns: list[str] | None = None,
                     snapshot: dict | None = None, prune: tuple | None = None,
                     dest: dict | None = None):
        """-> (cols: {name: np.ndarray}, valids: {name: np.ndarray|None}, nrows).

        ``prune``: zone-map predicates [(col, op, value)] — blocks they rule
        out are skipped for EVERY requested column (block partitioning is
        identical across a fileno's columns), shrinking the staged rows.

        ``dest``: optional {col: preallocated array} destinations (the
        executor's staging-buffer slots). A plain single-file column with
        no pruning/deletions decodes STRAIGHT into its slot (the returned
        array is a view of it), skipping the staging copy entirely."""
        schema = self.catalog.get(table)
        snap = snapshot or self.manifest.snapshot()
        tmeta = snap["tables"].get(table, {"segfiles": {}, "nrows": {}})
        files = tmeta["segfiles"].get(str(seg), [])
        want = columns if columns is not None else schema.column_names
        cols: dict[str, np.ndarray] = {}
        valids: dict[str, np.ndarray | None] = {}
        nrows = tmeta["nrows"].get(str(seg), 0)
        base = os.path.join(self.data_root(seg), table)
        keep = None
        self.last_prune = None
        # deletion bitmap (visimap analog): rows marked deleted are dropped
        # after assembly. Zone-map block pruning is skipped while a bitmap
        # exists — pruned blocks would desync the bitmap's row numbering;
        # VACUUM compaction restores pruned scans.
        keep_rows = self.delmask_keep(table, seg, snap)
        if prune and keep_rows is None:
            idx_cols = frozenset(
                d["column"] for d in getattr(schema, "indexes", {}).values())
            keep, kept_n, total_n = self._kept_blocks(table, files, base,
                                                      prune, idx_cols)
            self.last_prune = (kept_n, total_n)
        for name in want:
            if name.startswith("@rc:"):
                # raw column under its transient dictionary (group/sort/
                # join keys on raw TEXT)
                arr, vmask = self.raw_codes(table, seg, name[4:], snap)
                cols[name] = arr
                valids[name] = vmask
                continue
            if name.startswith("@hp:"):
                # host-evaluated predicate over a raw TEXT column: the
                # device stages a boolean column (the dictionary-LUT idea
                # at O(rows) host cost; cached per manifest version)
                arr, vmask = self.eval_host_pred(table, seg, name, snap)
                cols[name] = arr
                valids[name] = vmask
                continue
            if name.startswith("@rp:"):
                # one packed-prefix word of a raw column (device eq/LIKE)
                _, rcol, w = name.split(":", 2)
                words, _l = self.raw_prefix(table, seg, rcol, snap)
                cols[name] = words[:, int(w)]
                valids[name] = self.raw_chunk(table, seg, rcol, snap).valid
                continue
            if name.startswith("@rw:"):
                # one WIDE packed word (general device LIKE byte window);
                # pack only the lanes the column's max length needs
                _, rcol, w = name.split(":", 2)
                nw = max(-(-self.raw_max_len(table, rcol, snap) // 8),
                         int(w) + 1)
                words, _l = self.raw_prefix(table, seg, rcol, snap,
                                            nwords=min(nw, RAW_WIDE_WORDS))
                cols[name] = words[:, int(w)]
                valids[name] = self.raw_chunk(table, seg, rcol, snap).valid
                continue
            if name.startswith("@rl:"):
                rcol = name[4:]
                cols[name] = self.raw_lengths(table, seg, rcol, snap)
                valids[name] = self.raw_chunk(table, seg, rcol, snap).valid
                continue
            c = schema.column(name)
            stored_raw = c.type.kind is T.Kind.TEXT and (
                c.encoding == "raw"
                or any(os.path.basename(rel).startswith(name + ".")
                       and rel.endswith(".rawoffs.ggb") for rel in files))
            if stored_raw:
                # device sees a stable row surrogate; strings decode at
                # result finalize (fetch_raw). The file check guards the
                # crash window where raw segfiles committed but the
                # catalog's encoding resolution didn't persist — reading
                # offs/bytes blobs as int32 codes would be garbage
                cols[name] = ((np.int64(seg) << np.int64(40))
                              + np.arange(nrows, dtype=np.int64))
                valids[name] = self.raw_chunk(table, seg, name, snap).valid
                continue
            data_parts, valid_parts = [], []
            data_rels, valid_rels = [], []
            for rel in files:
                fn = os.path.basename(rel)
                if fn.startswith(name + ".") and fn.endswith(".ggb"):
                    if fn.endswith(".valid.ggb"):
                        valid_rels.append(rel)
                    else:
                        data_rels.append(rel)
            # in-place fast path: one data file, no block pruning, no
            # deletion bitmap — decode straight into the caller's slot
            d = None
            if dest is not None and keep is None and keep_rows is None \
                    and len(data_rels) == 1:
                d = dest.get(name)

            def _bidx(rel):
                # the kept-block slice applies to data AND valid files of
                # a fileno alike (block partitioning is identical), or the
                # two would misalign after pruning
                if keep is None:
                    return None
                parts = os.path.basename(rel).split(".")
                return keep.get(parts[1] if len(parts) >= 3 else None)

            for rel in valid_rels:
                valid_parts.append((rel, self.read_file(table, rel,
                                                        _bidx(rel))))
            for rel in data_rels:
                data_parts.append((rel, self.read_file(table, rel,
                                                       _bidx(rel), out=d)))
            if len(data_parts) == 1:
                # single segfile (the common post-load shape): hand the
                # cache-resident array through as-is — staging copies it
                # into its own buffer, so nothing downstream mutates it
                cols[name] = data_parts[0][1]
            elif data_parts:
                cols[name] = np.concatenate([a for _, a in data_parts])
            else:
                cols[name] = np.empty(0, dtype=c.type.np_dtype)
            if valid_parts:
                # files without a .valid sibling are all-valid
                vmap = {r.replace(".valid.ggb", ".ggb"): a for r, a in valid_parts}
                vs = []
                for r, a in data_parts:
                    vs.append(vmap.get(r, np.ones(len(a), dtype=np.uint8)))
                valids[name] = np.concatenate(vs).astype(bool)
            else:
                valids[name] = None
            if keep is None and len(cols[name]) != nrows:
                raise IOError(f"{table}.{name} seg{seg}: {len(cols[name])} rows, manifest says {nrows}")
        if keep is not None and want:
            nrows = len(next(iter(cols.values()))) if cols else 0
        if keep_rows is not None:
            # raw-TEXT surrogates keep their ORIGINAL row numbers through
            # the filter (they were generated before it), so fetch_raw
            # still indexes the full blob correctly
            for name in cols:
                cols[name] = cols[name][keep_rows]
                v = valids.get(name)
                if v is not None:
                    valids[name] = v[keep_rows]
            nrows = int(keep_rows.sum())
        return cols, valids, nrows

    # ---- raw TEXT columns (varlena analog) -----------------------------
    def raw_chunk(self, table: str, seg: int, col: str, snapshot=None):
        """Assembled (blob, offsets, valid, strings-cache) for one raw TEXT
        column of one segment, manifest-version cached."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = (table, col, seg, version)
        hit = self._raw_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        tmeta = snap["tables"].get(table, {"segfiles": {}})
        files = tmeta["segfiles"].get(str(seg), [])
        blob_rels, offs_parts, valid_parts = [], [], []
        bytes_base = 0
        valid_for = {}
        for rel in files:
            fn = os.path.basename(rel)
            if fn.startswith(col + ".") and fn.endswith(".valid.ggb"):
                valid_for[fn.replace(".valid.ggb", "")] = self.read_file(
                    table, rel)
        for rel in files:
            fn = os.path.basename(rel)
            if fn.startswith(col + ".") and fn.endswith(".rawoffs.ggb"):
                offs = self.read_file(table, rel).astype(np.int64)
                n = len(offs) - 1
                offs_parts.append(offs[1:] + bytes_base)   # per-row END offsets
                blob_rels.append(rel.replace(".rawoffs.ggb", ".rawbytes.ggb"))
                v = valid_for.get(fn.replace(".rawoffs.ggb", ""))
                valid_parts.append(np.asarray(v, bool) if v is not None
                                   else np.ones(n, dtype=bool))
                bytes_base += int(offs[-1])
        ends = np.concatenate(offs_parts) if offs_parts else np.zeros(0, np.int64)
        valid = np.concatenate(valid_parts) if valid_parts else np.zeros(0, bool)
        chunk = _RawChunk(ends, None if valid.all() else valid, blob_rels,
                          reader=lambda rel: self.read_file(table, rel))
        self._raw_cache.put(key, chunk, version=version)
        return chunk

    def raw_max_len(self, table: str, col: str, snapshot=None) -> int:
        """Max utf-8 byte length over every committed row of a raw column
        (cached per version) — gates device-decidability of general LIKE:
        rows longer than the staged window could match past it."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = ("@maxlen", table, col, version)
        hit = self._rawprefix_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        schema = self.catalog.get(table)
        best = 0
        for seg in range(schema.policy.numsegments):
            chunk = self.raw_chunk(table, seg, col, snap)
            ends = chunk.ends
            if len(ends):
                starts = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
                best = max(best, int((ends - starts).max()))
        self._rawprefix_cache.put(key, best, version=version)
        return best

    def raw_lengths(self, table: str, seg: int, col: str, snapshot=None):
        """Exact byte lengths of a raw column's rows for one segment —
        O(rows) offset subtraction straight off the chunk, WITHOUT the
        byte-window packing raw_prefix pays (an @rl-only consumer, e.g.
        ``length(col)`` device chains, must not fund word lanes it never
        reads). Cached per version under the same key raw_prefix shares,
        so either producer serves later readers."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        lkey = ("@len", table, col, seg, version)
        hit = self._rawprefix_cache.get(lkey, MISS)
        if hit is not MISS:
            return hit
        chunk = self.raw_chunk(table, seg, col, snap)
        ends = chunk.ends
        starts = (np.concatenate([np.zeros(1, np.int64), ends[:-1]])
                  if len(ends) else np.zeros(0, np.int64))
        lengths = (ends - starts).astype(np.int32)
        self._rawprefix_cache.put(lkey, lengths, version=version)
        return lengths

    def raw_is_ascii(self, table: str, col: str, snapshot=None) -> bool:
        """True when every committed byte of a raw column is < 0x80
        (cached per version) — gates the byte-window scalar lowerings
        whose semantics count CHARACTERS (upper/lower/substr/length):
        over pure ASCII, bytes and characters coincide, so the device
        byte ops are exact; otherwise those chains stay on the host."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = ("@ascii", table, col, version)
        hit = self._rawprefix_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        schema = self.catalog.get(table)
        ok = True
        for seg in range(schema.policy.numsegments):
            chunk = self.raw_chunk(table, seg, col, snap)
            if len(chunk.ends):
                blob = chunk.blob()
                if len(blob) and int(blob.max()) >= 0x80:
                    ok = False
                    break
        self._rawprefix_cache.put(key, ok, version=version)
        return ok

    def raw_prefix(self, table: str, seg: int, col: str, snapshot=None,
                   nwords: int = RAW_PREFIX_WORDS):
        """Packed fixed-width byte prefix of a raw TEXT column, the device
        representation for on-device equality/LIKE-prefix predicates
        (VERDICT r3 #7): the first RAW_PREFIX_BYTES utf-8 bytes of every
        row packed big-endian into RAW_PREFIX_WORDS int64 lanes (equal
        strings <=> equal words + equal length; utf-8 preserves prefix
        relations), plus the exact byte length. O(rows x 32) vectorized
        numpy, manifest-version cached — NOT the per-statement O(heap)
        python of the host-predicate fallback.
        -> (words [n, RAW_PREFIX_WORDS] int64, lengths [n] int32)."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = (table, col, seg, version, nwords)
        lkey = ("@len", table, col, seg, version)
        hit = self._rawprefix_cache.get(key, MISS)
        if hit is not MISS:
            lens_hit = self._rawprefix_cache.get(lkey, MISS)
            if lens_hit is not MISS:    # may be independently evicted
                return hit, lens_hit
        chunk = self.raw_chunk(table, seg, col, snap)
        ends = chunk.ends
        n = len(ends)
        blob = chunk.blob()
        starts = (np.concatenate([np.zeros(1, np.int64), ends[:-1]])
                  if n else np.zeros(0, np.int64))
        lengths = (ends - starts).astype(np.int32)
        words = np.zeros((n, nwords), np.uint64)
        if n and len(blob):
            # chunk rows: the transient n x width gather matrices would
            # otherwise spike ~KB/row of host memory on big segments
            # scale the chunk inversely with the window so the transient
            # gather matrices stay ~bounded regardless of nwords
            CH = max((1 << 22) // max(nwords, 1), 1 << 16)
            steps = np.arange(nwords * 8, dtype=np.int64)[None, :]
            for a in range(0, n, CH):
                b = min(a + CH, n)
                idx = starts[a:b, None] + steps
                m = idx < ends[a:b, None]
                data = np.where(m, blob[np.minimum(idx, len(blob) - 1)],
                                np.uint8(0)).astype(np.uint64)
                for w in range(nwords):
                    acc = np.zeros(b - a, np.uint64)
                    for j in range(8):
                        acc = (acc << np.uint64(8)) | data[:, w * 8 + j]
                    words[a:b, w] = acc
        self._rawprefix_cache.put(key, words.view(np.int64), version=version)
        self._rawprefix_cache.put(lkey, lengths, version=version)
        return words.view(np.int64), lengths

    @staticmethod
    def host_pred_name(col: str, payload: dict) -> str:
        """Virtual staged-column name carrying a host-evaluated raw-text
        predicate: '@hp:<col>:<hex json payload>'."""

        return f"@hp:{col}:{json.dumps(payload, sort_keys=True).encode().hex()}"

    def eval_host_pred(self, table: str, seg: int, name: str, snapshot=None):
        """-> (bool array, valid|None) for one '@hp:' virtual column."""

        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = (table, seg, name, version)
        hit = self._hp_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        _, col, hexpayload = name.split(":", 2)
        payload = json.loads(bytes.fromhex(hexpayload))
        chunk = self.raw_chunk(table, seg, col, snap)
        strs = chunk.strings()
        op = payload["op"]
        if op == "like":
            rx = T.like_to_regex(payload["pattern"])
            out = np.fromiter((rx.fullmatch(s) is not None for s in strs),
                              bool, len(strs))
        elif op == "eq":
            out = np.fromiter((s == payload["value"] for s in strs),
                              bool, len(strs))
        elif op == "in":
            vals = set(payload["values"])
            out = np.fromiter((s in vals for s in strs), bool, len(strs))
        elif op == "chain":
            # string-function chain + comparison (utils/strfuncs semantics)

            from greengage_tpu.utils import strfuncs

            chain = payload["chain"]
            vals = [strfuncs.apply_chain(s, chain) for s in strs]
            cmp = payload["cmp"]
            if cmp == "like":
                rx = T.like_to_regex(payload["value"])
                out = np.fromiter(
                    (rx.fullmatch(v) is not None for v in vals),
                    bool, len(vals))
            elif cmp == "in":
                targets = set(payload["value"])
                out = np.fromiter((v in targets for v in vals),
                                  bool, len(vals))
            else:
                fn = {"=": operator.eq, "<>": operator.ne,
                      "<": operator.lt, "<=": operator.le,
                      ">": operator.gt, ">=": operator.ge}[cmp]
                tgt = payload["value"]
                out = np.fromiter((fn(v, tgt) for v in vals),
                                  bool, len(vals))
        else:
            raise ValueError(f"unknown host predicate op {op}")
        res = (out, chunk.valid)
        self._hp_cache.put(key, res, version=version)
        return res

    def fetch_raw(self, table: str, col: str, surrogates: np.ndarray,
                  snapshot=None):
        """Decode raw-TEXT row surrogates ((seg << 40) | row) back to
        strings for result finalize."""
        out = np.empty(len(surrogates), dtype=object)
        if len(surrogates) == 0:
            return out
        sur = np.asarray(surrogates, np.int64)
        segs = sur >> np.int64(40)
        rows = sur & np.int64((1 << 40) - 1)
        for s in np.unique(segs):
            chunk = self.raw_chunk(table, int(s), col, snapshot)
            strs = chunk.strings()
            mask = segs == s
            out[mask] = [strs[r] for r in rows[mask]]
        return out

    def rewrite_table(self, table: str, new_numsegments: int) -> int:
        """ALTER TABLE ... EXPAND TABLE analog (tablecmds.c:4067): re-place
        every row at the new cluster width and publish atomically. Works on
        already-encoded columns (TEXT codes kept; placement hashes go through
        the dictionary LUT so string placement stays bytes-based)."""
        from greengage_tpu.catalog.schema import DistPolicy, PolicyKind

        schema = self.catalog.get(table)
        raw_names = self.raw_column_names(table)
        old_nseg = schema.policy.numsegments
        # gather all rows from the old layout
        parts_cols: dict[str, list] = {c.name: [] for c in schema.columns}
        parts_valids: dict[str, list] = {c.name: [] for c in schema.columns}
        any_valid = {c.name: False for c in schema.columns}
        snap = self.manifest.snapshot()
        total = 0
        read_segs = 1 if schema.policy.kind is PolicyKind.REPLICATED else old_nseg
        for seg in range(read_segs):
            cols, valids, n = self.read_segment(table, seg, snapshot=snap)
            total += n
            for c in schema.columns:
                if c.name in raw_names:
                    # re-placement needs the actual strings, not surrogates;
                    # the deletion bitmap filter must match read_segment's
                    strs = np.asarray(
                        self.raw_chunk(table, seg, c.name, snap).strings(),
                        dtype=object)
                    km = self.delmask_keep(table, seg, snap)
                    cols[c.name] = strs if km is None else strs[km]
                parts_cols[c.name].append(cols[c.name])
                v = valids[c.name]
                if v is not None:
                    any_valid[c.name] = True
                parts_valids[c.name].append(
                    v if v is not None else np.ones(n, dtype=bool))
        enc = {c.name: np.concatenate(parts_cols[c.name]) if parts_cols[c.name]
               else np.empty(0, dtype=(object if c.name in raw_names
                                       else c.type.np_dtype))
               for c in schema.columns}
        raw_strs = {n: enc[n] for n in raw_names}
        for n in raw_names:   # placeholder for width checks; never hashed
            enc[n] = np.zeros(len(raw_strs[n]), np.int64)
        valids = {
            c.name: np.concatenate(parts_valids[c.name])
            for c in schema.columns
            if any_valid[c.name] and parts_valids[c.name]
        }

        new_policy = DistPolicy(schema.policy.kind, schema.policy.keys, new_numsegments)
        old_files = [
            rel for files in snap["tables"].get(table, {"segfiles": {}})["segfiles"].values()
            for rel in files
        ]
        tx = self.manifest.begin()
        # the manifest carries the table width so layout + width publish in
        # ONE atomic commit; the catalog copy is reconciled from it on open
        tx["tables"][table] = {"segfiles": {}, "nrows": {},
                               "numsegments": new_numsegments}
        tmeta = tx["tables"][table]
        nrows = len(next(iter(enc.values()))) if enc else 0
        if new_policy.kind is PolicyKind.REPLICATED:
            seg_rows = [np.arange(nrows)] * new_numsegments
        elif new_policy.kind is PolicyKind.HASH:
            rh = self.row_hashes(schema, enc, valids, new_policy.keys)
            seg_of = (rh % np.uint32(new_numsegments)).astype(np.int32)
            seg_rows = [np.nonzero(seg_of == s)[0] for s in range(new_numsegments)]
        else:
            seg_of = (np.arange(nrows) % new_numsegments).astype(np.int32)
            seg_rows = [np.nonzero(seg_of == s)[0] for s in range(new_numsegments)]
        self._write_segfiles(schema, table, tmeta, enc, valids, seg_rows,
                             uuid.uuid4().hex[:12], raw_strs=raw_strs)
        v = self.manifest.prepare(tx)
        try:
            self.manifest.commit(v)
        except BaseException:
            # a lost commit (cross-process fold raced the root version
            # guard) must release the staged claim, as commit_tx does
            self.manifest.abort(v)
            raise
        # catalog: table now spans the new width (manifest is authoritative
        # if we crash before this save — see reconcile_widths)
        schema.policy = new_policy
        self.catalog._save()
        # GC the old layout's files (unreachable from the new manifest)
        for rel in old_files:
            try:
                os.remove(self.seg_file_path(table, rel))
            except OSError:
                pass
        return nrows

    def stage_replace(self, tx: dict, table: str, enc: dict, valids: dict,
                      raw_strs: dict | None = None) -> list:
        """Stage a full-table replacement into a manifest transaction.
        Returns the OLD file rels (unreachable once the tx commits; the
        caller GCs them post-commit). ``enc`` holds storage-representation
        arrays (TEXT = dictionary codes; raw TEXT = placeholder, actual
        strings in ``raw_strs``); placement is recomputed, so updated
        distribution keys move rows to their new owner segments
        (SplitUpdate's explicit redistribution analog,
        src/backend/executor/nodeSplitUpdate.c)."""
        from greengage_tpu.catalog.schema import PolicyKind

        schema = self.catalog.get(table)
        raw_cols = self.raw_column_names(table)
        if raw_cols - set(raw_strs or ()):
            raise ValueError(
                f"table {table} republish is missing decoded strings for "
                f"raw columns {sorted(raw_cols - set(raw_strs or ()))}")
        for c in schema.columns:
            v = valids.get(c.name)
            if not c.nullable and v is not None and not np.all(v):
                raise ValueError(
                    f'null value in column "{c.name}" violates not-null constraint')
        nseg = schema.policy.numsegments
        old_files = [
            rel for files in tx["tables"].get(
                table, {"segfiles": {}})["segfiles"].values()
            for rel in files
        ]
        nrows = len(next(iter(enc.values()))) if enc else 0
        tx["tables"][table] = {"segfiles": {}, "nrows": {},
                               "numsegments": nseg}
        tmeta = tx["tables"][table]
        if schema.policy.kind is PolicyKind.REPLICATED:
            seg_rows = [np.arange(nrows)] * nseg
        elif schema.policy.kind is PolicyKind.HASH:
            rh = self.row_hashes(schema, enc, valids, schema.policy.keys)
            seg_of = (rh % np.uint32(nseg)).astype(np.int32)
            seg_rows = [np.nonzero(seg_of == s)[0] for s in range(nseg)]
        else:
            seg_of = (np.arange(nrows) % nseg).astype(np.int32)
            seg_rows = [np.nonzero(seg_of == s)[0] for s in range(nseg)]
        self._write_segfiles(schema, table, tmeta, enc, valids, seg_rows,
                             uuid.uuid4().hex[:12], raw_strs=raw_strs)
        return old_files

    GC_GRACE_S = 30.0   # snapshot readers finish well within this

    def maybe_fold_manifest(self) -> bool:
        """Checkpoint the delta backlog into the root snapshot once it
        reaches manifest_delta_fold_threshold commits (the
        checkpoint_segments analog). Opportunistic and race-tolerant —
        a concurrent fold/root writer simply wins the claim."""
        threshold = 64
        if self.settings is not None:
            threshold = int(getattr(self.settings,
                                    "manifest_delta_fold_threshold", 64))
        if self.manifest.delta_backlog() < max(1, threshold):
            return False
        return self.manifest.fold(min_deltas=max(1, threshold))

    def gc_files(self, table: str, rels: list, defer: bool = True) -> None:
        """Reclaim files made unreachable by a commit. Deletion is DEFERRED
        by a grace period: concurrent lock-free readers may still be
        scanning these files from an older snapshot (the server's
        concurrent SELECT vs UPDATE interleaving). defer=False deletes
        immediately (rollback of files nobody else ever saw)."""

        if defer:
            if not hasattr(self, "_pending_gc"):
                self._pending_gc = []
            self._pending_gc.append((_time.monotonic(), table, list(rels)))
            self.reap_gc()
            return
        for rel in rels:
            try:
                os.remove(self.seg_file_path(table, rel))
            except OSError:
                pass
            if rel.endswith(".ggb") and len(
                    os.path.basename(rel).split(".")) == 3:
                try:   # derived block-index sidecar dies with its file
                    os.remove(self.seg_file_path(table, rel)[:-len(".ggb")]
                              + ".bidx.npz")
                except OSError:
                    pass

    def reap_gc(self) -> int:
        """Delete deferred-GC entries older than the grace period."""

        pend = getattr(self, "_pending_gc", [])
        now = _time.monotonic()
        keep, removed = [], 0
        for ts, table, rels in pend:
            if now - ts >= self.GC_GRACE_S:
                self.gc_files(table, rels, defer=False)
                removed += len(rels)
            else:
                keep.append((ts, table, rels))
        self._pending_gc = keep
        return removed

    def sweep_orphans(self, grace_s: float = 120.0) -> int:
        """Delete segment files not referenced by the current manifest and
        older than ``grace_s`` (crashed writers' staging, rolled-back DML
        from dead processes, deferred GC lost at exit) — the VACUUM role.
        Recent files are spared: they may belong to an in-flight write."""

        snap = self.manifest.snapshot()
        referenced = set()
        for tname, tmeta in snap.get("tables", {}).items():
            for files in tmeta.get("segfiles", {}).values():
                for rel in files:
                    referenced.add((tname, os.path.basename(rel)))
        removed = 0
        now = _time.time()
        # sweep the mirror trees too: replication/repair stage (.tmp /
        # .repair.) there, and GC'd files' mirror copies are just as
        # unreachable as the acting copies
        roots = {os.path.join(self.root, "data")}
        segs = getattr(self.catalog, "segments", None)
        if segs is not None:
            for c in range(segs.numsegments):
                roots.add(mirror_root(self.root, c))
        for root in sorted(roots):
            if not os.path.isdir(root):
                continue
            for tname in os.listdir(root):
                tdir = os.path.join(root, tname)
                if not os.path.isdir(tdir):
                    continue
                for segdir in os.listdir(tdir):
                    sdir = os.path.join(tdir, segdir)
                    if not segdir.startswith("seg") or not os.path.isdir(sdir):
                        continue
                    for fn in os.listdir(sdir):
                        if not fn.endswith(".ggb"):
                            if ".repair." not in fn and not \
                                    fn.endswith(".tmp"):
                                continue
                            # crashed repair/copy staging: age out below
                        elif (tname, fn) in referenced:
                            continue
                        p = os.path.join(sdir, fn)
                        try:
                            if now - os.path.getmtime(p) >= grace_s:
                                os.remove(p)
                                removed += 1
                        except OSError:
                            pass
        # crashed writers' in-doubt intent markers age out under the same
        # grace: compose never reads them, so a swept one only turns a
        # parked writer's commit into a clean write-write conflict
        removed += self.manifest.sweep_intents(grace_s)
        return removed

    def replace_contents(self, table: str, enc: dict, valids: dict,
                         raw_strs: dict | None = None) -> None:
        """Autocommit full-table replacement (see stage_replace)."""
        tx = self.manifest.begin()
        old_files = self.stage_replace(tx, table, enc, valids, raw_strs)
        self.manifest.commit_tables_tx(tx, [table])
        self.gc_files(table, old_files)
        self.maybe_fold_manifest()

    # ---- deletion bitmaps (the appendonly visimap analog) ---------------
    # DELETE/UPDATE never rewrite data files: they publish a per-segment
    # deletion bitmap ('@del.<fileno>.ggb' — '@' can never collide with a
    # column identifier) recorded in BOTH tmeta["delmask"] (lookup) and
    # segfiles (replication/archive/orphan-sweep walk segfiles, so the
    # bitmap rides every existing durability path). The bitmap covers the
    # first len(mask) rows of the segment in manifest file order; rows
    # appended later are implicitly live. Full rewrites (stage_replace /
    # rewrite_table / VACUUM compaction) drop it.
    # Reference: src/backend/access/appendonly/appendonly_visimap.c:1.

    def delmask_keep(self, table: str, seg: int,
                     snapshot: dict | None = None):
        """-> bool[nrows] keep mask (True = live) or None when the segment
        has no deletions. Manifest-version cached."""
        snap = snapshot or self.manifest.snapshot()
        version = snap.get("version", 0)
        key = (table, seg, version)
        hit = self._delmask_cache.get(key, MISS)
        if hit is not MISS:
            return hit
        tmeta = snap["tables"].get(table, {})
        rel = tmeta.get("delmask", {}).get(str(seg))
        keep = None
        if rel is not None:
            deleted = self.read_file(table, rel)
            nrows = tmeta.get("nrows", {}).get(str(seg), 0)
            keep = np.ones(nrows, dtype=bool)
            keep[: len(deleted)] = ~deleted.astype(bool)
            if keep.all():
                keep = None
        self._delmask_cache.put(key, keep, version=version)
        return keep

    def live_rowcounts(self, table: str, snapshot: dict | None = None) -> list[int]:
        """Per-segment VISIBLE row counts (manifest nrows minus deletion
        bitmap) — what read_segment will actually return."""
        snap = snapshot or self.manifest.snapshot()
        out = []
        for seg, n in enumerate(self.segment_rowcounts(table, snap)):
            keep = self.delmask_keep(table, seg, snap)
            out.append(int(keep.sum()) if keep is not None else n)
        return out

    def stage_delmask(self, tx: dict, table: str,
                      masks: dict[int, np.ndarray]) -> list:
        """Stage new deletion bitmaps (1 = deleted, full manifest length)
        into a manifest tx; returns the REPLACED bitmap rels for GC."""
        schema = self.catalog.get(table)
        tmeta = tx["tables"].setdefault(table, {"segfiles": {}, "nrows": {}})
        dm = tmeta.setdefault("delmask", {})
        compresstype = schema.options.get("compresstype", "zlib")
        complevel = int(schema.options.get("compresslevel", 1))
        fileno = uuid.uuid4().hex[:12]
        old_rels = []
        for seg, mask in masks.items():
            mask = np.asarray(mask, dtype=np.uint8)
            segdir = os.path.join(self.data_root(seg), table, f"seg{seg}")
            os.makedirs(segdir, exist_ok=True)
            fn = f"@del.{fileno}.ggb"
            write_column_file(os.path.join(segdir, fn), mask,
                              compresstype, complevel)
            rel = os.path.join(f"seg{seg}", fn)
            old = dm.get(str(seg))
            files = tmeta["segfiles"].setdefault(str(seg), [])
            if old is not None:
                old_rels.append(old)
                if old in files:
                    files.remove(old)
            files.append(rel)
            dm[str(seg)] = rel
        return old_rels

    def set_delmask(self, table: str, masks: dict[int, np.ndarray]) -> None:
        """Autocommit bitmap publish (one per-table delta commit).

        Retried (bounded) when fenced off by a concurrent write-intent
        merge: re-staging the SAME bitmaps against the fresh snapshot is
        correct by the visimap prefix contract — each mask covers the
        first len(mask) rows in manifest order, and rows an intent
        appended after this DELETE's snapshot are implicitly live. Other
        conflicts (a concurrent full-state commit changed row visibility)
        still surface: retrying those would replay stale visibility."""
        last = None
        for attempt in range(10):
            tx = self.manifest.begin()
            old = self.stage_delmask(tx, table, masks)
            try:
                self.manifest.commit_tables_tx(tx, [table])
            except IntentConflict as e:
                last = e
                # the freshly staged bitmap files never became visible
                staged = [tx["tables"][table]["delmask"][str(s)]
                          for s in masks]
                self.gc_files(table, staged, defer=False)
                counters.inc("manifest_cas_retry_total")
                _time.sleep(0.01 * (attempt + 1))
                continue
            self.gc_files(table, old)
            self.maybe_fold_manifest()
            return
        raise RuntimeError(
            f"write-write conflict persisted after retries: {last}")

    def insert_encoded(self, table: str, enc: dict, valids: dict,
                       raw_strs: dict | None = None,
                       tx: dict | None = None) -> int:
        """Append rows already in STORAGE representation (TEXT = dictionary
        codes, decimals scaled, dates as days) — the UPDATE republish-free
        path: the new row versions come straight off a raw-mode scan."""
        schema = self.catalog.get(table)
        for c in schema.columns:
            v = (valids or {}).get(c.name)
            if not c.nullable and v is not None and not np.all(v):
                raise ValueError(
                    f'null value in column "{c.name}" violates not-null '
                    "constraint")
        return self._append_encoded(table, schema, enc, dict(valids or {}),
                                    raw_strs or {}, tx, {})

    def reconcile_widths(self) -> None:
        """Crash recovery for expansion: the manifest's per-table width is
        the commit record; if the catalog copy lags (crash between manifest
        commit and catalog save in rewrite_table), adopt the manifest's."""
        from greengage_tpu.catalog.schema import DistPolicy

        snap = self.manifest.snapshot()
        changed = False
        for name, tmeta in snap["tables"].items():
            width = tmeta.get("numsegments")
            if width is None or name not in self.catalog:
                continue
            schema = self.catalog.get(name)
            if schema.policy.numsegments != width:
                schema.policy = DistPolicy(schema.policy.kind, schema.policy.keys, width)
                changed = True
        if changed:
            self.catalog._save()

    def _write_segfiles(self, schema, table, tmeta, enc, valids, seg_rows,
                        fileno, raw_strs=None) -> list:
        """Write per-segment column files, record them in ``tmeta``, and
        return the records for optimistic-retry re-merge."""
        compresstype = schema.options.get("compresstype", "zlib")
        complevel = int(schema.options.get("compresslevel", 1))
        raw_strs = raw_strs or {}
        records: list = []
        for s, idx in enumerate(seg_rows):
            if len(idx) == 0:
                continue
            # the STORAGE table name, not schema.name: partition children
            # ("t#part") share the parent's schema but own their directory
            segdir = os.path.join(self.data_root(s), table, f"seg{s}")
            os.makedirs(segdir, exist_ok=True)
            files = tmeta["segfiles"].setdefault(str(s), [])
            files_before = len(files)
            for c in schema.columns:
                if c.name in raw_strs:
                    # raw TEXT: utf-8 byte blob + row offsets (varlena-style
                    # datum stream, aocsam.c:661)
                    vmask = valids.get(c.name)
                    vals = raw_strs[c.name][idx]
                    ok = np.asarray(vmask, bool)[idx] if vmask is not None else None
                    bts = [b"" if (ok is not None and not ok[i]) or v is None
                           else str(v).encode("utf-8")
                           for i, v in enumerate(vals)]
                    lens = np.fromiter((len(b) for b in bts), np.int64, len(bts))
                    offs = np.concatenate(
                        [np.zeros(1, np.int64), np.cumsum(lens)])
                    blob = np.frombuffer(b"".join(bts), np.uint8).copy()
                    ofn = f"{c.name}.{fileno}.rawoffs.ggb"
                    bfn = f"{c.name}.{fileno}.rawbytes.ggb"
                    write_column_file(os.path.join(segdir, ofn), offs,
                                      compresstype, complevel)
                    write_column_file(os.path.join(segdir, bfn), blob,
                                      compresstype, complevel)
                    files.append(os.path.join(f"seg{s}", ofn))
                    files.append(os.path.join(f"seg{s}", bfn))
                else:
                    fn = f"{c.name}.{fileno}.ggb"
                    write_column_file(os.path.join(segdir, fn), enc[c.name][idx],
                                      compresstype, complevel)
                    files.append(os.path.join(f"seg{s}", fn))
                v = valids.get(c.name)
                if v is not None:
                    vfn = f"{c.name}.{fileno}.valid.ggb"
                    write_column_file(os.path.join(segdir, vfn),
                                      np.asarray(v, dtype=np.uint8)[idx],
                                      compresstype, complevel)
                    files.append(os.path.join(f"seg{s}", vfn))
            tmeta["nrows"][str(s)] = tmeta["nrows"].get(str(s), 0) + int(len(idx))
            records.append((s, list(files[files_before:]), int(len(idx))))
        return records

    def has_nulls(self, table: str, col: str, snapshot: dict | None = None) -> bool:
        """True if any committed segfile of this column has a validity file
        (compile-time schema for the executor's input staging)."""
        if col.startswith(("@hp:", "@rp:", "@rw:")):
            col = col.split(":", 2)[1]   # predicate nullability = column's
        elif col.startswith("@rc:") or col.startswith("@rl:"):
            col = col[4:]                # code/length nullability = column's
        snap = snapshot or self.manifest.snapshot()
        schema = self.catalog.get(table) if table in self.catalog else None
        names = (schema.storage_tables()
                 if schema is not None and schema.name == table else [table])
        marker = f"{col}."
        for name in names:
            tmeta = snap["tables"].get(name, {"segfiles": {}})
            for files in tmeta["segfiles"].values():
                for rel in files:
                    fn = os.path.basename(rel)
                    if fn.startswith(marker) and fn.endswith(".valid.ggb"):
                        return True
        return False

    def column_bounds(self, table: str, col: str,
                      snapshot: dict | None = None):
        """Exact global [min, max] over every committed block of a stored
        column, from block zone maps (blockfile.write_column_file) — the
        sound key-packing bounds the distributed ordered-window path needs
        (values at NULL positions are fillers inside the same zones, so
        the result is a superset of live values; never an underestimate).
        None when any block lacks a zone (TEXT/all-NaN) or no rows."""
        snap = snapshot or self.manifest.snapshot()
        schema = self.catalog.get(table) if table in self.catalog else None
        names = (schema.storage_tables()
                 if schema is not None and schema.name == table else [table])
        lo = hi = None
        for name in names:
            tmeta = snap["tables"].get(name, {"segfiles": {}})
            for seg, files in tmeta["segfiles"].items():
                for rel in files:
                    fn = os.path.basename(rel)
                    parts = fn.split(".")
                    if (len(parts) != 3 or not fn.endswith(".ggb")
                            or parts[0] != col):
                        continue
                    for b in self.read_footer_checked(name, rel)["blocks"]:
                        if not b["nrows"]:
                            continue
                        if "zmin" not in b:
                            return None
                        lo = b["zmin"] if lo is None else min(lo, b["zmin"])
                        hi = b["zmax"] if hi is None else max(hi, b["zmax"])
        return None if lo is None else (lo, hi)

    def segment_rowcounts(self, table: str, snapshot: dict | None = None) -> list[int]:
        schema = self.catalog.get(table)
        snap = snapshot or self.manifest.snapshot()
        names = (schema.storage_tables()
                 if schema.name == table else [table])
        out = [0] * schema.policy.numsegments
        for name in names:
            tmeta = snap["tables"].get(name, {"nrows": {}})
            for s in range(schema.policy.numsegments):
                out[s] += tmeta["nrows"].get(str(s), 0)
        return out
