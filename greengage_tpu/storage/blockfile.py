"""Column block files (.ggb) — the AOCS datum-stream analog.

Reference parity: one segment file set per column with block-level
compression and checksummed headers (src/backend/access/aocs/aocsam.c,
src/backend/cdb/cdbappendonlystorageformat.c). Layout:

    [frame]* [footer-json] [u64 footer_len] [u32 magic "GGBF"]

Each frame is ggcodec's checksummed block (native.block_encode). The footer
records per-block (offset, nrows) so scans can do block-level skipping
(block directory analog) and projection reads only touch requested columns'
files.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

from greengage_tpu.storage import native

FOOTER_MAGIC = 0x47474246  # "GGBF"
DEFAULT_BLOCK_ROWS = 1 << 16

_COMP_BY_NAME = {"none": native.COMP_NONE, "zlib": native.COMP_ZLIB, "zstd": native.COMP_ZSTD}


def write_column_file(path: str, values: np.ndarray, compresstype: str = "zlib",
                      complevel: int = 1, block_rows: int = DEFAULT_BLOCK_ROWS) -> dict:
    """Write a 1-D numpy array as a block file; returns footer metadata."""
    comp = _COMP_BY_NAME[compresstype]
    values = np.ascontiguousarray(values)
    blocks = []
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        off = 0
        zonable = values.dtype.kind in ("i", "u", "f") and values.dtype.itemsize > 1
        for start in range(0, len(values), block_rows):
            chunk = values[start : start + block_rows]
            frame = native.block_encode(chunk.tobytes(), len(chunk), comp, complevel)
            f.write(frame)
            b = {"offset": off, "nrows": len(chunk), "bytes": len(frame)}
            if zonable and len(chunk):
                # zone map: per-block min/max for scan pruning (the
                # PartitionSelector/block-directory analog — blocks whose
                # range cannot satisfy a scan predicate are never staged).
                # Integer bounds stay EXACT python ints (floats above 2^53
                # would make strict-inequality pruning unsound); float
                # columns exclude NaNs (they match no range predicate), and
                # an all-NaN block gets no zone and is never pruned.
                if chunk.dtype.kind == "f":
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore", RuntimeWarning)
                        lo, hi = np.nanmin(chunk), np.nanmax(chunk)
                    if not np.isnan(lo):
                        b["zmin"] = float(lo)
                        b["zmax"] = float(hi)
                else:
                    b["zmin"] = int(np.min(chunk))
                    b["zmax"] = int(np.max(chunk))
            blocks.append(b)
            off += len(frame)
        footer = {
            "dtype": values.dtype.str,
            "nrows": int(len(values)),
            "blocks": blocks,
        }
        fj = json.dumps(footer).encode()
        f.write(fj)
        f.write(len(fj).to_bytes(8, "little"))
        f.write(FOOTER_MAGIC.to_bytes(4, "little"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return footer


def read_footer(path: str) -> dict:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - 12)
        tail = f.read(12)
        if int.from_bytes(tail[8:12], "little") != FOOTER_MAGIC:
            raise IOError(f"{path}: bad footer magic")
        flen = int.from_bytes(tail[:8], "little")
        f.seek(size - 12 - flen)
        return json.loads(f.read(flen))


def read_column_file(path: str, block_indices: list[int] | None = None) -> np.ndarray:
    """Read all (or selected) blocks back into one numpy array."""
    footer = read_footer(path)
    dtype = np.dtype(footer["dtype"])
    blocks = footer["blocks"]
    if block_indices is not None:
        blocks = [blocks[i] for i in block_indices]
    parts = []
    with open(path, "rb") as f:
        for b in blocks:
            f.seek(b["offset"])
            frame = f.read(b["bytes"])
            raw, nrows, _ = native.block_decode(frame)
            arr = np.frombuffer(raw, dtype=dtype)
            if len(arr) != nrows:
                raise IOError(f"{path}: block row count mismatch")
            parts.append(arr)
    if not parts:
        return np.empty(0, dtype=dtype)
    return np.concatenate(parts)
