"""Column block files (.ggb) — the AOCS datum-stream analog.

Reference parity: one segment file set per column with block-level
compression and checksummed headers (src/backend/access/aocs/aocsam.c,
src/backend/cdb/cdbappendonlystorageformat.c). Layout:

    [frame]* [footer-json] [u32 footer-crc] [u64 footer_len] [u32 magic "GGBF"]

Each frame is ggcodec's checksummed block (native.block_encode). The footer
records per-block (offset, nrows) so scans can do block-level skipping
(block directory analog) and projection reads only touch requested columns'
files. The footer JSON carries its own crc32 in the tail so footer damage
(including a bit flip inside a valid-JSON value) classifies as corruption
instead of silently mis-describing the frames.

All verification failures raise the typed ``CorruptionError``
(storage/corruption.py) carrying the path, block index, and cause — the
contract the read-path self-heal and the scrubber dispatch on.
"""

from __future__ import annotations

import json
import os
import warnings
import zlib

import numpy as np

from greengage_tpu.storage import native
from greengage_tpu.storage.corruption import CorruptionError

# bumped with the checksummed-footer format change ("GGBF" -> "GGF2") so
# files written by the 12-byte-tail layout fail with a CLEAR bad_footer
# classification, never a misparse of JSON bytes as a CRC
FOOTER_MAGIC = 0x32464747  # "GGF2"
FOOTER_TAIL = 16           # u32 crc + u64 footer_len + u32 magic
DEFAULT_BLOCK_ROWS = 1 << 16

_COMP_BY_NAME = {"none": native.COMP_NONE, "zlib": native.COMP_ZLIB, "zstd": native.COMP_ZSTD}


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed/created entry survives a crash
    (rename durability needs the parent's metadata flushed too)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_column_file(path: str, values: np.ndarray, compresstype: str = "zlib",
                      complevel: int = 1, block_rows: int = DEFAULT_BLOCK_ROWS) -> dict:
    """Write a 1-D numpy array as a block file; returns footer metadata."""
    comp = _COMP_BY_NAME[compresstype]
    values = np.ascontiguousarray(values)
    blocks = []
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        off = 0
        zonable = values.dtype.kind in ("i", "u", "f") and values.dtype.itemsize > 1
        for start in range(0, len(values), block_rows):
            chunk = values[start : start + block_rows]
            frame = native.block_encode(chunk.tobytes(), len(chunk), comp, complevel)
            f.write(frame)
            b = {"offset": off, "nrows": len(chunk), "bytes": len(frame)}
            if zonable and len(chunk):
                # zone map: per-block min/max for scan pruning (the
                # PartitionSelector/block-directory analog — blocks whose
                # range cannot satisfy a scan predicate are never staged).
                # Integer bounds stay EXACT python ints (floats above 2^53
                # would make strict-inequality pruning unsound); float
                # columns exclude NaNs (they match no range predicate), and
                # an all-NaN block gets no zone and is never pruned.
                if chunk.dtype.kind == "f":
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore", RuntimeWarning)
                        lo, hi = np.nanmin(chunk), np.nanmax(chunk)
                    if not np.isnan(lo):
                        b["zmin"] = float(lo)
                        b["zmax"] = float(hi)
                else:
                    b["zmin"] = int(np.min(chunk))
                    b["zmax"] = int(np.max(chunk))
            blocks.append(b)
            off += len(frame)
        footer = {
            "dtype": values.dtype.str,
            "nrows": int(len(values)),
            "blocks": blocks,
        }
        fj = json.dumps(footer).encode()
        f.write(fj)
        f.write((zlib.crc32(fj) & 0xFFFFFFFF).to_bytes(4, "little"))
        f.write(len(fj).to_bytes(8, "little"))
        f.write(FOOTER_MAGIC.to_bytes(4, "little"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return footer


def read_footer(path: str) -> dict:
    """Parse + verify the footer. Short/truncated/garbage-tail/damaged
    footers classify as CorruptionError with the path and cause."""
    with open(path, "rb") as f:
        return _read_footer_fh(f, path)


def _read_footer_fh(f, path: str) -> dict:
    """read_footer against an already-open handle (single-open read path:
    the column read parses the footer and decodes frames from ONE open)."""
    f.seek(0, os.SEEK_END)
    size = f.tell()
    if size < FOOTER_TAIL:
        raise CorruptionError(
            "truncated",
            f"file is {size} bytes, smaller than the {FOOTER_TAIL}-byte "
            "footer tail", path=path)
    f.seek(size - FOOTER_TAIL)
    tail = f.read(FOOTER_TAIL)
    tail_magic = int.from_bytes(tail[12:16], "little")
    if tail_magic == 0x47474246:   # "GGBF": the pre-CRC 12-byte tail
        raise IOError(
            f"{path}: unsupported block-file format GGBF (written by an "
            "older, incompatible version) — re-ingest from original "
            "sources")
    if tail_magic != FOOTER_MAGIC:
        raise CorruptionError(
            "bad_footer", "bad footer magic (garbage tail or not a "
            "block file)", path=path)
    flen = int.from_bytes(tail[4:12], "little")
    if flen > size - FOOTER_TAIL:
        raise CorruptionError(
            "truncated",
            f"footer length {flen} exceeds file size {size}", path=path)
    f.seek(size - FOOTER_TAIL - flen)
    fj = f.read(flen)
    if (zlib.crc32(fj) & 0xFFFFFFFF) != int.from_bytes(tail[:4], "little"):
        raise CorruptionError(
            "bad_footer", "footer checksum mismatch", path=path)
    try:
        footer = json.loads(fj)
    except ValueError as e:
        raise CorruptionError(
            "bad_footer", f"footer is not valid JSON ({e})", path=path)
    if not isinstance(footer, dict) or not isinstance(
            footer.get("blocks"), list) or "dtype" not in footer:
        raise CorruptionError(
            "bad_footer", "footer missing dtype/blocks", path=path)
    try:
        np.dtype(footer["dtype"])
    except TypeError as e:
        raise CorruptionError(
            "bad_footer", f"footer dtype unparseable ({e})", path=path)
    return footer


def _maybe_inject_corruption(frame: bytes, segment: int | None) -> bytes:
    """The storage_corrupt_block fault point: a 'skip'-type fault flips one
    payload byte of the frame AT READ TIME (occurrence/start_after
    targeting picks which frame of which read) — the gp_inject_fault
    AppendOnlyStorageRead corruption analog."""
    from greengage_tpu.runtime.faultinject import faults

    if not faults.check("storage_corrupt_block", segment=segment):
        return frame
    bad = bytearray(frame)
    if bad:
        pos = native.HDR_LEN + max(0, (len(bad) - native.HDR_LEN) // 2) \
            if len(bad) > native.HDR_LEN else len(bad) // 2
        bad[min(pos, len(bad) - 1)] ^= 0xFF
    return bytes(bad)


def read_column_file(path: str, block_indices: list[int] | None = None,
                     segment: int | None = None,
                     out: np.ndarray | None = None) -> np.ndarray:
    """Read all (or selected) blocks back into one numpy array. ``segment``
    only targets the storage_corrupt_block fault point.

    Frames decode IN PLACE into one preallocated output array sized from
    the footer (native.block_decode_into): no per-block bytes objects and
    no final concatenate — the copy count the pipelined staging path is
    built around. ``out`` lets the caller provide that destination (e.g.
    a slot of the executor's [nseg*cap] staging buffer, dtype- and
    capacity-compatible); the return value is then a view of it."""
    with open(path, "rb") as f:
        footer = _read_footer_fh(f, path)   # one open serves footer + frames
        dtype = np.dtype(footer["dtype"])
        blocks = list(enumerate(footer["blocks"]))
        if block_indices is not None:
            blocks = [blocks[i] for i in block_indices]
        total_rows = sum(b["nrows"] for _, b in blocks)
        if out is not None and (out.dtype != dtype or len(out) < total_rows
                                or not out.flags.c_contiguous):
            out = None   # incompatible destination: decode a fresh array
        if out is None:
            out = np.empty(total_rows, dtype=dtype)
        else:
            out = out[:total_rows]
        if not blocks:
            return out
        u8 = out.view(np.uint8)
        itemsize = dtype.itemsize
        row = 0
        for i, b in blocks:
            f.seek(b["offset"])
            frame = f.read(b["bytes"])
            frame = _maybe_inject_corruption(frame, segment)
            slot = u8[row * itemsize: (row + b["nrows"]) * itemsize]
            try:
                nbytes, nrows = native.block_decode_into(frame, slot)
            except CorruptionError as e:
                raise e.locate(path=path, block=i)
            if nrows != b["nrows"] or nbytes != nrows * itemsize:
                raise CorruptionError(
                    "rowcount_mismatch",
                    f"block decoded {nbytes} bytes / {nrows} rows, footer "
                    f"says {b['nrows']} rows of {itemsize} bytes",
                    path=path, block=i)
            row += nrows
    return out


def verify_column_file(path: str, segment: int | None = None,
                       inject: bool = True) -> dict:
    """Verify the footer and EVERY frame (checksums, decode, row counts)
    without materializing the column. Raises CorruptionError (with path +
    block) on the first failure; returns {bytes, blocks, nrows} scanned —
    the scrub/repair verification primitive. ``inject=False`` exempts the
    read from the storage_corrupt_block fault point: repair's own
    verification must judge the REAL bytes, or an armed fault would
    quarantine healthy files."""
    footer = read_footer(path)
    dtype = np.dtype(footer["dtype"])
    total_rows = 0
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        for i, b in enumerate(footer["blocks"]):
            f.seek(b["offset"])
            frame = f.read(b["bytes"])
            if inject:
                frame = _maybe_inject_corruption(frame, segment)
            try:
                raw, nrows, consumed = native.block_decode(frame)
            except CorruptionError as e:
                raise e.locate(path=path, block=i)
            if consumed != b["bytes"]:
                raise CorruptionError(
                    "truncated",
                    f"frame consumed {consumed} bytes, footer says "
                    f"{b['bytes']}", path=path, block=i)
            if nrows != b["nrows"] or len(raw) != nrows * dtype.itemsize:
                raise CorruptionError(
                    "rowcount_mismatch",
                    f"block holds {len(raw) // max(dtype.itemsize, 1)} rows, "
                    f"frame header says {nrows}, footer says {b['nrows']}",
                    path=path, block=i)
            total_rows += nrows
    if total_rows != footer["nrows"]:
        raise CorruptionError(
            "rowcount_mismatch",
            f"frames hold {total_rows} rows, footer says {footer['nrows']}",
            path=path)
    return {"bytes": size, "blocks": len(footer["blocks"]), "nrows": total_rows}
