from greengage_tpu.storage.table_store import TableStore  # noqa: F401
from greengage_tpu.storage.manifest import Manifest  # noqa: F401
