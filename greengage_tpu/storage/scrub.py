"""Scrubber: proactive whole-cluster storage verification + repair.

The read path only heals corruption a query happens to trip over; the
scrub pass is the background-verification role (the reference's
``appendonly_verify_block_checksums`` reads + gprecoverseg repair, and the
near-data scrubbing emphasis of Taurus-style storage layers): walk every
manifest-referenced block file of every content, verify the footer and
every frame checksum, repair corrupt/missing files from the in-sync
standby tree (or quarantine them when no healthy copy exists), and —
optionally — refresh damaged standby-tree copies from a healthy acting
copy so the NEXT failover doesn't inherit rot.

Exposed as ``gg scrub`` (mgmt/cli.py); returns a machine-readable report:

    {files_scanned, files_verified, files_repaired, files_quarantined,
     files_missing, standby_verified, standby_repaired, bytes_scanned,
     problems: [{table, relpath, cause, status, ...}]}
"""

from __future__ import annotations

import os

from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage.corruption import CorruptionError


class Scrubber:
    def __init__(self, store, repair: bool = True):
        self.store = store
        self.repair = repair

    def scrub(self, tables: list[str] | None = None,
              mirrors: bool = False) -> dict:
        """Verify (and repair-or-quarantine) every manifest-referenced
        file; with ``mirrors=True`` also verify/refresh standby copies.
        ``tables`` takes LOGICAL names: partitioned parents expand to
        their per-partition storage tables (the manifest keys); an
        unknown name raises instead of silently scanning nothing."""
        snap = self.store.manifest.snapshot()
        if tables is not None:
            want: set[str] = set()
            for t in tables:
                if t in self.store.catalog:
                    want.update(self.store.catalog.get(t).storage_tables())
                elif t in snap.get("tables", {}):
                    want.add(t)   # raw storage name (e.g. "sales#p1")
                else:
                    raise ValueError(f"unknown table {t!r}")
            tables = sorted(want)
        rep = {"files_scanned": 0, "files_verified": 0, "files_repaired": 0,
               "files_quarantined": 0, "files_missing": 0, "files_corrupt": 0,
               "standby_verified": 0, "standby_repaired": 0,
               "bytes_scanned": 0, "problems": []}
        for tname in sorted(snap.get("tables", {})):
            if tables is not None and tname not in tables:
                continue
            segfiles = snap["tables"][tname].get("segfiles", {})
            for seg in sorted(segfiles, key=int):
                content = int(seg)
                for rel in segfiles[seg]:
                    self._scrub_one(tname, content, rel, rep)
                    if mirrors:
                        self._scrub_standby(tname, content, rel, rep)
        counters.inc("storage_scrub_runs")
        counters.inc("storage_scrub_files", rep["files_scanned"])
        log = getattr(self.store, "log", None)
        if log is not None:
            log.info("scrub",
                     f"scrub: {rep['files_verified']} verified, "
                     f"{rep['files_repaired']} repaired, "
                     f"{rep['files_quarantined']} quarantined, "
                     f"{rep['files_missing']} missing, "
                     f"{rep['bytes_scanned']} bytes")
        return rep

    # ---- one acting-tree file ------------------------------------------
    def _scrub_one(self, table: str, content: int, rel: str,
                   rep: dict) -> None:
        from greengage_tpu.storage.blockfile import verify_column_file

        store = self.store
        if faults.check("scrub_file", segment=content):
            rep["problems"].append({"table": table, "relpath": rel,
                                    "status": "skipped"})
            return   # 'skip' fault: hole in coverage, recorded as such
        path = store.seg_file_path(table, rel)
        rep["files_scanned"] += 1
        try:
            st = verify_column_file(path, segment=content)
            rep["files_verified"] += 1
            rep["bytes_scanned"] += st["bytes"]
            return
        except FileNotFoundError:
            err = CorruptionError(
                "missing", "manifest-referenced file is missing", path=path)
        except CorruptionError as e:
            err = e
        err.locate(table=table, content=content, relpath=rel)
        if not self.repair:
            rep["files_corrupt" if err.cause != "missing"
                else "files_missing"] += 1
            rep["problems"].append(dict(err.to_dict(), status="corrupt"))
            return
        try:
            store.handle_corruption(table, content, rel, path, err)
            # repair_file already re-verified every frame of the new copy
            rep["files_repaired"] += 1
            try:
                rep["bytes_scanned"] += os.path.getsize(path)
            except OSError:
                pass
            rep["problems"].append(dict(err.to_dict(), status="repaired"))
        except CorruptionError:
            # handle_corruption already quarantined what it could;
            # storage_ok now fails for this content -> FTS takes over
            rep["files_quarantined" if err.cause != "missing"
                else "files_missing"] += 1
            rep["problems"].append(dict(err.to_dict(), status="quarantined"
                                        if err.cause != "missing"
                                        else "missing"))

    # ---- the standby copy ----------------------------------------------
    def _scrub_standby(self, table: str, content: int, rel: str,
                       rep: dict) -> None:
        """Verify the OTHER tree's copy; refresh it from a healthy acting
        copy (committed files are immutable, so copy-over is always the
        right repair) — keeps the next failover from inheriting rot."""
        from greengage_tpu.runtime.replication import copy_durable
        from greengage_tpu.storage.blockfile import verify_column_file

        store = self.store
        standby = store.standby_root(content)
        if standby is None:
            return
        spath = os.path.join(standby, table, rel)
        try:
            # inject=False: standby health must reflect the real bytes
            st = verify_column_file(spath, inject=False)
            rep["standby_verified"] += 1
            rep["bytes_scanned"] += st["bytes"]
            return
        except (FileNotFoundError, CorruptionError) as e:
            cause = getattr(e, "cause", "missing")
        if not self.repair:
            rep["problems"].append({"table": table, "relpath": rel,
                                    "cause": cause,
                                    "status": "standby_corrupt"})
            return
        apath = store.seg_file_path(table, rel)
        try:
            # only refresh from a healthy source
            verify_column_file(apath, inject=False)
        except (FileNotFoundError, CorruptionError):
            rep["problems"].append({"table": table, "relpath": rel,
                                    "cause": cause,
                                    "status": "standby_corrupt_no_source"})
            return
        try:
            os.makedirs(os.path.dirname(spath), exist_ok=True)
            copy_durable(apath, spath)
        except OSError as e:
            # a flaky/full standby disk must not abort the whole walk —
            # the remaining files (and their report lines) still matter
            rep["problems"].append({"table": table, "relpath": rel,
                                    "cause": cause, "error": str(e)[:120],
                                    "status": "standby_refresh_failed"})
            return
        rep["standby_repaired"] += 1
        counters.inc("storage_standby_repair")
        rep["problems"].append({"table": table, "relpath": rel,
                                "cause": cause,
                                "status": "standby_repaired"})
