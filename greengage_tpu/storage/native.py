"""ctypes bindings for the native ggcodec library, with numpy fallbacks.

The native library (native/ggcodec.cpp) is the host-side performance path for
distribution hashing and block encode/decode — the role the reference fills
with C (src/backend/cdb/cdbhash.c, cdbappendonlystorageformat.c). If the .so
is missing we build it with make; if that fails (no toolchain) the numpy
fallbacks are bit-identical but slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib

import numpy as np

from greengage_tpu.storage.corruption import CorruptionError

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO = os.path.join(_NATIVE_DIR, "libggcodec.so")

HASH_INIT = np.uint32(0x9E3779B9)
COMBINE_MUL = np.uint32(0x01000193)
# "GGB2": bumped with the CRC-covers-header format change so files written
# by the old frame layout fail with a CLEAR bad_magic, not a confusing
# checksum mismatch (must match GG_BLOCK_MAGIC in native/ggcodec.cpp)
BLOCK_MAGIC = 0x47474232
HDR_LEN = 32

_lib = None
_load_mu = threading.Lock()


def _load():
    # serialized: two staging threads racing the first load would run
    # `make` twice and publish half-configured handles (gg check races);
    # the steady-state cost is one uncontended acquire per call, noise
    # next to the ctypes dispatch it guards
    with _load_mu:
        return _load_locked()


def _load_locked():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True, capture_output=True)
        except Exception:
            pass
    if os.path.exists(_SO):
        try:
            lib = ctypes.CDLL(_SO)
            lib.gg_hash_i64_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32, ctypes.c_void_p]
            lib.gg_hash_combine_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
            lib.gg_hash_bytes.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
            lib.gg_hash_bytes.restype = ctypes.c_uint32
            lib.gg_block_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64]
            lib.gg_block_encode.restype = ctypes.c_int64
            lib.gg_block_decode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
            lib.gg_block_decode.restype = ctypes.c_int64
            _lib = lib
            return lib
        except OSError:
            pass
    _lib = False
    return False


def have_native() -> bool:
    return bool(_load())


# ---------------------------------------------------------------------------
# Hashing — numpy reference implementation (spec source of truth shared with
# greengage_tpu/ops/hashing.py, which mirrors it in JAX for on-device motion)
# ---------------------------------------------------------------------------

def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h.astype(np.uint32, copy=True)
    with np.errstate(over="ignore"):
        h ^= h >> np.uint32(16)
        h *= np.uint32(0x85EBCA6B)
        h ^= h >> np.uint32(13)
        h *= np.uint32(0xC2B2AE35)
        h ^= h >> np.uint32(16)
    return h


def hash_i64(vals: np.ndarray, seed: int = 0) -> np.ndarray:
    """uint32 hash of an int64 array (spec: fmix32 over lo then hi halves)."""
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    lib = _load()
    if lib:
        out = np.empty(len(vals), dtype=np.uint32)
        lib.gg_hash_i64_batch(vals.ctypes.data, len(vals), ctypes.c_uint32(seed & 0xFFFFFFFF),
                              out.ctypes.data)
        return out
    u = vals.view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    h = np.uint32(seed & 0xFFFFFFFF) ^ HASH_INIT
    h = _fmix32(np.uint32(h) ^ lo)
    h = _fmix32(h ^ hi)
    return h


def hash_combine(acc: np.ndarray, h: np.ndarray) -> np.ndarray:
    acc = np.ascontiguousarray(acc, dtype=np.uint32)
    h = np.ascontiguousarray(h, dtype=np.uint32)
    lib = _load()
    if lib:
        out = acc.copy()
        lib.gg_hash_combine_batch(out.ctypes.data, h.ctypes.data, len(acc))
        return out
    with np.errstate(over="ignore"):
        return _fmix32(acc * COMBINE_MUL ^ h)


def hash_bytes(data: bytes, seed: int = 0) -> int:
    """uint32 hash of a byte string (8-byte LE chunk folding + length)."""
    lib = _load()
    if lib:
        return int(lib.gg_hash_bytes(data, len(data), ctypes.c_uint32(seed & 0xFFFFFFFF)))
    acc = np.uint32(seed & 0xFFFFFFFF) ^ HASH_INIT
    acc_arr = np.array([acc], dtype=np.uint32)
    for i in range(0, len(data), 8):
        chunk = int.from_bytes(data[i : i + 8].ljust(8, b"\0"), "little")
        hv = hash_i64(np.array([np.uint64(chunk).astype(np.int64)], dtype=np.int64).view(np.int64))
        acc_arr = hash_combine(acc_arr, hv)
    acc_arr = hash_combine(acc_arr, hash_i64(np.array([len(data)], dtype=np.int64)))
    return int(acc_arr[0])


# ---------------------------------------------------------------------------
# Block frame codec
# ---------------------------------------------------------------------------

COMP_NONE, COMP_ZLIB, COMP_ZSTD = 0, 1, 2


def block_encode(raw: bytes | np.ndarray, nrows: int, compression: int = COMP_ZLIB,
                 level: int = 1) -> bytes:
    raw = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, (bytes, bytearray)) else np.ascontiguousarray(raw).view(np.uint8).ravel()
    lib = _load()
    if lib and compression in (COMP_NONE, COMP_ZLIB):
        # capacity covers zlib's worst case (compressBound ~ raw + raw/1000 + 64)
        # plus header; the C side stores raw on any compress failure.
        cap = HDR_LEN + len(raw) + len(raw) // 1000 + 4096
        dst = np.empty(cap, dtype=np.uint8)
        n = lib.gg_block_encode(raw.ctypes.data, len(raw), ctypes.c_uint32(nrows),
                                compression, level, dst.ctypes.data, cap)
        if n < 0:
            raise IOError("block encode failed")
        return dst[:n].tobytes()
    payload = raw.tobytes()
    comp = compression
    if compression == COMP_ZSTD:
        try:
            import zstandard
        except ModuleNotFoundError:
            # optional codec: degrade the WRITE to zlib instead of failing
            # the statement — the frame header records the codec actually
            # used, so readers never need the missing module. zstd levels
            # go to 22; zlib rejects anything past 9.
            compression = comp = COMP_ZLIB
            level = min(level, 9)
    if compression == COMP_ZLIB:
        c = zlib.compress(payload, level)
        if len(c) < len(payload):
            payload = c
        else:
            comp = COMP_NONE
    elif compression == COMP_ZSTD:
        c = zstandard.ZstdCompressor(level=level).compress(payload)
        if len(c) < len(payload):
            payload = c
        else:
            comp = COMP_NONE
    # the CRC covers the header fields as well as the payload, so flipped
    # metadata (nrows/raw_len/comp_len/codec byte) is caught at decode —
    # bit-identical to gg_block_encode in native/ggcodec.cpp
    hdr = (BLOCK_MAGIC.to_bytes(4, "little") + int(nrows).to_bytes(4, "little")
           + bytes([comp, 0]) + b"\0\0" + len(raw).to_bytes(8, "little")
           + len(payload).to_bytes(8, "little"))
    crc = zlib.crc32(payload, zlib.crc32(hdr)) & 0xFFFFFFFF
    return hdr + crc.to_bytes(4, "little") + payload


def _check_frame_header(frame: bytes) -> tuple[int, int, int, int, int, int]:
    """Validate a frame's header WITHOUT touching the payload.
    -> (nrows, comp, raw_len, comp_len, want_crc, total_len)."""
    if len(frame) < HDR_LEN:
        raise CorruptionError(
            "truncated", f"frame is {len(frame)} bytes, header needs {HDR_LEN}")
    magic = int.from_bytes(frame[:4], "little")
    if magic == 0x47474231:   # "GGB1": the pre-header-CRC layout
        # NOT corruption: old-format data must refuse loudly, never feed
        # the repair/quarantine machinery (which would eat valid files)
        raise IOError(
            "unsupported block format GGB1 (written by an older, "
            "incompatible version) — re-ingest from original sources")
    if magic != BLOCK_MAGIC:
        raise CorruptionError("bad_magic", "bad block magic")
    nrows = int.from_bytes(frame[4:8], "little")
    comp = frame[8]
    raw_len = int.from_bytes(frame[12:20], "little")
    comp_len = int.from_bytes(frame[20:28], "little")
    want_crc = int.from_bytes(frame[28:32], "little")
    total = HDR_LEN + comp_len
    if len(frame) < total:
        raise CorruptionError(
            "truncated",
            f"frame payload truncated ({len(frame)} bytes, header claims {total})")
    # bound raw_len BEFORE any allocation: the native fast path allocates
    # its output buffer ahead of the CRC check, so a flipped length must
    # not drive a huge malloc first. zlib expands at most ~1032:1 and
    # stored-raw is 1:1; zstd frames never reach a pre-CRC allocation
    # (python path checks the CRC before decompressing), so a legitimate
    # high-ratio zstd frame is NOT rejected here.
    if raw_len < 0 or (comp == COMP_NONE and raw_len != comp_len) \
            or (comp == COMP_ZLIB and raw_len > comp_len * 1032 + 4096):
        raise CorruptionError(
            "decode_failed",
            f"implausible frame lengths (raw {raw_len}, stored {comp_len})")
    return nrows, comp, raw_len, comp_len, want_crc, total


def block_decode(frame: bytes) -> tuple[bytes, int, int]:
    """-> (raw bytes, nrows, frame length consumed). Verifies the frame
    checksum (header + payload); all failures raise the typed
    CorruptionError so readers can classify repair vs quarantine."""
    nrows, comp, raw_len, comp_len, want_crc, total = \
        _check_frame_header(frame)
    lib = _load()
    if lib and comp in (COMP_NONE, COMP_ZLIB):
        src = np.frombuffer(frame[:total], dtype=np.uint8)
        dst = np.empty(max(raw_len, 1), dtype=np.uint8)
        nrows_out = ctypes.c_uint32()
        n = lib.gg_block_decode(src.ctypes.data, len(src), dst.ctypes.data, len(dst),
                                ctypes.byref(nrows_out))
        if n == -2:
            raise CorruptionError("crc_mismatch", "block checksum mismatch")
        if n == -1:
            raise CorruptionError("bad_magic", "bad block magic")
        if n < 0:
            raise CorruptionError("decode_failed", f"block decode failed ({n})")
        return dst[:n].tobytes(), nrows_out.value, total
    payload = frame[HDR_LEN:total]
    crc = zlib.crc32(payload, zlib.crc32(frame[: HDR_LEN - 4])) & 0xFFFFFFFF
    if crc != want_crc:
        raise CorruptionError("crc_mismatch", "block checksum mismatch")
    if comp == COMP_ZLIB:
        try:
            raw = zlib.decompress(payload)
        except zlib.error as e:
            raise CorruptionError("decode_failed", f"zlib decompress failed: {e}")
    elif comp == COMP_ZSTD:
        try:
            import zstandard
        except ModuleNotFoundError:
            raise IOError(
                "block is zstd-compressed but the optional 'zstandard' "
                "module is not installed on this host")

        try:
            raw = zstandard.ZstdDecompressor().decompress(payload, max_output_size=raw_len)
        except zstandard.ZstdError as e:
            raise CorruptionError("decode_failed", f"zstd decompress failed: {e}")
    elif comp == COMP_NONE:
        if raw_len != comp_len:
            raise CorruptionError(
                "decode_failed",
                f"stored-raw frame length mismatch ({comp_len} != {raw_len})")
        raw = bytes(payload)
    else:
        raise CorruptionError("decode_failed", f"unknown compression {comp}")
    if len(raw) != raw_len:
        raise CorruptionError(
            "decode_failed",
            f"decoded {len(raw)} bytes, header claims {raw_len}")
    return raw, nrows, total


def block_decode_into(frame: bytes, dst: np.ndarray) -> tuple[int, int]:
    """Decode one frame's rows DIRECTLY into ``dst`` (a contiguous uint8
    view of the destination slot) — the in-place staging path: no
    intermediate bytes object, no post-decode copy. Same verification and
    CorruptionError classification as block_decode. -> (bytes written,
    nrows)."""
    nrows, comp, raw_len, comp_len, want_crc, total = \
        _check_frame_header(frame)
    if raw_len > len(dst):
        raise CorruptionError(
            "rowcount_mismatch",
            f"frame holds {raw_len} bytes, destination slot is {len(dst)}")
    lib = _load()
    if lib and comp in (COMP_NONE, COMP_ZLIB):
        src = np.frombuffer(frame[:total], dtype=np.uint8)
        nrows_out = ctypes.c_uint32()
        n = lib.gg_block_decode(src.ctypes.data, len(src),
                                dst.ctypes.data, len(dst),
                                ctypes.byref(nrows_out))
        if n == -2:
            raise CorruptionError("crc_mismatch", "block checksum mismatch")
        if n == -1:
            raise CorruptionError("bad_magic", "bad block magic")
        if n < 0:
            raise CorruptionError("decode_failed", f"block decode failed ({n})")
        return int(n), int(nrows_out.value)
    raw, nrows, _total = block_decode(frame)
    if len(raw) > len(dst):
        raise CorruptionError(
            "rowcount_mismatch",
            f"block holds {len(raw)} bytes, destination slot is {len(dst)}")
    dst[:len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return len(raw), nrows
