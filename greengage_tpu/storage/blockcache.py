"""Byte-accounted LRU block-cache registry — the bufmgr analog.

Reference parity: the shared buffer pool (src/backend/storage/buffer/
bufmgr.c) gives every read path one bounded, recency-evicting cache with
hit/miss/eviction accounting. Our reproduction grew six ad-hoc dict caches
(raw chunks, host predicates, raw codes, packed prefixes, deletion masks,
staged device inputs), each with its own "pop the first key" pseudo-
eviction — which evicts INSERTION order, not recency, and none of which
bound actual bytes. This module replaces all of them:

  - ``CacheRegistry`` owns one global byte budget (the ``scan_cache_limit_mb``
    GUC, read live from the wired settings) shared by every named cache.
  - ``BlockCache`` is one named member: an OrderedDict in recency order
    (every hit moves the entry to MRU), so the registry's eviction scan can
    find the GLOBAL least-recently-used entry by comparing each cache's
    head tick.
  - Entries carry their byte size (``nbytes_of`` estimates when the caller
    doesn't know) and an optional manifest version tag;
    ``invalidate_versions(keep)`` drops every tagged entry from another
    version — the CdbComponentDatabases/relcache invalidation analog for
    a manifest bump (DML, index build, expansion).
  - ``scan_cache_hit`` / ``scan_cache_miss`` / ``scan_cache_evict``
    counters land in the runtime.logger registry so EXPLAIN ANALYZE and
    tests can assert cache behavior without wall clocks.

Thread safety: one registry RLock covers every operation — the staging
thread pool hits these caches from many threads at once.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from greengage_tpu.runtime import lockdebug, memaccount, overload
from greengage_tpu.runtime.logger import counters

MISS = object()   # sentinel distinguishing "absent" from a cached None

DEFAULT_LIMIT_MB = 1024


def nbytes_of(value) -> int:
    """Best-effort byte estimate of a cached value (numpy / jax arrays
    report exactly; containers sum their members; scalars cost a token)."""
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        try:
            return int(nb) + 64
        except (TypeError, ValueError):
            pass
    if isinstance(value, (tuple, list)):
        return 64 + sum(nbytes_of(v) for v in value)
    if isinstance(value, dict):
        return 64 + sum(nbytes_of(v) for v in value.values())
    if isinstance(value, (str, bytes)):
        return 64 + len(value)
    return 64


class BlockCache:
    """One named cache inside a registry. All mutation happens under the
    registry lock; entries are (value, nbytes, version, tick)."""

    def __init__(self, registry: "CacheRegistry", name: str):
        self.registry = registry
        self.name = name
        # access-witnessed under GGTPU_RACE_DEBUG: every touch must hold
        # the registry lock (docs/ANALYSIS.md "Race analysis")
        self._d: OrderedDict = lockdebug.shared(OrderedDict(),
                                                f"blockcache.{name}._d")
        self.bytes = 0

    # -- reads ----------------------------------------------------------
    def get(self, key, default=None):
        reg = self.registry
        with reg._lock:
            ent = self._d.get(key)
            if ent is None:
                counters.inc("scan_cache_miss")
                return default
            self._d.move_to_end(key)
            ent[3] = reg._next_tick()
            counters.inc("scan_cache_hit")
            return ent[0]

    def peek(self, key, default=None):
        """Read without touching recency or hit/miss counters."""
        with self.registry._lock:
            ent = self._d.get(key)
            return default if ent is None else ent[0]

    def __contains__(self, key) -> bool:
        with self.registry._lock:
            return key in self._d

    def __len__(self) -> int:
        with self.registry._lock:
            return len(self._d)

    def keys(self) -> list:
        with self.registry._lock:
            return list(self._d.keys())

    # -- writes ---------------------------------------------------------
    def put(self, key, value, nbytes: int | None = None,
            version: int | None = None) -> None:
        nb = nbytes_of(value) if nbytes is None else int(nbytes) + 64
        reg = self.registry
        with reg._lock:
            old = self._d.pop(key, None)
            if old is not None:
                self.bytes -= old[1]
                reg._total -= old[1]
            if nb > reg.limit_bytes():
                # an entry bigger than the WHOLE budget can never be
                # resident: refuse it outright rather than evicting every
                # other cache's warm state on its behalf and then evicting
                # it anyway
                return
            self._d[key] = [value, nb, version, reg._next_tick()]
            self.bytes += nb
            reg._total += nb
            reg._evict_to_fit()
        # memory accounting (runtime/memaccount.py): attribute the bytes
        # this statement INSERTED into the shared cache to its
        # 'blockcache' owner — charged OUTSIDE the registry lock so the
        # account lock never nests under it (lock-order hygiene). Pool
        # threads reach here bound to the statement's account.
        memaccount.charge("blockcache", nb, item=self.name)

    def pop(self, key, default=None):
        with self.registry._lock:
            ent = self._d.pop(key, None)
            if ent is None:
                return default
            self.bytes -= ent[1]
            self.registry._total -= ent[1]
            return ent[0]

    def clear(self) -> None:
        with self.registry._lock:
            self.registry._total -= self.bytes
            self.bytes = 0
            self._d.clear()

    def drop(self, pred) -> int:
        """Remove entries whose KEY satisfies ``pred``; -> count removed."""
        with self.registry._lock:
            victims = [k for k in self._d if pred(k)]
            for k in victims:
                ent = self._d.pop(k)
                self.bytes -= ent[1]
                self.registry._total -= ent[1]
            return len(victims)


class CacheRegistry:
    """Shared byte budget + global-LRU eviction over named BlockCaches."""

    def __init__(self, limit_mb: int | None = None):
        self._lock = lockdebug.named(threading.RLock(),
                                     "blockcache.registry._lock")
        self._caches: dict[str, BlockCache] = {}
        self._tick = 0
        self._total = 0
        self._limit_mb = limit_mb
        # wired by the session (Database.__init__); read live so
        # SET scan_cache_limit_mb applies to the next eviction decision
        self.settings = None

    def cache(self, name: str) -> BlockCache:
        with self._lock:
            c = self._caches.get(name)
            if c is None:
                c = self._caches[name] = BlockCache(self, name)
            return c

    def limit_bytes(self) -> int:
        mb = None
        if self.settings is not None:
            mb = getattr(self.settings, "scan_cache_limit_mb", None)
        if mb is None:
            mb = self._limit_mb if self._limit_mb is not None \
                else DEFAULT_LIMIT_MB
        base = max(int(mb), 1) << 20
        # memory-pressure brownout (runtime/overload.py): under device
        # pressure the shared budget shrinks by the brownout cache
        # factor — read live, so SET and state transitions apply to the
        # next eviction decision, exactly like the GUC itself
        factor = overload.CONTROLLER.cache_factor()
        if factor >= 1.0:
            return base
        return max(int(base * factor), 1 << 20)

    def evict_to_fit(self) -> None:
        """Public eviction-to-budget pass: applied on a brownout
        transition edge so the shrunken budget frees bytes NOW instead
        of waiting for the next insert."""
        with self._lock:
            self._evict_to_fit()

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def _next_tick(self) -> int:
        self._tick += 1
        return self._tick

    def _evict_to_fit(self) -> None:
        """Evict the GLOBALLY least-recent entry until under budget. Each
        cache's OrderedDict head is its own LRU, so the global LRU is the
        minimum head tick across caches — O(#caches) per eviction."""
        limit = self.limit_bytes()
        while self._total > limit:
            best = None
            best_cache = None
            for c in self._caches.values():
                if not c._d:
                    continue
                k = next(iter(c._d))
                tick = c._d[k][3]
                if best is None or tick < best[1]:
                    best = (k, tick)
                    best_cache = c
            if best_cache is None:
                return   # nothing left to evict
            ent = best_cache._d.pop(best[0])
            best_cache.bytes -= ent[1]
            self._total -= ent[1]
            counters.inc("scan_cache_evict")

    def invalidate_versions(self, keep_version: int) -> int:
        """Drop every version-tagged entry from another manifest version
        (the manifest-bump invalidation); untagged entries — immutable
        committed files — stay. -> count removed."""
        removed = 0
        with self._lock:
            for c in self._caches.values():
                victims = [k for k, ent in c._d.items()
                           if ent[2] is not None and ent[2] != keep_version]
                for k in victims:
                    ent = c._d.pop(k)
                    c.bytes -= ent[1]
                    self._total -= ent[1]
                removed += len(victims)
        return removed

    def clear(self) -> None:
        with self._lock:
            for c in self._caches.values():
                c._d.clear()
                c.bytes = 0
            self._total = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "total_bytes": self._total,
                "limit_bytes": self.limit_bytes(),
                "caches": {n: {"entries": len(c._d), "bytes": c.bytes}
                           for n, c in self._caches.items()},
            }
