"""Continuous archiving + point-in-time recovery — the WAL-archive analog.

The reference ships WAL segments to an archive (archive_command,
src/backend/access/transam/xlogarchive.c) and replays them to a recovery
target (PITR). This engine's "WAL" is the manifest-version sequence: each
commit atomically publishes manifest v+1 whose file lists fully determine
the cluster contents, and segment files are immutable once written
(append-only storage; DML republishes under NEW filenos). So archiving is:

  per committed version v: copy the manifest (tiny) + the segment files
  NEW since the previously archived version (diffed against its archived
  manifest — incremental by construction, file names embed unique
  filenos and are never rewritten) + the catalog + the append-only
  dictionaries (a newer superset decodes any older version's codes).

Durability details: every file lands via temp-write + os.replace (a
crash mid-copy never leaves a truncated file that looks archived), the
whole archive pass runs under an flock (concurrent per-commit archiving
and `gg archive` catch-up serialize instead of losing index entries),
and the index entry is written last, marking the version complete.
Timestamps are UTC (recovery_target_time comparisons stay monotonic).

PITR rebuilds a cluster directory from the archived manifest at the
requested version/timestamp and the files it references. Restore targets
an EMPTY directory (like pg_basebackup -D), and the restored cluster
starts with mirrors marked unsynced (run `gg replicate` after).
"""

from __future__ import annotations

import datetime
import fcntl
import json
import os
import shutil
import tempfile
from contextlib import contextmanager


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="microseconds")


def _parse_ts(s: str) -> datetime.datetime:
    """Accept both ISO-T and the PG-style 'YYYY-MM-DD HH:MM:SS' recovery
    target form; naive timestamps are taken as UTC."""
    dt = datetime.datetime.fromisoformat(s.replace(" ", "T"))
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


def _atomic_copy(src: str, dst: str) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst), prefix=".arch")
    os.close(fd)
    shutil.copy(src, tmp)
    os.replace(tmp, dst)


def _atomic_write(dst: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst), prefix=".arch")
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    os.replace(tmp, dst)


class Archive:
    def __init__(self, path: str):
        self.path = path

    # ---- layout --------------------------------------------------------
    def _p(self, *parts) -> str:
        return os.path.join(self.path, *parts)

    @contextmanager
    def _locked(self):
        os.makedirs(self.path, exist_ok=True)
        with open(self._p(".lock"), "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            yield

    def _index(self) -> dict:
        try:
            with open(self._p("index.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"versions": {}}

    def _save_index(self, idx: dict) -> None:
        _atomic_write(self._p("index.json"),
                      json.dumps(idx, indent=1).encode())

    def versions(self) -> list[tuple[int, str]]:
        idx = self._index()
        return sorted((int(v), meta["ts"])
                      for v, meta in idx["versions"].items())

    # ---- archive one committed version ---------------------------------
    def archive_now(self, cluster_path: str, store) -> int | None:
        """Archive the cluster's CURRENT committed snapshot. Returns the
        version newly archived, or None if it was already archived (the
        catalog copy is still refreshed then — DDL changes the catalog
        without bumping the manifest version)."""
        with self._locked():
            return self._archive_locked(cluster_path, store)

    def _archive_locked(self, cluster_path: str, store) -> int | None:
        snap = store.manifest.snapshot()
        v = snap.get("version", 0)
        idx = self._index()
        cat_src = os.path.join(cluster_path, "catalog.json")
        if str(v) in idx["versions"]:
            # segment data for v is complete; catalog-only DDL since then
            # lands as a NEW timestamped catalog revision — never
            # overwriting an earlier one (a DROP TABLE must not destroy
            # the archive's ability to restore the pre-drop catalog)
            ent = idx["versions"][str(v)]
            revs = ent.setdefault("catalogs", [{"k": 0, "ts": ent["ts"]}])
            last_k = revs[-1]["k"]
            with open(cat_src, "rb") as f:
                cur = f.read()
            try:
                with open(self._p("catalogs",
                                  f"catalog.{v}.{last_k}.json"), "rb") as f:
                    old = f.read()
            except OSError:
                old = None
            if cur != old:
                k = last_k + 1
                _atomic_write(self._p("catalogs", f"catalog.{v}.{k}.json"),
                              cur)
                revs.append({"k": k, "ts": _utcnow()})
                self._save_index(idx)
            return None
        # diff against the newest archived version's manifest: only files
        # new since then need copying (plus belt-and-braces existence
        # checks — atomic copies mean an existing file IS complete)
        prev_rels: set = set()
        archived = [int(k) for k in idx["versions"]]
        if archived:
            pv = max(archived)
            try:
                with open(self._p("manifests", f"manifest.{pv}.json")) as f:
                    pm = json.load(f)
                for tname, tmeta in pm.get("tables", {}).items():
                    for files in tmeta["segfiles"].values():
                        for rel in files:
                            prev_rels.add((tname, rel))
            except (OSError, ValueError):
                pass   # fall back to per-file existence checks
        copied = 0
        for tname, tmeta in snap["tables"].items():
            dst_base = self._p("files", tname)
            for segkey, files in tmeta["segfiles"].items():
                # reads follow the store's failover redirect: a promoted
                # mirror's tree holds this content's current files
                src_base = os.path.join(store.data_root(int(segkey)), tname)
                for rel in files:
                    dst = os.path.join(dst_base, rel)
                    if (tname, rel) in prev_rels or os.path.exists(dst):
                        continue
                    _atomic_copy(os.path.join(src_base, rel), dst)
                    copied += 1
            # dictionaries: append-only -> latest copy serves all
            # versions; skip when the size is unchanged. Partition
            # children ('t#p1') share the PARENT's dictionary files
            parent = tname.split("#", 1)[0]
            src_dict_base = os.path.join(cluster_path, "data", parent)
            dict_dst_base = self._p("files", parent)
            if os.path.isdir(src_dict_base):
                for fn in os.listdir(src_dict_base):
                    if not fn.startswith("dict_"):
                        continue
                    src = os.path.join(src_dict_base, fn)
                    dst = os.path.join(dict_dst_base, fn)
                    try:
                        if os.path.getsize(dst) == os.path.getsize(src):
                            continue
                    except OSError:
                        pass
                    _atomic_copy(src, dst)
        _atomic_write(self._p("manifests", f"manifest.{v}.json"),
                      json.dumps(snap, indent=1).encode())
        with open(cat_src, "rb") as f:
            _atomic_write(self._p("catalogs", f"catalog.{v}.0.json"),
                          f.read())
        # index entry LAST: it marks the version complete
        idx = self._index()
        ts = _utcnow()
        idx["versions"][str(v)] = {"ts": ts, "files": copied,
                                   "catalogs": [{"k": 0, "ts": ts}]}
        self._save_index(idx)
        return v

    # ---- PITR ----------------------------------------------------------
    def resolve_target(self, version: int | None = None,
                       time: str | None = None) -> int:
        """Recovery target: the newest archived version <= the requested
        version / UTC timestamp (recovery_target_time semantics)."""
        vs = self.versions()
        if not vs:
            raise ValueError("archive is empty")
        if version is None and time is None:
            return vs[-1][0]
        target = _parse_ts(time) if time is not None else None
        best = None
        for v, ts in vs:
            if version is not None and v > version:
                continue
            if target is not None and _parse_ts(ts) > target:
                continue
            best = v if best is None else max(best, v)
        if best is None:
            raise ValueError(
                f"no archived version at or before the requested target "
                f"(earliest is v{vs[0][0]} @ {vs[0][1]})")
        return best

    def restore(self, target_dir: str, version: int | None = None,
                time: str | None = None) -> int:
        """Rebuild a cluster directory at the recovery target. The
        manifest is written LAST so a half-restored directory is never
        openable as a valid cluster."""
        v = self.resolve_target(version, time)
        os.makedirs(target_dir, exist_ok=True)
        if os.path.exists(os.path.join(target_dir, "manifest.json")):
            raise ValueError(
                f"refusing to restore into {target_dir}: already a cluster "
                "(manifest.json exists)")
        with open(self._p("manifests", f"manifest.{v}.json")) as f:
            snap = json.load(f)
        # catalog revision: with a time target, the last revision at or
        # before it (recovers schemas later DDL dropped); otherwise the
        # latest revision of the target version
        revs = self._index()["versions"][str(v)].get(
            "catalogs", [{"k": 0, "ts": ""}])
        k = revs[-1]["k"]
        if time is not None:
            target = _parse_ts(time)
            eligible = [r["k"] for r in revs
                        if not r["ts"] or _parse_ts(r["ts"]) <= target]
            k = eligible[-1] if eligible else revs[0]["k"]
        with open(self._p("catalogs", f"catalog.{v}.{k}.json")) as f:
            cat = json.load(f)
        # restored files always land in the preferred data/ layout, so
        # acting roles from the archived catalog (e.g. a promoted mirror)
        # must be reset: role := preferred_role, primaries up, mirrors
        # down+unsynced until rebuilt (FTS must not promote them)
        for ent in cat.get("segments", {}).get("entries", []):
            ent["role"] = ent.get("preferred_role", ent.get("role"))
            if ent["role"] == "m":
                ent["synced"] = False
                ent["status"] = "d"
                ent["device_index"] = None
            else:
                ent["status"] = "u"
                if ent.get("content", -1) >= 0:
                    # a promotion moves the device binding to the mirror
                    # entry; restored primaries must get it back
                    ent["device_index"] = ent["content"]
        with open(os.path.join(target_dir, "catalog.json"), "w") as f:
            json.dump(cat, f, indent=1)
        for tname, tmeta in snap["tables"].items():
            src_base = self._p("files", tname)
            dst_base = os.path.join(target_dir, "data", tname)
            # dictionaries live under the PARENT name for partition children
            parent = tname.split("#", 1)[0]
            pdict_src = self._p("files", parent)
            pdict_dst = os.path.join(target_dir, "data", parent)
            if os.path.isdir(pdict_src):
                for fn in os.listdir(pdict_src):
                    if fn.startswith("dict_"):
                        os.makedirs(pdict_dst, exist_ok=True)
                        shutil.copy(os.path.join(pdict_src, fn),
                                    os.path.join(pdict_dst, fn))
            for files in tmeta["segfiles"].values():
                for rel in files:
                    dst = os.path.join(dst_base, rel)
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy(os.path.join(src_base, rel), dst)
        with open(os.path.join(target_dir, "manifest.json"), "w") as f:
            json.dump(snap, f, indent=1)
        return v
