"""Native CSV ingest: ctypes bindings over ggcodec's csv functions.

The COPY hot path (reference: fstream + gpfdist parsing). Quoted files and
exotic options fall back to Python's csv module in the session layer.
"""

from __future__ import annotations

import ctypes

import numpy as np

from greengage_tpu import types as T
from greengage_tpu.storage import native


class CsvFallback(Exception):
    """Raised when the fast path can't handle the input (quotes, etc.)."""


def _lib():
    lib = native._load()
    if not lib:
        raise CsvFallback("native library unavailable")
    if not hasattr(lib, "_csv_ready"):
        lib.gg_csv_index.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint8, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.gg_csv_index.restype = ctypes.c_int64
        for fn in (lib.gg_parse_i64, lib.gg_parse_f64, lib.gg_parse_date):
            fn.restype = ctypes.c_int64
        lib.gg_parse_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.gg_parse_f64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        lib.gg_parse_date.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        lib._csv_ready = True
    return lib


def parse_file(path: str, schema, delimiter: str = ",", header: bool = False,
               null_marker: str = ""):
    """Parse a CSV file natively into storage-representation columns.

    -> (cols {name: np.ndarray | list[str]}, valids {name: bool array}).
    Raises CsvFallback when the file needs the quoting-aware Python reader.
    TEXT columns come back as Python strings (dictionary encoding happens in
    the store); a non-empty null_marker also falls back (the fast path's
    NULL is the empty field, PG's CSV default).
    """
    if len(delimiter) != 1 or null_marker not in ("",):
        raise CsvFallback("options need the python reader")
    lib = _lib()
    with open(path, "rb") as f:
        buf = np.frombuffer(f.read(), dtype=np.uint8)
    if buf.size == 0:
        return ({c.name: np.empty(0, dtype=c.type.np_dtype) if c.type.kind
                 is not T.Kind.TEXT else [] for c in schema.columns}, {})
    ncols = len(schema.columns)
    cap = int(buf.size // 2) + ncols + 16
    starts = np.empty(cap, dtype=np.int64)
    lens = np.empty(cap, dtype=np.int32)
    nf = lib.gg_csv_index(buf.ctypes.data, buf.size, ord(delimiter), cap,
                          starts.ctypes.data, lens.ctypes.data)
    if nf == -2:
        raise CsvFallback("quoted fields")
    if nf < 0:
        raise CsvFallback("field capacity")
    if nf % ncols != 0:
        raise ValueError(
            f"CSV arity mismatch: {nf} fields is not a multiple of {ncols} columns")
    nrows = nf // ncols
    if header:
        starts = starts[ncols:]
        lens = lens[ncols:]
        nrows -= 1
    cols: dict = {}
    valids: dict = {}
    raw = buf.tobytes()   # one copy, shared by all TEXT columns
    for i, c in enumerate(schema.columns):
        k = c.type.kind
        if k is T.Kind.TEXT:
            s = starts[i::ncols][:nrows]
            ln = lens[i::ncols][:nrows]
            cols[c.name] = [raw[a:a + b].decode("utf-8")
                            for a, b in zip(s, ln)]
            va = ln > 0   # empty field = NULL (PG CSV default, python path parity)
            if not va.all():
                valids[c.name] = np.asarray(va, dtype=bool)
            continue
        if k is T.Kind.BOOL:
            raise CsvFallback("bool literals need the python reader")
        valid = np.empty(nrows, dtype=np.uint8)
        if k in (T.Kind.INT32, T.Kind.INT64, T.Kind.DECIMAL):
            out = np.empty(nrows, dtype=np.int64)
            scale = c.type.scale if k is T.Kind.DECIMAL else 0
            rc = lib.gg_parse_i64(buf.ctypes.data, starts.ctypes.data,
                                  lens.ctypes.data, nrows, ncols, i, scale,
                                  out.ctypes.data, valid.ctypes.data)
        elif k is T.Kind.FLOAT64:
            out = np.empty(nrows, dtype=np.float64)
            rc = lib.gg_parse_f64(buf.ctypes.data, starts.ctypes.data,
                                  lens.ctypes.data, nrows, ncols, i,
                                  out.ctypes.data, valid.ctypes.data)
        elif k is T.Kind.DATE:
            out = np.empty(nrows, dtype=np.int32)
            rc = lib.gg_parse_date(buf.ctypes.data, starts.ctypes.data,
                                   lens.ctypes.data, nrows, ncols, i,
                                   out.ctypes.data, valid.ctypes.data)
        else:
            raise CsvFallback(f"type {c.type}")
        if rc < 0:
            raise ValueError(
                f'COPY: invalid value for column "{c.name}" at row {-rc}')
        if k is T.Kind.INT32:
            bad = (out < -(2**31)) | (out >= 2**31)
            if bad.any():
                row = int(np.argmax(bad)) + 1
                raise ValueError(
                    f'COPY: value out of range for int column "{c.name}" '
                    f"at row {row}")
        cols[c.name] = out.astype(c.type.np_dtype, copy=False)
        va = valid.astype(bool)
        if not va.all():
            valids[c.name] = va
    return cols, valids
