"""Typed storage-corruption errors — the AO block-checksum failure model.

Reference parity: the reference classifies append-only storage damage at
the point of detection (``cdbappendonlystorageformat.c`` errors carry the
file, block and header kind; ``appendonly_verify_block_checksums``
distinguishes header vs content checksums). Ours is one exception type
with a ``cause`` taxonomy so the read path, the scrubber, and tests can
dispatch on WHAT failed, and a location (table / content / relpath /
block) attached as it propagates up through the layers that know it.

``CorruptionError`` subclasses ``IOError`` so pre-existing handlers of
storage read failures keep working unchanged.
"""

from __future__ import annotations

# cause taxonomy (stable strings: quarantine sidecars + tests use them)
BAD_MAGIC = "bad_magic"              # frame header magic mismatch
CRC_MISMATCH = "crc_mismatch"        # frame checksum mismatch
TRUNCATED = "truncated"              # file/frame shorter than its header claims
BAD_FOOTER = "bad_footer"            # footer magic/checksum/JSON/dtype damage
ROWCOUNT_MISMATCH = "rowcount_mismatch"  # decoded rows != header/footer rows
DECODE_FAILED = "decode_failed"      # decompression/layout failure past the CRC
MISSING = "missing"                  # manifest-referenced file is gone

CAUSES = (BAD_MAGIC, CRC_MISMATCH, TRUNCATED, BAD_FOOTER,
          ROWCOUNT_MISMATCH, DECODE_FAILED, MISSING)


class CorruptionError(IOError):
    """A block file (or one frame of it) failed verification.

    Raised typed from the codec (`storage/native.py`) and the file layer
    (`storage/blockfile.py`) with ``cause`` + ``path``; the store layer
    (`storage/table_store.py`) locates it (table, content, relpath) before
    deciding repair vs quarantine.
    """

    def __init__(self, cause: str, message: str | None = None, *,
                 path: str | None = None, table: str | None = None,
                 content: int | None = None, relpath: str | None = None,
                 block: int | None = None):
        assert cause in CAUSES, cause
        self.cause = cause
        self.message = message or cause.replace("_", " ")
        self.path = path
        self.table = table
        self.content = content
        self.relpath = relpath
        self.block = block
        super().__init__(self._render())

    def _render(self) -> str:
        where = (f"{self.table}/{self.relpath}"
                 if self.table and self.relpath
                 else (self.path or self.relpath or "<unknown file>"))
        blk = f" block {self.block}" if self.block is not None else ""
        seg = f" (content {self.content})" if self.content is not None else ""
        return f"corrupt storage {where}{blk}{seg}: {self.message} [{self.cause}]"

    def locate(self, **kw) -> "CorruptionError":
        """Fill in location fields the raising layer didn't know (never
        overwrites one already set) and refresh the rendered message."""
        for k, v in kw.items():
            if getattr(self, k, None) is None:
                setattr(self, k, v)
        self.args = (self._render(),)
        return self

    def to_dict(self) -> dict:
        """JSON-able record (the quarantine sidecar body)."""
        return {
            "cause": self.cause,
            "message": self.message,
            "path": self.path,
            "table": self.table,
            "content": self.content,
            "relpath": self.relpath,
            "block": self.block,
        }
