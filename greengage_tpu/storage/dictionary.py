"""Append-only string dictionaries for TEXT columns.

Codes are assigned in first-seen order and never change, so a row's
distribution placement (which hashes the *string bytes*, not the code) and
any stored code remain stable across appends. Dictionaries are table-global
(shared by all segments) so equality joins/group-bys on a single table's
column can compare codes directly; cross-table text comparisons go through
host-built code translation tables (see ops/expr.py).
"""

from __future__ import annotations

import json
import os
import tempfile
import hashlib

import numpy as np

from greengage_tpu.storage import native


class Dictionary:
    def __init__(self, values: list[str] | None = None):
        self.values: list[str] = list(values or [])
        self._index: dict[str, int] = {v: i for i, v in enumerate(self.values)}
        self._digest: str | None = None
        self._digest_len = -1

    def __len__(self) -> int:
        return len(self.values)

    def fingerprint(self) -> str:
        """Content digest for the executor's executable-cache shape
        signature: compiled programs bake this dictionary's hash/rank LUTs,
        so an executable is reusable only while the content is identical.
        Dictionaries are append-only, which makes the cached digest
        invalidatable by length alone."""
        if self._digest is None or self._digest_len != len(self.values):
            h = hashlib.sha1()
            for v in self.values:
                h.update(v.encode("utf-8", "surrogatepass"))
                h.update(b"\x00")
            self._digest = h.hexdigest()[:16]
            self._digest_len = len(self.values)
        return self._digest

    def encode(self, strings) -> np.ndarray:
        """Map strings -> int32 codes, appending unseen values."""
        out = np.empty(len(strings), dtype=np.int32)
        idx = self._index
        vals = self.values
        for i, s in enumerate(strings):
            code = idx.get(s)
            if code is None:
                code = len(vals)
                vals.append(s)
                idx[s] = code
            out[i] = code
        return out

    def decode(self, codes: np.ndarray) -> list[str]:
        return [self.values[c] for c in codes]

    def encode_coded(self, vocab: list[str], codes: np.ndarray) -> np.ndarray:
        """Bulk path: encode only the (small) vocabulary through the normal
        append path, then remap the per-row code array vectorized — O(|vocab|)
        Python work for any number of rows."""
        mapping = self.encode(vocab)
        return mapping.astype(np.int32)[codes]

    def lookup(self, s: str) -> int:
        """Code for s, or -1 if absent (absent ⇒ no row equals s)."""
        return self._index.get(s, -1)

    def hashes(self, seed: int = 0) -> np.ndarray:
        """Per-entry uint32 distribution hashes (device motion LUT), plus
        one sentinel row (hash 0) so translated code -1 (string absent from
        this dictionary) negative-indexes onto the sentinel instead of
        silently hashing as the last real entry."""
        return np.array(
            [native.hash_bytes(v.encode("utf-8"), seed) for v in self.values]
            + [0],
            dtype=np.uint32,
        )

    # ---- persistence ---------------------------------------------------
    def save(self, path: str) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".", prefix=".dict")
        with os.fdopen(fd, "w") as f:
            json.dump(self.values, f)
            f.flush()
            os.fsync(f.fileno())  # commit-critical: codes referenced by committed blocks
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "Dictionary":
        if not os.path.exists(path):
            return Dictionary()
        with open(path) as f:
            return Dictionary(json.load(f))
