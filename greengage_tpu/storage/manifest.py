"""Manifest-based MVCC commit — the distributed-visibility analog.

The reference achieves cluster-wide atomic visibility with 2PC + the
distributed log (src/backend/cdb/cdbtm.c, access/transam/distributedlog.c).
Our storage is append-only (no in-place update), so a transaction's writes
are invisible staged files until a commit record publishes them. Two commit
paths share one snapshot space:

ROOT path (structural: CREATE/DROP/width changes, and checkpoint folds):
  prepare(tx): durably stage the next root as manifest.<v>.prepared
  commit(v):   atomically replace manifest.json  (commit point)
  abort(v):    delete the staged root

DELTA path (table-state writes: INSERT/DELETE/UPDATE/delmask) — the
per-segment-WAL analog that keeps writers to DIFFERENT tables off one
global CAS:
  prepare_delta(tx, tables): stage one per-table delta file per written
      table under deltas/, claimed with an EXCLUSIVE hard link on the
      table's next sequence number — the CAS is PER TABLE, so concurrent
      appenders to different tables never conflict
  commit_delta(handle): append ONE fsynced line to commits.log (O_APPEND;
      the line is the atomic multi-table commit record, and the log's
      prefix order is the cluster-wide total order of delta commits)
  abort_delta(handle): unlink the staged delta files (release the claims)

WRITE-INTENT path (append-only commits: hot-table INSERT/COPY and the
streaming ingest plane) — the distributedlog + visimap analog that takes
same-table appenders off the per-table claim entirely:
  stage_intent(table, records): durably stage a per-writer intent record
      under intents/, named by the writer's txid — txid-unique names mean
      N same-table appenders stage concurrently with ZERO claim retries
      by construction
  commit_intent(handle): append ONE fsynced MERGE line ({"w": ...}) to
      commits.log carrying the new segfile records INLINE, then remove
      the intent file. Compose never reads intent files: a merge line
      extends the table's segfiles/nrows instead of replacing its state,
      so appenders commute with each other and overlapping DELETE/UPDATE
      is arbitrated by row visibility (the delmask covers a PREFIX of the
      manifest row order; rows appended after the mask was computed are
      implicitly live — the visimap discipline).
  State-REPLACING delta commits are fenced against in-flight merges by a
  per-table intent sequence (iseq): prepare_delta validates the writer's
  base iseq and commit_delta re-validates it under the commit-log flock,
  so a full-state line can never silently clobber a merge that landed
  after its snapshot (the loser gets a clean write-write conflict).

Readers snapshot the composed state (root + committed deltas in log
order) once per query, so concurrent loads never tear a scan (snapshot
isolation). The effective version = root version + applied delta count is
total-ordered by the log prefix, so equal versions always denote equal
states (cache keys stay sound). fold() — the checkpoint — rewrites the
root at the current effective state, advances the log offset, and GC's
the folded delta files; recover() additionally compacts the log itself.

Crash matrix (docs/ROBUSTNESS.md):
  * kill-9 after prepare_delta, before commit_delta: the staged delta
    files block the table's next sequence (same-table writers conflict,
    exactly like a stale root claim) until recover() rolls them back.
  * kill-9 after the commit line is durable: the commit survives; fold /
    recover() fold it into the root eventually.
  * kill-9 mid-fold: the root replace is atomic; a replayed line whose
    sequence is <= the root's folded sequence is skipped, so the fold is
    idempotent and no committed row is ever lost.
  * kill-9 after stage_intent, before the merge line is durable: the
    intent file is in-doubt evidence only (no reader depends on it) —
    recover() rolls it back exactly like a stale delta claim, and the
    appended rows' segfiles are unreferenced orphans for the sweep.
  * kill-9 after the merge line is durable, before the intent file is
    removed: the commit survives (the line carries the records); the
    leftover intent marker is plain garbage recover()/GC sweeps.
"""

from __future__ import annotations

import fcntl
import json
import os
import tempfile
import threading
import time
import uuid

from greengage_tpu.runtime import lockdebug
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters


class IntentConflict(RuntimeError):
    """A state-replacing commit lost to a write-intent merge that landed
    after its snapshot (or a parked intent expired before resolving).
    Subclasses RuntimeError so every existing write-write-conflict
    handler keeps working; callers that can safely re-stage against a
    fresh snapshot (delmask publishes — the bitmap covers a prefix of
    the row order, so merged appends stay implicitly live) catch THIS
    type to retry, while full-rewrite publishes must surface it."""


class ManifestError(RuntimeError):
    """FATAL: the cluster's commit record is unreadable. Nothing can be
    repaired from segment mirrors (the manifest IS the thing that says
    which files exist) — recover from the standby coordinator, a backup,
    or the archive (docs/ROBUSTNESS.md)."""


class CoordinatorFenced(RuntimeError):
    """This cluster directory has been FENCED by a promoting standby
    (runtime/standby.py write_fence): a paused-not-dead primary woke up
    after its standby took over. Every commit path re-verifies the fence
    at its atomic commit point, so the stale primary cannot fork the
    lineage — the statement dies typed and retryable (SQLSTATE 57P01
    analog: admin/failover shutdown; retry against the promoted
    coordinator's address)."""


class Manifest:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, "manifest.json")
        self.delta_dir = os.path.join(root, "deltas")
        self.intent_dir = os.path.join(root, "intents")
        self.log_path = os.path.join(root, "commits.log")
        # composed-snapshot memo: (root file sig, log file sig) -> the
        # composed state as a JSON string. snapshot() re-parses the string
        # per call so callers can mutate their copy freely (they do — the
        # DTM mutates tx["tables"] nested dicts in place).
        # lockdebug.named: order-asserting wrappers under GGTPU_LOCK_DEBUG
        # (docs/ANALYSIS.md) — the PR-6 chaos storm found its races on
        # exactly these locks; raw threading.Lock when disabled
        self._compose_lock = lockdebug.named(threading.Lock(),
                                             "manifest._compose_lock")
        self._compose_key = None
        self._compose_json = None
        self._compose_meta: dict = {"seqs": {}, "iseqs": {}, "applied": 0,
                                    "log_end": 0, "root_version": 0}
        # parsed delta-file contents; immutable once committed, keyed
        # (table, seq). Bounded: cleared whenever the root is replaced.
        # Own lock (never held across I/O): _read_delta runs OUTSIDE
        # _compose_lock by design (the compose loop re-stats between
        # attempts), and every snapshot-taking role — statements, the
        # serving pipeline, FTS, the spill prefetcher — reaches it
        # concurrently (gg check races).
        self._delta_lock = lockdebug.named(threading.Lock(),
                                           "manifest._delta_lock")
        self._delta_cache: dict = lockdebug.shared(
            {}, "manifest._delta_cache")
        self._log_lock = lockdebug.named(   # in-process append serializer
            threading.Lock(), "manifest._log_lock")
        # serializes the root version-guard check against the replace (two
        # in-process folds must not replace out of order; cross-process
        # ordering is upheld by the staged-claim CAS + guard re-check)
        self._root_commit_lock = lockdebug.named(
            threading.Lock(), "manifest._root_commit_lock")

    # ---- raw root ------------------------------------------------------
    def _root(self) -> dict:
        if not os.path.exists(self.path):
            return {"version": 0, "tables": {}}
        with open(self.path) as f:
            try:
                return json.load(f)
            except ValueError as e:
                # never let a bare JSONDecodeError escape: this is the
                # cluster's commit record, name it and say what to do
                raise ManifestError(
                    f"corrupt manifest at {self.path}: {e} — restore from "
                    "the standby coordinator, a backup, or the archive"
                ) from e

    def _ensure_root(self) -> None:
        """Materialize the empty root before the first delta commit: other
        subsystems (archive restore guard, standby seeding) treat the root
        file's presence as 'this directory is a cluster'."""
        if os.path.exists(self.path):
            return
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".manifest")
        with os.fdopen(fd, "w") as f:
            json.dump({"version": 0, "tables": {}}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, self.path)     # lose the race quietly
        except FileExistsError:
            pass
        os.remove(tmp)

    @staticmethod
    def _sig(path: str):
        try:
            st = os.stat(path)
            return (st.st_ino, st.st_size, st.st_mtime_ns)
        except OSError:
            return None

    def _check_fence(self) -> None:
        """Refuse to commit into a fenced cluster dir. Called inside every
        locked/flocked commit point (atomic with the commit, like the
        intent-token re-check), so a standby promotion that lands between
        a writer's prepare and its commit turns the stale primary's
        commit into a clean typed failure instead of split-brain."""
        faults.check("coordinator_fence")
        fp = os.path.join(self.root, "coordinator.fence")
        if not os.path.exists(fp):
            return
        try:
            with open(fp) as f:
                owner = json.load(f).get("standby", "?")
        except (OSError, ValueError):
            owner = "?"
        raise CoordinatorFenced(
            f"cluster at {self.root} was fenced by promoted standby "
            f"{owner!r}: this coordinator is stale and must not commit — "
            "retry against the promoted coordinator")

    # ---- delta plumbing ------------------------------------------------
    def _delta_path(self, table: str, seq: int) -> str:
        # '#' (partition children) is filesystem-safe; '.' can't appear in
        # table names, so "<table>.<seq>.delta" parses unambiguously
        return os.path.join(self.delta_dir, f"{table}.{seq}.delta")

    def _read_delta(self, table: str, seq: int) -> dict | None:
        path = self._delta_path(table, seq)
        try:
            st = os.stat(path)
        except OSError:
            return None
        # the file's identity is part of the key: a cross-process DROP +
        # re-CREATE restarts the table at seq 1 with a NEW file, and the
        # recreated delta must never be served from the dropped table's
        # cached bytes (only same-process commits clear the cache)
        key = (table, seq, st.st_ino, st.st_mtime_ns)
        with self._delta_lock:
            hit = self._delta_cache.get(key)
        if hit is not None:
            return json.loads(hit)
        try:
            with open(path) as f:
                raw = f.read()
            parsed = json.loads(raw)
        except (OSError, ValueError):
            return None
        with self._delta_lock:
            if len(self._delta_cache) > 512:
                self._delta_cache.clear()   # bound a long-lived reader
            self._delta_cache[key] = raw
        return parsed

    def _log_lines(self, offset: int) -> tuple[list[dict], int]:
        """Complete committed lines from ``offset``; -> (lines, end_offset).
        A torn tail (crash mid-append) ends the committed prefix."""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(offset)
                buf = f.read()
        except OSError:
            return [], offset
        lines: list[dict] = []
        end = offset
        for chunk in buf.split(b"\n"):
            take = end + len(chunk) + 1
            if take > offset + len(buf):
                break       # no trailing newline: torn/in-flight append
            try:
                lines.append(json.loads(chunk))
            except ValueError:
                break       # garbled line: treat as end of committed prefix
            end = take
        return lines, end

    # ---- snapshots -----------------------------------------------------
    def _compose(self) -> dict:
        """Compose root + committed deltas; memoized on file signatures.
        Returns the internal meta dict {json, seqs, applied, log_end,
        root_version, version} — callers must not mutate it."""
        key = (self._sig(self.path), self._sig(self.log_path))
        with self._compose_lock:
            if key == self._compose_key and self._compose_json is not None:
                return self._compose_meta
        last = None
        for _ in range(6):
            meta = self._compose_once()
            # the memo key must be the signatures read BEFORE composing —
            # the state the compose is actually based on. Re-stat'ing
            # after would stamp a concurrent commit's key onto this (now
            # stale) composition, and a later begin() served from the memo
            # would hand out stale base_seqs: a spurious same-table CAS
            # conflict for a writer that is in fact perfectly serialized.
            key2 = (self._sig(self.path), self._sig(self.log_path))
            if meta is not None:
                meta["json"] = meta.pop("_json")
                if key2 == key:
                    with self._compose_lock:
                        self._compose_key = key
                        self._compose_json = meta["json"]
                        self._compose_meta = meta
                    return meta
                last = meta   # consistent, but the base moved: recompose
            # meta None = a concurrent fold GC'd a delta mid-compose; the
            # root moved forward — re-read against the new base either way
            key = key2
        if last is not None:
            # perpetually-moving target (heavy concurrent commit traffic):
            # the last compose is a consistent snapshot initiated within
            # this call — serve it unmemoized
            return last
        raise ManifestError(
            f"manifest compose raced concurrent folds repeatedly under "
            f"{self.root} — delta files referenced by commits.log are "
            "missing")

    def _compose_once(self) -> dict | None:
        root = self._root()
        tables = root.get("tables", {})
        seqs = dict(root.get("delta_seqs", {}))
        iseqs = {t: int(s) for t, s in root.get("intent_seqs", {}).items()}
        log_pos = int(root.get("log_pos", 0))
        lines, log_end = self._log_lines(log_pos)
        applied = 0
        for line in lines:
            entries = line.get("t") or {}
            hit = False
            for table, seq in entries.items():
                seq = int(seq)
                if seq <= int(seqs.get(table, 0)):
                    continue    # folded into the root already (idempotence)
                delta = self._read_delta(table, seq)
                if delta is None:
                    return None     # racing fold GC: recompose
                state = delta.get("state")
                if state is None:
                    tables.pop(table, None)
                    seqs.pop(table, None)
                    iseqs.pop(table, None)
                else:
                    tables[table] = state
                    seqs[table] = seq
                hit = True
            # write-intent MERGE lines ("w"): the records are carried
            # INLINE, so no intent file is ever read here. The iseq bump
            # and `applied` count are UNCONDITIONAL per mentioned table —
            # a compose from an older root replays more merge lines but
            # starts from lower stored intent_seqs, so equal versions
            # keep denoting equal states (cache keys stay sound).
            wents = line.get("w") or {}
            sents = line.get("s") or {}
            for table, recs in wents.items():
                iseqs[table] = iseqs.get(table, 0) + 1
                # a first-ever append creates the table's storage state
                # (the delta path does the same via its staged snapshot);
                # a "w" line cannot resurrect a dropped table because
                # commit_intent's token re-check is atomic with the log
                # append and DROP removes tokens before its tombstone
                state = tables.setdefault(
                    table, {"segfiles": {}, "nrows": {}})
                segfiles = state.setdefault("segfiles", {})
                nrows = state.setdefault("nrows", {})
                for seg, rels, n in recs:
                    files = segfiles.setdefault(str(seg), [])
                    # rel-membership dedup keeps replay on an older root
                    # idempotent (segfile names embed a tx-unique fileno)
                    if rels and rels[0] in files:
                        continue
                    files.extend(rels)
                    nrows[str(seg)] = int(nrows.get(str(seg), 0)) + int(n)
                marks = sents.get(table) or {}
                if marks:
                    # ingest resume watermarks ride the merge line; max()
                    # keeps out-of-order replay and concurrent per-stream
                    # flushes idempotent
                    streams = state.setdefault("streams", {})
                    for sid, mseq in marks.items():
                        streams[sid] = max(int(streams.get(sid, 0)),
                                           int(mseq))
            if wents:
                hit = True
            if hit:
                applied += 1
        version = int(root.get("version", 0)) + applied
        snap = {"version": version, "tables": tables}
        return {"_json": json.dumps(snap), "seqs": seqs, "iseqs": iseqs,
                "applied": applied, "log_end": log_end,
                "root_version": int(root.get("version", 0)),
                "version": version}

    def snapshot(self) -> dict:
        """The committed state: root snapshot + committed per-table deltas
        applied in commit-log order. Fresh objects per call (callers
        mutate their copy)."""
        return json.loads(self._compose()["json"])

    def version(self) -> int:
        return self._compose()["version"]

    def delta_backlog(self) -> int:
        """Committed-but-unfolded delta commits (checkpoint pressure)."""
        return self._compose()["applied"]

    # ---- transactions --------------------------------------------------
    def begin(self) -> dict:
        """Start a write tx from the current snapshot; mutate tx['tables'].
        base_seqs carries the per-table delta sequence the snapshot
        reflects — the delta path's per-table CAS expectation."""
        meta = self._compose()
        snap = json.loads(meta["json"])
        return {"base_version": snap["version"], "tables": snap["tables"],
                "base_seqs": dict(meta["seqs"]),
                "base_iseqs": dict(meta["iseqs"])}

    # ---- ROOT path (structural commits; every root commit is a fold) ---
    def _staged_path(self, version: int) -> str:
        return os.path.join(self.root, f"manifest.{version}.prepared")

    def prepare(self, tx: dict) -> int:
        """Phase 1: durably stage the new root. Returns the new version.

        The staged file is claimed with an EXCLUSIVE hard link: two writers
        racing past the version check cannot both stage version v — the
        loser gets the same write-write conflict it would have gotten from
        the version check (the CAS is atomic, not just check-then-write).
        A root commit folds: its staged content embeds the current delta
        sequences and log offset, so committed deltas are incorporated and
        their files become GC-able at commit."""
        meta = self._compose()
        if meta["version"] != tx["base_version"]:
            counters.inc("manifest_cas_conflict_total")
            raise RuntimeError(
                f"write-write conflict: base v{tx['base_version']} != "
                f"current v{meta['version']}")
        version = tx["base_version"] + 1
        seqs = {t: s for t, s in meta["seqs"].items() if t in tx["tables"]}
        iseqs = {t: s for t, s in meta["iseqs"].items() if t in tx["tables"]}
        data = {"version": version, "tables": tx["tables"],
                "delta_seqs": seqs, "intent_seqs": iseqs,
                "log_pos": meta["log_end"]}
        staged = self._staged_path(version)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".manifest")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, staged)
        except FileExistsError:
            os.remove(tmp)
            counters.inc("manifest_cas_conflict_total")
            raise RuntimeError(
                f"write-write conflict: version v{version} already prepared "
                "by a concurrent writer")
        os.remove(tmp)
        return version

    def commit(self, version: int) -> None:
        """Phase 2: the atomic commit point (copy + atomic replace).

        The staged file is KEPT as a permanent claim on its version
        number: a concurrent writer that read the manifest just before
        this commit still holds the old version and would otherwise
        re-prepare (and later clobber) this version — its exclusive link
        against the surviving claim turns that into the write-write
        conflict it is. Claims are tiny and GC'd far behind the head by
        recover()."""
        staged = self._staged_path(version)
        if not os.path.exists(staged):
            raise RuntimeError(f"no prepared manifest v{version}")
        with open(staged) as f:
            data = json.load(f)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".manifest")
        with os.fdopen(fd, "wb") as f:
            with open(staged, "rb") as src:
                f.write(src.read())
            f.flush()
            os.fsync(f.fileno())
        with self._root_commit_lock:
            # Version guard: a staged root must never replace a NEWER one.
            # Effective versions advance through delta log lines, so two
            # folds can stage v and v' > v concurrently (the old root path
            # couldn't: version v' was only preparable after v committed);
            # replacing out of order would roll the root BACK and fork the
            # lineage — composes downstream of v' would reference deltas
            # the v'-commit's GC already aged out. The loser keeps its
            # staged claim (recover() sweeps claims behind the head) and
            # gets the conflict; fold() treats it as a lost claim and
            # yields.
            cur = int(self._root().get("version", 0))
            if cur >= version:
                os.remove(tmp)
                raise RuntimeError(
                    f"write-write conflict: root advanced to v{cur} before "
                    f"staged v{version} could commit")
            try:
                self._check_fence()
            except BaseException:
                os.remove(tmp)
                raise
            os.replace(tmp, self.path)
        with self._delta_lock:
            self._delta_cache.clear()
        # the new root folded every delta at or below its recorded
        # sequences: GC their files (best-effort; recover() is the backstop)
        self._gc_deltas(int(data.get("log_pos", 0)))
        # same ride-along for intent markers left by crashed writers
        self.sweep_intents()

    def abort(self, version: int) -> None:
        staged = self._staged_path(version)
        if os.path.exists(staged):
            os.remove(staged)

    # ---- DELTA path (per-table state commits) --------------------------
    def prepare_delta(self, tx: dict, tables: list[str]) -> dict:
        """Phase 1 of the per-table path: stage one delta file per written
        table, each claimed via an exclusive hard link on the table's next
        sequence. Tables are claimed in sorted order (deadlock-free);
        a lost claim releases everything already claimed and raises the
        write-write conflict. Returns the commit handle."""
        base_seqs = tx.get("base_seqs", {})
        # hand-built txs (fold, restores, tests) carry no base_iseqs and
        # opt out of the intent fence; begin()-issued txs always carry it
        base_iseqs = tx.get("base_iseqs")
        cur = self._compose()
        handle = {"txid": uuid.uuid4().hex[:12], "tables": {}, "iseq": {}}
        claimed: list[tuple[str, int]] = []
        try:
            os.makedirs(self.delta_dir, exist_ok=True)
            self._ensure_root()
            for table in sorted(tables):
                want = int(base_seqs.get(table, 0))
                have = int(cur["seqs"].get(table, 0))
                if have != want:
                    counters.inc("manifest_cas_conflict_total")
                    raise RuntimeError(
                        f"write-write conflict on table {table!r}: base "
                        f"seq {want} != current seq {have}")
                if base_iseqs is not None:
                    # intent fence: this full-state line would CLOBBER any
                    # merge that landed after the writer's snapshot
                    iwant = int(base_iseqs.get(table, 0))
                    ihave = int(cur["iseqs"].get(table, 0))
                    if ihave != iwant:
                        counters.inc("manifest_intent_conflict_total")
                        raise IntentConflict(
                            f"write-write conflict on table {table!r}: "
                            f"{ihave - iwant} intent merge(s) landed since "
                            "this transaction's snapshot")
                    handle["iseq"][table] = iwant
                seq = want + 1
                data = {"txid": handle["txid"], "table": table, "seq": seq,
                        "state": tx["tables"].get(table)}
                fd, tmp = tempfile.mkstemp(dir=self.delta_dir,
                                           prefix=".delta")
                with os.fdopen(fd, "w") as f:
                    json.dump(data, f)
                    f.flush()
                    os.fsync(f.fileno())
                try:
                    os.link(tmp, self._delta_path(table, seq))
                except FileExistsError:
                    os.remove(tmp)
                    counters.inc("manifest_cas_conflict_total")
                    raise RuntimeError(
                        f"write-write conflict: delta {table}.{seq} already "
                        "staged by a concurrent writer")
                os.remove(tmp)
                claimed.append((table, seq))
                handle["tables"][table] = seq
            # post-claim re-validation closes the check/claim window against
            # a concurrent commit+fold recycling our claimed sequence
            now = self._compose()
            for table, seq in claimed:
                if int(now["seqs"].get(table, 0)) >= seq:
                    counters.inc("manifest_cas_conflict_total")
                    raise RuntimeError(
                        f"write-write conflict: table {table!r} advanced to "
                        f"seq {now['seqs'].get(table)} during prepare")
                if base_iseqs is not None and \
                        int(now["iseqs"].get(table, 0)) \
                        != int(base_iseqs.get(table, 0)):
                    counters.inc("manifest_intent_conflict_total")
                    raise IntentConflict(
                        f"write-write conflict on table {table!r}: an "
                        "intent merge landed during prepare")
        except BaseException:
            for table, seq in claimed:
                try:
                    os.remove(self._delta_path(table, seq))
                except OSError:
                    pass
            raise
        return handle

    def commit_delta(self, handle: dict) -> int:
        """Phase 2: append the fsynced commit line — the atomic multi-table
        commit record. Returns the new effective version.

        The claims are re-validated first: a grace-expired GC (a 2PC
        parked > GC_GRACE_S between prepare and commit) or a concurrent
        process's recover() may have removed the staged files, and a
        commit record must never reference deltas that no longer exist —
        that would wedge every later compose. The expired committer gets
        a clean write-write conflict (tx aborts) instead."""
        for table, seq in handle.get("tables", {}).items():
            if not os.path.exists(self._delta_path(table, int(seq))):
                raise RuntimeError(
                    f"write-write conflict: staged delta {table}.{seq} "
                    "expired before commit (claim removed by GC or "
                    "recovery)")
        line = (json.dumps({"x": handle["txid"], "t": handle["tables"]})
                + "\n").encode()
        with self._log_lock:
            fd = os.open(self.log_path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                # cross-process exclusion against recover()'s compaction
                # truncate: an append can never land between its size
                # check and the truncate
                fcntl.flock(fd, fcntl.LOCK_EX)
                # final intent fence, atomic with the append: commit_intent
                # serializes through this same flock, so an iseq that still
                # matches HERE cannot be invalidated before our line lands.
                # Without this, a merge committing inside the prepare ->
                # commit window would be silently erased by this full-state
                # line (lost update on the appended rows).
                expect = handle.get("iseq") or {}
                if expect:
                    now = self._compose()
                    for table, iwant in expect.items():
                        if int(now["iseqs"].get(table, 0)) != int(iwant):
                            counters.inc("manifest_intent_conflict_total")
                            raise IntentConflict(
                                f"write-write conflict on table {table!r}: "
                                "an intent merge landed during this "
                                "transaction's commit window")
                # promotion fence, atomic with the append: a standby that
                # fenced this dir strictly before this point keeps the
                # line out of the log entirely (split-brain invariant)
                self._check_fence()
                os.write(fd, line)
                os.fsync(fd)
            finally:
                os.close(fd)
        counters.inc("manifest_delta_commits")
        return self.version()

    def abort_delta(self, handle: dict) -> None:
        """Release the staged per-table claims (rollback before commit)."""
        for table, seq in handle.get("tables", {}).items():
            try:
                os.remove(self._delta_path(table, int(seq)))
            except OSError:
                pass

    # ---- WRITE-INTENT path (concurrent same-table appends) -------------
    def _intent_path(self, table: str, txid: str) -> str:
        # txid-unique names: no exclusive-link CAS, hence no claim retry
        return os.path.join(self.intent_dir, f"{table}.{txid}.intent")

    def stage_intent(self, table: str, records: list,
                     streams: dict | None = None) -> dict:
        """Stage a per-writer write-intent for an APPEND-ONLY commit.

        ``records`` is the _write_segfiles output — [(seg, [rels], nrows)]
        per written segment. The durable intent file is in-doubt crash
        evidence plus the expiry token commit_intent re-checks; it is
        never read by compose (the merge line carries the records), so
        sweeping it can only abort an uncommitted writer, never corrupt a
        committed state. Returns the commit handle."""
        os.makedirs(self.intent_dir, exist_ok=True)
        self._ensure_root()
        txid = uuid.uuid4().hex[:12]
        recs = [(int(seg), list(rels), int(n)) for seg, rels, n in records]
        marks = {str(k): int(v) for k, v in (streams or {}).items()}
        data = {"txid": txid, "table": table, "records": recs,
                "streams": marks}
        path = self._intent_path(table, txid)
        fd, tmp = tempfile.mkstemp(dir=self.intent_dir, prefix=".intent")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # crash window: intent staged, merge line not durable — recover()
        # rolls this writer back exactly like a stale delta claim
        faults.check("intent_stage")
        return {"txid": txid, "table": table, "records": recs,
                "streams": marks, "path": path}

    def commit_intent(self, handle: dict) -> int:
        """Resolve a staged intent: append ONE fsynced merge line, then
        remove the intent file. Returns the new effective version.

        The intent file is re-checked first, mirroring commit_delta's
        claim re-validation: a writer parked past the GC grace (or raced
        by recover()/DROP) finds its token gone and gets a clean
        write-write conflict instead of publishing rows whose segfiles
        the orphan sweep may already have reclaimed."""
        path = handle["path"]
        if not os.path.exists(path):
            counters.inc("manifest_intent_conflict_total")
            raise IntentConflict(
                f"write-write conflict: staged intent {handle['table']}."
                f"{handle['txid']} expired before commit (removed by GC, "
                "recovery, or DROP TABLE)")
        rec: dict = {"x": handle["txid"],
                     "w": {handle["table"]: handle["records"]}}
        if handle.get("streams"):
            rec["s"] = {handle["table"]: handle["streams"]}
        line = (json.dumps(rec) + "\n").encode()
        # crash window A: resolve reached, line not appended — rollback
        faults.check("intent_resolve")
        with self._log_lock:
            fd = os.open(self.log_path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                # token re-check ATOMIC with the append: a sweep or DROP
                # that removes the token strictly before this point keeps
                # the merge line out of the log entirely, so a "w" line
                # can never land after its table's drop tombstone
                if not os.path.exists(path):
                    counters.inc("manifest_intent_conflict_total")
                    raise IntentConflict(
                        f"write-write conflict: staged intent "
                        f"{handle['table']}.{handle['txid']} expired "
                        "before commit (removed by GC, recovery, or "
                        "DROP TABLE)")
                # promotion fence, atomic with the append (see commit_delta)
                self._check_fence()
                os.write(fd, line)
                os.fsync(fd)
            finally:
                os.close(fd)
        # crash window B: line durable, marker not yet removed — the
        # commit SURVIVES; the leftover marker is garbage for the sweep
        faults.check("intent_resolve")
        try:
            os.remove(path)
        except OSError:
            pass
        counters.inc("manifest_intent_commits")
        return self.version()

    def abort_intent(self, handle: dict) -> None:
        """Withdraw a staged intent (rollback before the merge line)."""
        try:
            os.remove(handle["path"])
        except OSError:
            pass

    def sweep_intents(self, grace_s: float | None = None) -> int:
        """Remove write-intent files older than the grace window — the
        delta-claim grace-GC discipline applied to intents. Safe at any
        time: compose never reads intent files, so a swept file either
        aborts a crashed/parked writer (which gets the clean conflict at
        commit_intent, like an expired delta claim) or clears a committed
        writer's leftover marker. Returns the number removed."""
        if grace_s is None:
            grace_s = self.GC_GRACE_S
        try:
            names = os.listdir(self.intent_dir)
        except OSError:
            return 0
        removed = 0
        now = time.time()
        for fn in names:
            if not fn.endswith(".intent"):
                continue
            path = os.path.join(self.intent_dir, fn)
            try:
                if now - os.stat(path).st_mtime < grace_s:
                    continue
                os.remove(path)
                removed += 1
            except OSError:
                continue
        if removed:
            counters.inc("manifest_intent_swept_total", removed)
        return removed

    # ---- checkpoint fold -----------------------------------------------
    def fold(self, min_deltas: int = 1) -> bool:
        """Fold committed deltas into the root snapshot (the checkpoint):
        stage a root at the current effective state (log offset advanced
        past every folded line), commit it, GC the folded delta files.
        Opportunistic — a lost root claim means another writer/folder is
        moving the root and this fold simply yields. Returns True when a
        fold committed."""
        meta = self._compose()
        if meta["applied"] < max(1, min_deltas):
            return False
        tx = {"base_version": meta["version"],
              "tables": json.loads(meta["json"])["tables"]}
        try:
            v = self.prepare(tx)
        except RuntimeError:
            return False        # concurrent fold/root writer owns the move
        # crash window A: staged but not committed — recover() rolls the
        # claim back; deltas + log intact, nothing lost
        faults.check("delta_fold")
        try:
            self.commit(v)
        except RuntimeError:
            # the root advanced past our staged version while we held the
            # claim (a concurrent fold from a later effective base): that
            # fold subsumed this one's work — yield, releasing the claim
            self.abort(v)
            return False
        except BaseException:
            self.abort(v)
            raise
        # crash window B: root committed, folded delta files not yet GC'd —
        # compose skips sequences at/below the root's, recover() sweeps
        faults.check("delta_fold")
        counters.inc("manifest_folds")
        return True

    # Delta files outlive their fold by a grace period (the
    # TableStore.gc_files GC_GRACE_S principle): a lock-free composer
    # that read the PREVIOUS root may still need them, and a folded delta
    # applied on that older root composes the identical state (the
    # sequence guard keeps replay idempotent). Without the grace, heavy
    # fold traffic (threshold 1) starves compose — every retry races a
    # fresh fold's unlink. recover() sweeps unconditionally at startup.
    GC_GRACE_S = 20.0

    def _gc_deltas(self, log_pos: int, grace_s: float | None = None) -> None:
        """Best-effort delta-file GC after a root commit. The committing
        fold's composed state is already stale the moment it lands (a
        table's first write, a commit, an in-flight claim may all have
        raced it), so classification against that state is unsound — the
        rules here use only ground truth observable NOW:

        * a (table, seq) referenced by a committed log line at/after the
          new root's offset is LIVE (committed but not yet folded): never
          touched;
        * everything else — folded files, dead chains of dropped tables,
          crashed claims — is removed once older than the grace window.
          Youth protects in-flight claims (prepare_delta -> commit_delta
          spans milliseconds) and composers holding the previous root;
          recover() and drop_table_deltas() handle the cases where the
          caller KNOWS there is no concurrency."""
        if grace_s is None:
            grace_s = self.GC_GRACE_S
        try:
            names = os.listdir(self.delta_dir)
        except OSError:
            return
        referenced: set = set()
        for line in self._log_lines(int(log_pos))[0]:
            for t, s in (line.get("t") or {}).items():
                referenced.add((t, int(s)))
        now = time.time()
        for fn in names:
            if not fn.endswith(".delta"):
                continue
            try:
                stem, seq_s = fn[:-len(".delta")].rsplit(".", 1)
                seq = int(seq_s)
            except ValueError:
                continue
            if (stem, seq) in referenced:
                continue
            path = os.path.join(self.delta_dir, fn)
            try:
                if now - os.stat(path).st_mtime < grace_s:
                    continue
                os.remove(path)
            except OSError:
                pass

    def drop_table_deltas(self, table: str) -> None:
        """Unlink a dropped table's whole delta chain NOW (no grace): a
        later CREATE of the same name restarts at seq 1 and must not
        collide with a stale claim. Only callers that hold the session's
        exclusive write mode (DROP TABLE does) may use this — under that
        lock no composer or claimant can be in flight for the table in
        this process, and a cross-process composer that loses the race
        simply recomposes against the new root (the table is gone from
        it)."""
        try:
            names = os.listdir(self.delta_dir)
        except OSError:
            return
        for fn in names:
            if fn.endswith(".delta") \
                    and fn[:-len(".delta")].rsplit(".", 1)[0] == table:
                try:
                    os.remove(os.path.join(self.delta_dir, fn))
                except OSError:
                    pass
        # the dropped table's staged intents go with it (no grace, same
        # contract): an in-flight appender finds its token gone and gets
        # the clean conflict at commit_intent
        swept = 0
        try:
            inames = os.listdir(self.intent_dir)
        except OSError:
            inames = []
        for fn in inames:
            if fn.endswith(".intent") \
                    and fn[:-len(".intent")].rsplit(".", 1)[0] == table:
                try:
                    os.remove(os.path.join(self.intent_dir, fn))
                    swept += 1
                except OSError:
                    pass
        if swept:
            counters.inc("manifest_intent_swept_total", swept)
        with self._compose_lock:
            self._compose_key = None
        with self._delta_lock:
            self._delta_cache.clear()

    # ---- recovery ------------------------------------------------------
    def recover(self) -> list[int]:
        """In-doubt resolution (cdbdtxrecovery.c analog), run on startup
        with no concurrent writers:

        1. roll back prepared-but-uncommitted ROOT stages above the
           committed head (claims at/below it are permanent markers,
           GC'd once far behind);
        2. roll back staged delta files whose (table, seq) no committed
           log line references — a crash between prepare_delta and
           commit_delta (their claims were blocking the table);
        3. compact: fold every committed delta into the root and truncate
           the commit log, so a freshly-opened cluster always starts from
           a plain root snapshot.

        A corrupt manifest.json SURFACES here as ManifestError (startup
        must refuse to open, not quietly roll back live versions against
        a half-read head).

        The no-concurrent-writers contract is fail-safe, not assumed: a
        live writer in another process whose staged claims this sweep
        removes gets a clean write-write conflict at commit_delta (which
        re-validates its claims), and the log compaction re-checks the
        log size under the cross-process append lock — a commit racing
        recovery is either fully kept or cleanly refused, never hidden."""
        meta = self._compose()
        current = meta["version"]
        rolled = []
        for fn in os.listdir(self.root):
            if fn.startswith("manifest.") and fn.endswith(".prepared"):
                v = int(fn.split(".")[1])
                if v > current:
                    os.remove(os.path.join(self.root, fn))
                    rolled.append(v)
                elif v < current - 64:
                    os.remove(os.path.join(self.root, fn))
        # in-doubt deltas: staged claims above the committed sequence
        committed = dict(meta["seqs"])
        root = self._root()
        folded = {t: int(s) for t, s in root.get("delta_seqs", {}).items()}
        try:
            names = os.listdir(self.delta_dir)
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".delta"):
                continue
            try:
                stem, seq_s = fn[:-len(".delta")].rsplit(".", 1)
                seq = int(seq_s)
            except ValueError:
                continue
            if seq > int(committed.get(stem, 0)):
                # staged, never committed: the in-doubt tx rolls back
                os.remove(os.path.join(self.delta_dir, fn))
                rolled.append(-seq)
            elif seq <= folded.get(stem, 0):
                os.remove(os.path.join(self.delta_dir, fn))   # fold leftover
        # in-doubt write intents: at exclusive-open startup EVERY intent
        # file is removable — an uncommitted one rolls its writer back
        # (exactly like the staged delta claims above; its orphaned
        # segfiles fall to the store's sweep), a committed one is only
        # the leftover marker of a kill between the durable merge line
        # and the unlink. Counted (manifest_intent_swept_total), not
        # appended to `rolled` — callers assert recover() idempotence as
        # `recover() == []` and a marker sweep is not a rolled-back root.
        self.sweep_intents(grace_s=0.0)
        with self._compose_lock:
            self._compose_key = None    # delta files moved under us
        with self._delta_lock:
            self._delta_cache.clear()
        # compaction: fold everything, then reset the log (exclusive-open
        # startup is the one safe moment to shrink it)
        meta = self._compose()
        if meta["applied"] > 0:
            self.fold(min_deltas=1)
        meta = self._compose()
        if meta["applied"] == 0 and os.path.exists(self.log_path):
            root = self._root()
            if int(root.get("log_pos", 0)) >= meta["log_end"] \
                    and meta["log_end"] > 0:
                try:
                    # Ordering: root (log_pos=0) FIRST, truncate second —
                    # a failure in between is benign (replayed lines are
                    # sequence-guarded no-ops), while truncating first
                    # would leave log_pos pointing past a short log and
                    # silently hide every later commit. Both steps run
                    # under the cross-process append lock with a size
                    # re-check, so a commit landing after the compose is
                    # never erased.
                    root["log_pos"] = 0
                    fd, tmp = tempfile.mkstemp(dir=self.root,
                                               prefix=".manifest")
                    with os.fdopen(fd, "w") as f:
                        json.dump(root, f, indent=1)
                        f.flush()
                        os.fsync(f.fileno())
                    lf = os.open(self.log_path, os.O_RDWR)
                    try:
                        fcntl.flock(lf, fcntl.LOCK_EX)
                        if os.fstat(lf).st_size == meta["log_end"]:
                            os.replace(tmp, self.path)
                            os.ftruncate(lf, 0)
                        else:       # a commit landed since the compose
                            os.remove(tmp)
                    finally:
                        os.close(lf)
                except OSError:
                    pass
        # sweep grace-lingering folded files too (exclusive-open startup:
        # no composer can hold an older root, no claim can be in flight) —
        # a fresh open always starts from a clean deltas/ directory
        self._gc_deltas(int(self._root().get("log_pos", 0)), grace_s=0.0)
        with self._compose_lock:
            self._compose_key = None
        with self._delta_lock:
            self._delta_cache.clear()
        return rolled

    def commit_tx(self, tx: dict) -> int:
        """One-phase ROOT convenience (structural commits: DROP TABLE,
        width changes, restores — each is also a checkpoint fold)."""
        v = self.prepare(tx)
        try:
            self.commit(v)
        except BaseException:
            # a lost commit guard (cross-process fold raced us) releases
            # the claim and surfaces the conflict — the commit did NOT
            # apply, and the caller must not believe it did
            self.abort(v)
            raise
        return v

    def commit_tables_tx(self, tx: dict, tables: list[str]) -> int:
        """One-phase DELTA convenience: publish ``tables``' states from
        ``tx`` through the per-table path. Returns the effective version."""
        handle = self.prepare_delta(tx, tables)
        try:
            return self.commit_delta(handle)
        except BaseException:
            self.abort_delta(handle)
            raise
