"""Manifest-based MVCC commit — the distributed-visibility analog.

The reference achieves cluster-wide atomic visibility with 2PC + the
distributed log (src/backend/cdb/cdbtm.c, access/transam/distributedlog.c).
Our storage is append-only (no in-place update), so a transaction's writes
are invisible staged files until a single atomic manifest swap publishes
them — the manifest version is the distributed commit record. The DTM-lite
layer (runtime/dtm.py) drives prepare/commit over this API:

  prepare(tx): durably stage the next manifest as manifest.<v>.prepared
  commit(tx):  atomically rename it over manifest.json  (commit point)
  abort(tx):   delete the staged manifest + orphaned segfiles

Readers snapshot manifest.json once per query, so concurrent loads never
tear a scan (snapshot isolation).
"""

from __future__ import annotations

import json
import os
import tempfile


class ManifestError(RuntimeError):
    """FATAL: the cluster's commit record is unreadable. Nothing can be
    repaired from segment mirrors (the manifest IS the thing that says
    which files exist) — recover from the standby coordinator, a backup,
    or the archive (docs/ROBUSTNESS.md)."""


class Manifest:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, "manifest.json")

    # ---- snapshots -----------------------------------------------------
    def snapshot(self) -> dict:
        if not os.path.exists(self.path):
            return {"version": 0, "tables": {}}
        with open(self.path) as f:
            try:
                return json.load(f)
            except ValueError as e:
                # never let a bare JSONDecodeError escape: this is the
                # cluster's commit record, name it and say what to do
                raise ManifestError(
                    f"corrupt manifest at {self.path}: {e} — restore from "
                    "the standby coordinator, a backup, or the archive"
                ) from e

    # ---- transactions --------------------------------------------------
    def begin(self) -> dict:
        """Start a write tx from the current snapshot; mutate tx['tables']."""
        snap = self.snapshot()
        return {"base_version": snap["version"], "tables": snap["tables"]}

    def _staged_path(self, version: int) -> str:
        return os.path.join(self.root, f"manifest.{version}.prepared")

    def prepare(self, tx: dict) -> int:
        """Phase 1: durably stage the new manifest. Returns new version.

        The staged file is claimed with an EXCLUSIVE hard link: two writers
        racing past the version check cannot both stage version v — the
        loser gets the same write-write conflict it would have gotten from
        the version check (the CAS is atomic, not just check-then-write)."""
        current = self.snapshot()
        if current["version"] != tx["base_version"]:
            raise RuntimeError(
                f"write-write conflict: base v{tx['base_version']} != current v{current['version']}"
            )
        version = tx["base_version"] + 1
        data = {"version": version, "tables": tx["tables"]}
        staged = self._staged_path(version)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".manifest")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, staged)
        except FileExistsError:
            os.remove(tmp)
            raise RuntimeError(
                f"write-write conflict: version v{version} already prepared "
                "by a concurrent writer")
        os.remove(tmp)
        return version

    def commit(self, version: int) -> None:
        """Phase 2: the atomic commit point (copy + atomic replace).

        The staged file is KEPT as a permanent claim on its version
        number: a concurrent writer that read the manifest just before
        this commit still holds the old version and would otherwise
        re-prepare (and later clobber) this version — its exclusive link
        against the surviving claim turns that into the write-write
        conflict it is. Claims are tiny and GC'd far behind the head by
        recover()."""
        staged = self._staged_path(version)
        if not os.path.exists(staged):
            raise RuntimeError(f"no prepared manifest v{version}")
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".manifest")
        with os.fdopen(fd, "wb") as f:
            with open(staged, "rb") as src:
                f.write(src.read())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def abort(self, version: int) -> None:
        staged = self._staged_path(version)
        if os.path.exists(staged):
            os.remove(staged)

    def recover(self) -> list[int]:
        """In-doubt resolution (cdbdtxrecovery.c analog): roll back any
        prepared-but-uncommitted manifests (version ABOVE the committed
        head) found after a crash; claims at or below the head are the
        committed versions' permanent markers (GC'd once far behind).

        A corrupt manifest.json SURFACES here as ManifestError (startup
        must refuse to open, not quietly roll back live versions against
        a half-read head)."""
        current = self.snapshot().get("version", 0)
        rolled = []
        for fn in os.listdir(self.root):
            if fn.startswith("manifest.") and fn.endswith(".prepared"):
                v = int(fn.split(".")[1])
                if v > current:
                    os.remove(os.path.join(self.root, fn))
                    rolled.append(v)
                elif v < current - 64:
                    os.remove(os.path.join(self.root, fn))
        return rolled

    def commit_tx(self, tx: dict) -> int:
        """One-phase convenience (single-writer fast path, like GP's
        one-phase commit optimization for single-gang xacts)."""
        v = self.prepare(tx)
        self.commit(v)
        return v
