"""geo extension: spherical-distance helpers (the earthdistance/postgis
slice of gpcontrib). Pure jnp — XLA fuses the trig chain into the
surrounding scan, so distance predicates cost one fused elementwise pass."""

import jax.numpy as jnp

from greengage_tpu import types as T
from greengage_tpu.extensions import register_scalar

_EARTH_KM = 6371.0088  # IUGG mean radius


def _haversine_km(lat1, lon1, lat2, lon2):
    p1, p2 = jnp.radians(lat1), jnp.radians(lat2)
    dphi = p2 - p1
    dlmb = jnp.radians(lon2 - lon1)
    a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2
    return 2 * _EARTH_KM * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


register_scalar("haversine_km", _haversine_km, ("float64",) * 4, T.FLOAT64,
                extension="geo")
