"""Bundled extensions (the gpcontrib/ analog): loadable via
CREATE EXTENSION <name>; each module registers its scalar functions
through greengage_tpu.extensions.register_scalar at import."""
