"""Segment (chip) configuration — the gp_segment_configuration analog.

Reference parity: src/include/catalog/gp_segment_config.h. Each content id
(segment) maps to a device of the JAX mesh; role/status drive FTS-lite
failover decisions (src/backend/fts/fts.c). A monotonically increasing
``version`` invalidates cached dispatch topology, mirroring how the
dispatcher consumes the FTS version (src/backend/cdb/dispatcher/README.md).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field


class SegmentRole(enum.Enum):
    PRIMARY = "p"
    MIRROR = "m"


class SegmentStatus(enum.Enum):
    UP = "u"
    DOWN = "d"


@dataclass
class SegmentEntry:
    content: int                 # segment index (-1 = coordinator, like GP)
    role: SegmentRole
    preferred_role: SegmentRole
    status: SegmentStatus = SegmentStatus.UP
    mode_synced: bool = True     # mirror caught up (gp_stat_replication analog)
    host: str = "localhost"
    device_index: int | None = None  # index into mesh devices (primaries only)


@dataclass
class SegmentConfig:
    """Cluster topology: content -> primary/mirror entries."""

    numsegments: int
    entries: list[SegmentEntry] = field(default_factory=list)
    version: int = 0  # bumped on any change (FTS version analog)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @staticmethod
    def create(numsegments: int, with_mirrors: bool = False) -> "SegmentConfig":
        cfg = SegmentConfig(numsegments=numsegments)
        cfg.entries.append(
            SegmentEntry(-1, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=None)
        )
        for c in range(numsegments):
            cfg.entries.append(
                SegmentEntry(c, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=c)
            )
            if with_mirrors:
                # a new mirror holds no data: not in sync until the first
                # replication pass completes (runtime/replication.py)
                cfg.entries.append(SegmentEntry(
                    c, SegmentRole.MIRROR, SegmentRole.MIRROR, mode_synced=False))
        return cfg

    def acting_primary(self, content: int) -> "SegmentEntry | None":
        """The entry currently serving reads/writes for this content (may be
        a promoted mirror)."""
        for e in self.entries:
            if e.content == content and e.role is SegmentRole.PRIMARY:
                return e
        return None

    def has_mirrors(self) -> bool:
        return any(e.content >= 0 and (e.role is SegmentRole.MIRROR or
                                       e.preferred_role is SegmentRole.MIRROR)
                   for e in self.entries)

    # ---- persistence (part of the catalog; gp_segment_configuration is a
    # catalog table in the reference) --------------------------------------
    def to_dict(self) -> dict:
        return {
            "numsegments": self.numsegments,
            "version": self.version,
            "entries": [
                {"content": e.content, "role": e.role.value,
                 "preferred_role": e.preferred_role.value,
                 "status": e.status.value, "synced": e.mode_synced,
                 "host": e.host, "device_index": e.device_index}
                for e in self.entries
            ],
        }

    @staticmethod
    def from_dict(d: dict) -> "SegmentConfig":
        cfg = SegmentConfig(numsegments=d["numsegments"])
        cfg.version = d.get("version", 0)
        for e in d.get("entries", []):
            cfg.entries.append(SegmentEntry(
                e["content"], SegmentRole(e["role"]),
                SegmentRole(e["preferred_role"]), SegmentStatus(e["status"]),
                e.get("synced", True), e.get("host", "localhost"),
                e.get("device_index")))
        return cfg

    def expand(self, new_numsegments: int) -> None:
        """Add segments for cluster expansion, PRESERVING existing entries
        (down markers, promoted mirrors, mirror pairs survive — gpexpand
        never resets FTS state)."""
        if new_numsegments <= self.numsegments:
            raise ValueError("expansion must increase the segment count")
        has_mirrors = any(e.role is SegmentRole.MIRROR for e in self.entries)
        for c in range(self.numsegments, new_numsegments):
            self.entries.append(
                SegmentEntry(c, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=c))
            if has_mirrors:
                # new mirror holds no data until the first replication pass
                self.entries.append(SegmentEntry(
                    c, SegmentRole.MIRROR, SegmentRole.MIRROR, mode_synced=False))
        self.numsegments = new_numsegments
        self.version += 1

    def primaries(self) -> list[SegmentEntry]:
        return sorted(
            (e for e in self.entries if e.role is SegmentRole.PRIMARY and e.content >= 0),
            key=lambda e: e.content,
        )

    def entry(self, content: int, role: SegmentRole) -> SegmentEntry:
        for e in self.entries:
            if e.content == content and e.role is role:
                return e
        raise KeyError((content, role))

    def mark_down(self, content: int) -> None:
        """FTS verdict: primary is dead; promote its mirror if in sync."""
        with self._lock:
            primary = self.entry(content, SegmentRole.PRIMARY)
            primary.status = SegmentStatus.DOWN
            try:
                mirror = self.entry(content, SegmentRole.MIRROR)
            except KeyError:
                mirror = None
            if mirror is not None and mirror.mode_synced:
                # promotion: swap roles (ftsmessagehandler.c analog)
                primary.role = SegmentRole.MIRROR
                mirror.role = SegmentRole.PRIMARY
                mirror.status = SegmentStatus.UP
                mirror.device_index = primary.device_index
                primary.device_index = None
            self.version += 1

    def all_up(self) -> bool:
        return all(
            e.status is SegmentStatus.UP for e in self.entries if e.role is SegmentRole.PRIMARY
        )
