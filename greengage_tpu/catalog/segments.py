"""Segment (chip) configuration — the gp_segment_configuration analog.

Reference parity: src/include/catalog/gp_segment_config.h. Each content id
(segment) maps to a device of the JAX mesh; role/status drive FTS-lite
failover decisions (src/backend/fts/fts.c). A monotonically increasing
``version`` invalidates cached dispatch topology, mirroring how the
dispatcher consumes the FTS version (src/backend/cdb/dispatcher/README.md).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field


class SegmentRole(enum.Enum):
    PRIMARY = "p"
    MIRROR = "m"


class SegmentStatus(enum.Enum):
    UP = "u"
    DOWN = "d"


@dataclass
class SegmentEntry:
    content: int                 # segment index (-1 = coordinator, like GP)
    role: SegmentRole
    preferred_role: SegmentRole
    status: SegmentStatus = SegmentStatus.UP
    mode_synced: bool = True     # mirror caught up (gp_stat_replication analog)
    host: str = "localhost"
    device_index: int | None = None  # index into mesh devices (primaries only)


@dataclass
class SegmentConfig:
    """Cluster topology: content -> primary/mirror entries."""

    numsegments: int
    entries: list[SegmentEntry] = field(default_factory=list)
    version: int = 0  # bumped on any change (FTS version analog)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @staticmethod
    def create(numsegments: int, with_mirrors: bool = False) -> "SegmentConfig":
        cfg = SegmentConfig(numsegments=numsegments)
        cfg.entries.append(
            SegmentEntry(-1, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=None)
        )
        for c in range(numsegments):
            cfg.entries.append(
                SegmentEntry(c, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=c)
            )
            if with_mirrors:
                cfg.entries.append(SegmentEntry(c, SegmentRole.MIRROR, SegmentRole.MIRROR))
        return cfg

    def expand(self, new_numsegments: int) -> None:
        """Add segments for cluster expansion, PRESERVING existing entries
        (down markers, promoted mirrors, mirror pairs survive — gpexpand
        never resets FTS state)."""
        if new_numsegments <= self.numsegments:
            raise ValueError("expansion must increase the segment count")
        has_mirrors = any(e.role is SegmentRole.MIRROR for e in self.entries)
        for c in range(self.numsegments, new_numsegments):
            self.entries.append(
                SegmentEntry(c, SegmentRole.PRIMARY, SegmentRole.PRIMARY, device_index=c))
            if has_mirrors:
                self.entries.append(SegmentEntry(c, SegmentRole.MIRROR, SegmentRole.MIRROR))
        self.numsegments = new_numsegments
        self.version += 1

    def primaries(self) -> list[SegmentEntry]:
        return sorted(
            (e for e in self.entries if e.role is SegmentRole.PRIMARY and e.content >= 0),
            key=lambda e: e.content,
        )

    def entry(self, content: int, role: SegmentRole) -> SegmentEntry:
        for e in self.entries:
            if e.content == content and e.role is role:
                return e
        raise KeyError((content, role))

    def mark_down(self, content: int) -> None:
        """FTS verdict: primary is dead; promote its mirror if in sync."""
        with self._lock:
            primary = self.entry(content, SegmentRole.PRIMARY)
            primary.status = SegmentStatus.DOWN
            try:
                mirror = self.entry(content, SegmentRole.MIRROR)
            except KeyError:
                mirror = None
            if mirror is not None and mirror.mode_synced:
                # promotion: swap roles (ftsmessagehandler.c analog)
                primary.role = SegmentRole.MIRROR
                mirror.role = SegmentRole.PRIMARY
                mirror.status = SegmentStatus.UP
                mirror.device_index = primary.device_index
                primary.device_index = None
            self.version += 1

    def all_up(self) -> bool:
        return all(
            e.status is SegmentStatus.UP for e in self.entries if e.role is SegmentRole.PRIMARY
        )
