"""Table schemas and distribution policies.

Reference parity: gp_distribution_policy (src/include/catalog/gp_policy.h) —
every table carries a policy {HASH(cols), RANDOM, REPLICATED} plus
``numsegments`` (the table's width, which may lag the cluster width during
expansion, gp_policy.h:35). We reproduce exactly that model.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from greengage_tpu import types as T


class PolicyKind(enum.Enum):
    HASH = "hash"          # DISTRIBUTED BY (cols): rows placed by key hash
    RANDOM = "random"      # DISTRIBUTED RANDOMLY: round-robin, locus Strewn
    REPLICATED = "replicated"  # DISTRIBUTED REPLICATED: full copy per segment


@dataclass(frozen=True)
class DistPolicy:
    kind: PolicyKind
    keys: tuple[str, ...] = ()      # distribution key column names (HASH only)
    numsegments: int = 0            # table width; 0 = cluster width at create

    def __post_init__(self):
        if self.kind is PolicyKind.HASH and not self.keys:
            raise ValueError("HASH policy requires keys")
        if self.kind is not PolicyKind.HASH and self.keys:
            raise ValueError("keys only valid for HASH policy")

    def describe(self) -> str:
        if self.kind is PolicyKind.HASH:
            return f"DISTRIBUTED BY ({', '.join(self.keys)})"
        if self.kind is PolicyKind.RANDOM:
            return "DISTRIBUTED RANDOMLY"
        return "DISTRIBUTED REPLICATED"


@dataclass(frozen=True)
class Column:
    name: str
    type: T.SqlType
    nullable: bool = True
    # TEXT storage encoding: "auto" resolves at first insert to "dict"
    # (code per row + table-global dictionary; low NDV) or "raw" (byte
    # blob + offsets per segment; high NDV — the varlena analog,
    # src/backend/access/aocs/aocsam.c:661 datum streams)
    encoding: str = "auto"


@dataclass
class TableSchema:
    name: str
    columns: list[Column]
    policy: DistPolicy
    options: dict = field(default_factory=dict)  # e.g. compresstype, blocksize
    stats: object = None   # planner.stats.TableStats from ANALYZE (or None)

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column in {self.name}")
        for k in self.policy.keys:
            if k not in names:
                raise ValueError(f"distribution key {k} not a column of {self.name}")

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"{self.name}.{name}")

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "columns": [
                {
                    "name": c.name,
                    "kind": c.type.kind.value,
                    "scale": c.type.scale,
                    "nullable": c.nullable,
                    **({"encoding": c.encoding} if c.encoding != "auto" else {}),
                }
                for c in self.columns
            ],
            "policy": {
                "kind": self.policy.kind.value,
                "keys": list(self.policy.keys),
                "numsegments": self.policy.numsegments,
            },
            "options": self.options,
            **({"stats": self.stats.to_dict()} if self.stats is not None else {}),
        }

    @staticmethod
    def from_dict(d: dict) -> "TableSchema":
        cols = [
            Column(c["name"], T.SqlType(T.Kind(c["kind"]), c.get("scale", 0)),
                   c.get("nullable", True), c.get("encoding", "auto"))
            for c in d["columns"]
        ]
        p = d["policy"]
        policy = DistPolicy(PolicyKind(p["kind"]), tuple(p.get("keys", ())), p.get("numsegments", 0))
        schema = TableSchema(d["name"], cols, policy, d.get("options", {}))
        if "stats" in d:
            from greengage_tpu.planner.stats import TableStats

            schema.stats = TableStats.from_dict(d["stats"])
        return schema
