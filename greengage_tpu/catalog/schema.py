"""Table schemas and distribution policies.

Reference parity: gp_distribution_policy (src/include/catalog/gp_policy.h) —
every table carries a policy {HASH(cols), RANDOM, REPLICATED} plus
``numsegments`` (the table's width, which may lag the cluster width during
expansion, gp_policy.h:35). We reproduce exactly that model.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from greengage_tpu import types as T


class PolicyKind(enum.Enum):
    HASH = "hash"          # DISTRIBUTED BY (cols): rows placed by key hash
    RANDOM = "random"      # DISTRIBUTED RANDOMLY: round-robin, locus Strewn
    REPLICATED = "replicated"  # DISTRIBUTED REPLICATED: full copy per segment


@dataclass(frozen=True)
class DistPolicy:
    kind: PolicyKind
    keys: tuple[str, ...] = ()      # distribution key column names (HASH only)
    numsegments: int = 0            # table width; 0 = cluster width at create

    def __post_init__(self):
        if self.kind is PolicyKind.HASH and not self.keys:
            raise ValueError("HASH policy requires keys")
        if self.kind is not PolicyKind.HASH and self.keys:
            raise ValueError("keys only valid for HASH policy")

    def describe(self) -> str:
        if self.kind is PolicyKind.HASH:
            return f"DISTRIBUTED BY ({', '.join(self.keys)})"
        if self.kind is PolicyKind.RANDOM:
            return "DISTRIBUTED RANDOMLY"
        return "DISTRIBUTED REPLICATED"


@dataclass(frozen=True)
class Partition:
    """One partition of a RANGE/LIST-partitioned table.

    Reference parity: pg_partition_rule (src/backend/cdb/cdbpartition.c) —
    single-level here; each partition's rows live in their own storage
    table ``<parent>#<name>`` so pruning is a staging decision and DROP
    PARTITION is O(1). RANGE bounds are half-open [lo, hi) in the
    column's storage representation (dates = epoch days, decimals =
    scaled ints); None = unbounded. LIST carries its value set.
    ``default``: catches rows no other partition accepts."""

    name: str
    lo: object = None           # RANGE inclusive start
    hi: object = None           # RANGE exclusive end
    values: tuple = ()          # LIST values
    default: bool = False

    def storage_name(self, parent: str) -> str:
        return f"{parent}#{self.name}"

    def to_dict(self) -> dict:
        d = {"name": self.name}
        if self.lo is not None:
            d["lo"] = self.lo
        if self.hi is not None:
            d["hi"] = self.hi
        if self.values:
            d["values"] = list(self.values)
        if self.default:
            d["default"] = True
        return d

    @staticmethod
    def from_dict(d: dict) -> "Partition":
        return Partition(d["name"], d.get("lo"), d.get("hi"),
                         tuple(d.get("values", ())), d.get("default", False))


@dataclass(frozen=True)
class Column:
    name: str
    type: T.SqlType
    nullable: bool = True
    # TEXT storage encoding: "auto" resolves at first insert to "dict"
    # (code per row + table-global dictionary; low NDV) or "raw" (byte
    # blob + offsets per segment; high NDV — the varlena analog,
    # src/backend/access/aocs/aocsam.c:661 datum streams)
    encoding: str = "auto"


@dataclass
class TableSchema:
    name: str
    columns: list[Column]
    policy: DistPolicy
    options: dict = field(default_factory=dict)  # e.g. compresstype, blocksize
    stats: object = None   # planner.stats.TableStats from ANALYZE (or None)
    # single-level partitioning (cdbpartition.c role): ("range"|"list",
    # column name) + the partition set; None = unpartitioned
    partition_by: tuple | None = None
    partitions: list[Partition] = field(default_factory=list)
    # secondary indexes: name -> {"column": col, "using": "btree"|"bitmap"}.
    # Both access methods lower to the same per-segfile block-value index
    # (storage sidecars; see table_store.block_index) — the pg_index
    # analog that turns unclustered equality scans block-selective
    indexes: dict = field(default_factory=dict)

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column in {self.name}")
        for k in self.policy.keys:
            if k not in names:
                raise ValueError(f"distribution key {k} not a column of {self.name}")

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"{self.name}.{name}")

    # ---- partitioning ------------------------------------------------
    @property
    def is_partitioned(self) -> bool:
        return self.partition_by is not None

    def storage_tables(self) -> list[str]:
        """Storage-level table names holding this table's rows."""
        if not self.is_partitioned:
            return [self.name]
        return [p.storage_name(self.name) for p in self.partitions]

    def partition(self, name: str) -> Partition:
        for p in self.partitions:
            if p.name == name:
                return p
        raise KeyError(f"partition {name} of {self.name}")

    def route_rows(self, values, valid) -> "list":
        """Partition index per row (host-side, at write time). -1 = no
        partition accepts the row (an error unless a DEFAULT exists —
        handled by the caller). NULL partition keys route to the DEFAULT
        partition, like the reference's default-part catch-all."""
        import numpy as np

        kind, _col = self.partition_by
        v = np.asarray(values)
        out = np.full(len(v), -1, dtype=np.int64)
        default_i = next((i for i, p in enumerate(self.partitions)
                          if p.default), None)
        for i, p in enumerate(self.partitions):
            if p.default:
                continue
            if kind == "range":
                m = np.ones(len(v), bool)
                if p.lo is not None:
                    m &= v >= p.lo
                if p.hi is not None:
                    m &= v < p.hi
            else:
                m = np.isin(v, np.asarray(list(p.values), dtype=v.dtype))
            out = np.where((out == -1) & m, i, out)
        if valid is not None:
            out = np.where(np.asarray(valid, bool), out, -1)
        if default_i is not None:
            out = np.where(out == -1, default_i, out)
        return out

    def partitions_for_values(self, values) -> list[int]:
        """Runtime partition selection from an explicit key-value set —
        the EXECUTION-time half of the PartitionSelector role
        (src/backend/executor/nodePartitionSelector.c): indices of
        partitions that can hold ANY of ``values`` (storage
        representation). Default partitions always survive."""
        import numpy as np

        kind, _col = self.partition_by
        v = np.asarray(list(values) if not hasattr(values, "dtype")
                       else values)
        keep = []
        for i, p in enumerate(self.partitions):
            if p.default:
                keep.append(i)
                continue
            if kind == "range":
                m = np.ones(len(v), bool)
                if p.lo is not None:
                    m &= v >= p.lo
                if p.hi is not None:
                    m &= v < p.hi
                if m.any():
                    keep.append(i)
            else:
                if np.isin(v, np.asarray(list(p.values))).any():
                    keep.append(i)
        return keep

    def prune_partitions(self, conjuncts: list[tuple]) -> list[int]:
        """Static partition pruning: indices of partitions that can hold
        rows satisfying the pushed conjuncts [(col, op, value)] — the
        plan-time half of the PartitionSelector role
        (src/backend/executor/nodePartitionSelector.c)."""
        kind, col = self.partition_by
        keep = []
        for i, p in enumerate(self.partitions):
            if p.default:
                keep.append(i)   # catch-all: never statically prunable
                continue
            ok = True
            for c, op, val in conjuncts:
                if c != col:
                    continue
                if kind == "range":
                    # partition holds x in [lo, hi); prune when NO such x
                    # can satisfy the conjunct (int bounds tighten by 1)
                    lo, hi = p.lo, p.hi
                    is_int = isinstance(val, int)
                    if op == "=" and ((lo is not None and val < lo)
                                      or (hi is not None and val >= hi)):
                        ok = False
                    elif op == "<" and lo is not None and lo >= val:
                        ok = False
                    elif op == "<=" and lo is not None and lo > val:
                        ok = False
                    elif op == ">" and hi is not None and (
                            hi <= val or (is_int and hi <= val + 1)):
                        ok = False
                    elif op == ">=" and hi is not None and hi <= val:
                        ok = False
                else:
                    vals = p.values
                    if op == "=" and val not in vals:
                        ok = False
                    elif op == "<" and all(x >= val for x in vals):
                        ok = False
                    elif op == "<=" and all(x > val for x in vals):
                        ok = False
                    elif op == ">" and all(x <= val for x in vals):
                        ok = False
                    elif op == ">=" and all(x < val for x in vals):
                        ok = False
                if not ok:
                    break
            if ok:
                keep.append(i)
        return keep

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "columns": [
                {
                    "name": c.name,
                    "kind": c.type.kind.value,
                    "scale": c.type.scale,
                    "nullable": c.nullable,
                    **({"encoding": c.encoding} if c.encoding != "auto" else {}),
                }
                for c in self.columns
            ],
            "policy": {
                "kind": self.policy.kind.value,
                "keys": list(self.policy.keys),
                "numsegments": self.policy.numsegments,
            },
            "options": self.options,
            **({"stats": self.stats.to_dict()} if self.stats is not None else {}),
            **({"partition_by": list(self.partition_by),
                "partitions": [p.to_dict() for p in self.partitions]}
               if self.partition_by is not None else {}),
            **({"indexes": self.indexes} if self.indexes else {}),
        }

    @staticmethod
    def from_dict(d: dict) -> "TableSchema":
        cols = [
            Column(c["name"], T.SqlType(T.Kind(c["kind"]), c.get("scale", 0)),
                   c.get("nullable", True), c.get("encoding", "auto"))
            for c in d["columns"]
        ]
        p = d["policy"]
        policy = DistPolicy(PolicyKind(p["kind"]), tuple(p.get("keys", ())), p.get("numsegments", 0))
        schema = TableSchema(d["name"], cols, policy, d.get("options", {}))
        if "partition_by" in d:
            schema.partition_by = tuple(d["partition_by"])
            schema.partitions = [Partition.from_dict(p)
                                 for p in d.get("partitions", [])]
        if "stats" in d:
            from greengage_tpu.planner.stats import TableStats

            schema.stats = TableStats.from_dict(d["stats"])
        schema.indexes = d.get("indexes", {})
        return schema
