from greengage_tpu.catalog.schema import (  # noqa: F401
    Partition,
    Column,
    DistPolicy,
    PolicyKind,
    TableSchema,
)
from greengage_tpu.catalog.catalog import Catalog  # noqa: F401
from greengage_tpu.catalog.segments import SegmentConfig, SegmentRole, SegmentStatus  # noqa: F401
