"""The catalog: table schemas + cluster topology, persisted as JSON.

Reference parity: the master-only system catalog (src/backend/catalog) that
the QD consults for planning and dispatch. We keep it deliberately small: a
dict of TableSchema plus the SegmentConfig, durably stored in the cluster
directory and versioned via the storage manifest (MVCC commits live in
storage.manifest, not here).
"""

from __future__ import annotations

import json
import os
import tempfile

from greengage_tpu.catalog.schema import TableSchema
from greengage_tpu.catalog.segments import SegmentConfig


class Catalog:
    def __init__(self, numsegments: int, path: str | None = None,
                 mirrors: bool = False):
        self.tables: dict[str, TableSchema] = {}
        self.extensions: list[str] = []   # CREATE EXTENSION survivors
        self.resource_groups: list[dict] = []   # resgroup definitions
        self.segments = SegmentConfig.create(numsegments, with_mirrors=mirrors)
        self.path = path  # cluster dir; None = in-memory only

    # ---- table DDL -----------------------------------------------------
    def create_table(self, schema: TableSchema, if_not_exists: bool = False) -> None:
        if schema.name in self.tables:
            if if_not_exists:
                return
            raise ValueError(f'table "{schema.name}" already exists')
        if schema.policy.numsegments == 0:
            schema.policy = type(schema.policy)(
                schema.policy.kind, schema.policy.keys, self.segments.numsegments
            )
        self.tables[schema.name] = schema
        self._save()

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        if name not in self.tables:
            if if_exists:
                return
            raise ValueError(f'table "{name}" does not exist')
        del self.tables[name]
        self._save()

    def get(self, name: str) -> TableSchema:
        if name not in self.tables:
            # partition child storage tables ("parent#part") share the
            # parent's schema — every storage path (insert, read, expand,
            # replicate) resolves them transparently
            if "#" in name:
                parent, part = name.split("#", 1)
                if parent in self.tables:
                    schema = self.tables[parent]
                    if any(p.name == part for p in schema.partitions):
                        return schema
            raise ValueError(f'relation "{name}" does not exist')
        return self.tables[name]

    def __contains__(self, name: str) -> bool:
        if name in self.tables:
            return True
        if "#" in name:
            try:
                self.get(name)
                return True
            except ValueError:
                return False
        return False

    # ---- persistence ---------------------------------------------------
    def _save(self) -> None:
        if self.path is None:
            return
        data = {
            "numsegments": self.segments.numsegments,
            "segments": self.segments.to_dict(),
            "tables": {n: t.to_dict() for n, t in self.tables.items()},
            "extensions": self.extensions,
            "resource_groups": self.resource_groups,
        }
        os.makedirs(self.path, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.path, prefix=".catalog")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, "catalog.json"))

    @staticmethod
    def load(path: str) -> "Catalog":
        with open(os.path.join(path, "catalog.json")) as f:
            data = json.load(f)
        cat = Catalog(data["numsegments"], path=path)
        if "segments" in data:
            cat.segments = SegmentConfig.from_dict(data["segments"])
        for n, t in data["tables"].items():
            cat.tables[n] = TableSchema.from_dict(t)
        cat.extensions = list(data.get("extensions", ()))
        cat.resource_groups = list(data.get("resource_groups", ()))
        return cat
