"""Hand-written SQL lexer + recursive-descent parser.

Grammar subset of the reference's PostgreSQL 9.4 bison grammar
(src/backend/parser/gram.y + scan.l) chosen to cover the analytical
workloads (TPC-H/TPC-DS class queries), GP DDL (DISTRIBUTED BY), INSERT,
COPY, EXPLAIN. Precedence follows PG: OR < AND < NOT < comparison/IS/IN/
BETWEEN/LIKE < additive < multiplicative < unary minus.
"""

from __future__ import annotations

import copy
import dataclasses
import re

from greengage_tpu.sql import ast as A


class SqlError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*|"[^"]+")
  | (?P<op><>|!=|<=|>=|\|\||[-+*/%(),.;=<>\[\]])
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "null", "true", "false", "is",
    "in", "between", "like", "case", "when", "then", "else", "end", "cast",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "distinct",
    "asc", "desc", "nulls", "first", "last", "create", "table", "drop",
    "insert", "into", "values", "copy", "explain", "analyze", "date",
    "interval", "extract", "distributed", "randomly", "replicated", "with",
    "exists", "if", "show", "union", "all", "substring", "for",
    "begin", "commit", "rollback", "abort", "set", "to", "transaction", "work",
    "delete", "update", "over", "partition",
}


class Lexer:
    def __init__(self, text: str):
        self.tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m:
                raise SqlError(f"lex error at {text[pos:pos+20]!r}")
            pos = m.end()
            if m.lastgroup == "ws":
                continue
            kind = m.lastgroup
            val = m.group()
            if kind == "ident":
                if val.startswith('"'):
                    self.tokens.append(("name", val[1:-1]))
                elif val.lower() in KEYWORDS:
                    self.tokens.append(("kw", val.lower()))
                else:
                    self.tokens.append(("name", val.lower()))
            elif kind == "str":
                self.tokens.append(("str", val[1:-1].replace("''", "'")))
            elif kind == "num":
                self.tokens.append(("num", val))
            else:
                self.tokens.append(("op", val))
        self.tokens.append(("eof", ""))


class Parser:
    def __init__(self, text: str):
        self.toks = Lexer(text).tokens
        self.i = 0
        self._recursive_ctes: dict = {}

    # ---- token helpers -------------------------------------------------
    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind, val=None):
        t = self.peek()
        if t[0] == kind and (val is None or t[1] == val):
            self.i += 1
            return t
        return None

    def expect(self, kind, val=None):
        t = self.accept(kind, val)
        if t is None:
            raise SqlError(f"expected {val or kind}, got {self.peek()[1]!r}")
        return t

    def at_kw(self, *kws):
        t = self.peek()
        return t[0] == "kw" and t[1] in kws

    # frame words (ROWS/RANGE/UNBOUNDED/...) are context-sensitive like in
    # the reference grammar: plain identifiers elsewhere, recognized only
    # inside an OVER () clause
    def at_word(self, *words):
        t = self.peek()
        return t[0] in ("kw", "name") and t[1] in words

    def accept_word(self, word):
        if self.at_word(word):
            return self.next()
        return None

    def expect_word(self, word):
        t = self.accept_word(word)
        if t is None:
            raise SqlError(f"expected {word}, got {self.peek()[1]!r}")
        return t

    # ---- statements ----------------------------------------------------
    def parse(self) -> list[A.ANode]:
        stmts = []
        while self.peek()[0] != "eof":
            stmts.append(self.statement())
            while self.accept("op", ";"):
                pass
        return stmts

    def statement(self) -> A.ANode:
        if self.at_kw("with"):
            # WITH ctes: inline expansion (non-recursive). The reference
            # materializes shared CTEs via ShareInputScan
            # (src/backend/executor/nodeShareInputScan.c:1); here every
            # reference inlines the subplan and XLA's common-subexpression
            # elimination dedupes identical subprograms within the single
            # compiled SPMD program — the TPU-native sharing analog.
            ctes = self.with_prefix(allow_recursive=True)
            if self.at_kw("insert"):
                stmt = self.insert_stmt()
            else:
                stmt = self.select_or_union()
            stmt = _substitute_ctes(stmt, ctes)
            if self._recursive_ctes:
                if isinstance(stmt, A.InsertStmt):
                    raise SqlError(
                        "WITH RECURSIVE over INSERT is not supported")
                stmt._recursive_ctes = self._recursive_ctes
                self._recursive_ctes = {}
            return stmt
        if self.at_kw("select"):
            return self.select_or_union()
        if self.at_word("declare"):
            # DECLARE <name> PARALLEL RETRIEVE CURSOR FOR <select>
            self.next()
            name = self.expect("name")[1]
            for w in ("parallel", "retrieve", "cursor"):
                self.expect_word(w)
            self.expect("kw", "for")
            return A.DeclareCursorStmt(name, self.select_or_union())
        if self.at_word("retrieve"):
            # RETRIEVE ALL FROM ENDPOINT <n> OF <cursor>
            self.next()
            self.expect_word("all")
            self.expect("kw", "from")
            self.expect_word("endpoint")
            ep = int(self.expect("num")[1])
            self.expect_word("of")
            return A.RetrieveStmt(ep, self.expect("name")[1])
        if self.at_word("close"):
            self.next()
            return A.CloseCursorStmt(self.expect("name")[1])
        if self.at_kw("create"):
            return self.create_table()
        if self.at_kw("drop"):
            return self.drop_table()
        if self.at_word("alter"):
            return self.alter_table()
        if self.at_kw("insert"):
            return self.insert_stmt()
        if self.at_kw("copy"):
            return self.copy_stmt()
        if self.at_kw("delete"):
            self.next()
            self.expect("kw", "from")
            table = self.expect("name")[1]
            where = self.expr() if self.accept("kw", "where") else None
            return A.DeleteStmt(table, where)
        if self.at_kw("update"):
            self.next()
            table = self.expect("name")[1]
            self.expect("kw", "set")
            sets = []
            while True:
                col = self.expect("name")[1]
                self.expect("op", "=")
                sets.append((col, self.expr()))
                if not self.accept("op", ","):
                    break
            where = self.expr() if self.accept("kw", "where") else None
            return A.UpdateStmt(table, sets, where)
        if self.at_kw("explain"):
            self.next()
            analyze = bool(self.accept("kw", "analyze"))
            return A.ExplainStmt(self.statement(), analyze)
        if self.at_kw("analyze"):
            self.next()
            t = self.accept("name")
            return A.AnalyzeStmt(t[1] if t else None)
        if self.at_kw("show"):
            self.next()
            return A.ShowStmt(self.next()[1])
        if self.at_kw("set"):
            self.next()
            name = self.next()[1]
            if not self.accept("op", "="):
                self.expect("kw", "to")
            # negative numeric values lex as two tokens ('-', number):
            # `SET log_min_duration_ms = -1` must parse (-1 = disabled)
            neg = self.accept("op", "-")
            value = self.next()[1]
            if neg:
                value = f"-{value}"
            return A.SetStmt(name, value)
        if self.at_kw("begin"):
            self.next()
            self.accept("kw", "transaction") or self.accept("kw", "work")
            return A.TxStmt("begin")
        if self.at_kw("commit"):
            self.next()
            self.accept("kw", "transaction") or self.accept("kw", "work")
            return A.TxStmt("commit")
        if self.at_kw("rollback") or self.at_kw("abort"):
            self.next()
            self.accept("kw", "transaction") or self.accept("kw", "work")
            return A.TxStmt("abort")
        raise SqlError(f"unexpected {self.peek()[1]!r}")

    # ---- WITH (common table expressions) ------------------------------
    def with_prefix(self, allow_recursive: bool = False) -> dict:
        """Parse `WITH [RECURSIVE] name [(cols)] AS (query) [, ...]`
        -> {name: query}.

        Later CTEs may reference earlier ones (expanded eagerly, so the
        returned queries are self-contained). Self-referencing CTEs under
        RECURSIVE are NOT substituted: they land in
        ``self._recursive_ctes`` as RecursiveCTE (base/recursive split)
        and the name stays a plain table reference the session resolves
        to the materialized worktable result (gram.y:12190 semantics via
        session-level iteration)."""
        self.expect("kw", "with")
        recursive = bool(self.at_word("recursive") and self.next())
        if recursive and not allow_recursive:
            raise SqlError(
                "WITH RECURSIVE is only supported at statement level")
        ctes: dict = {}
        while True:
            name = self.expect("name")[1]
            colnames = None
            if self.accept("op", "("):
                colnames = [self.expect("name")[1]]
                while self.accept("op", ","):
                    colnames.append(self.expect("name")[1])
                self.expect("op", ")")
            self.expect("kw", "as")
            self.expect("op", "(")
            inner = self.with_prefix() if self.at_kw("with") else {}
            q = self.select_or_union()
            self.expect("op", ")")
            q = _substitute_ctes(q, {**ctes, **inner})
            if recursive and _references_table(q, name):
                self._recursive_ctes[name] = _split_recursive_cte(
                    name, q, colnames)
            else:
                if colnames:
                    _apply_cte_column_aliases(q, colnames, name)
                ctes[name] = q
            if not self.accept("op", ","):
                break
        return ctes

    # ---- SELECT --------------------------------------------------------
    def select_or_union(self) -> A.ANode:
        first = self.select_stmt(stop_at_setops=True)
        if not self.at_kw("union"):
            # trailing ORDER BY/LIMIT belong to the single select
            self._select_tail(first)
            return first
        u = A.UnionStmt(selects=[first], all=True)
        is_all = None
        while self.accept("kw", "union"):
            branch_all = bool(self.accept("kw", "all"))
            if is_all is None:
                is_all = branch_all
            elif is_all != branch_all:
                raise SqlError("mixed UNION / UNION ALL is not supported")
            u.selects.append(self.select_stmt(stop_at_setops=True))
        u.all = bool(is_all)
        # ORDER BY / LIMIT after the last branch apply to the union
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            u.order_by.append(self.order_item())
            while self.accept("op", ","):
                u.order_by.append(self.order_item())
        if self.accept("kw", "limit"):
            u.limit = int(self.expect("num")[1])
        if self.accept("kw", "offset"):
            u.offset = int(self.expect("num")[1])
        return u

    def _select_tail(self, s: A.SelectStmt) -> None:
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            s.order_by.append(self.order_item())
            while self.accept("op", ","):
                s.order_by.append(self.order_item())
        if self.accept("kw", "limit"):
            s.limit = int(self.expect("num")[1])
        if self.accept("kw", "offset"):
            s.offset = int(self.expect("num")[1])

    def select_stmt(self, stop_at_setops: bool = False) -> A.SelectStmt:
        self.expect("kw", "select")
        s = A.SelectStmt()
        s.distinct = bool(self.accept("kw", "distinct"))
        s.items.append(self.select_item())
        while self.accept("op", ","):
            s.items.append(self.select_item())
        if self.accept("kw", "from"):
            s.from_.append(self.table_ref())
            while self.accept("op", ","):
                s.from_.append(self.table_ref())
        if self.accept("kw", "where"):
            s.where = self.expr()
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            self._group_by_clause(s)
        if self.accept("kw", "having"):
            s.having = self.expr()
        if not stop_at_setops:
            self._select_tail(s)
        return s

    def _group_by_clause(self, s: A.SelectStmt) -> None:
        """GROUP BY items: plain exprs mixed with ROLLUP/CUBE/GROUPING SETS
        constructs (gram.y:12457 group_clause). Normalized here into either
        s.group_by (plain only) or s.grouping_sets (the cross product of
        every item's set list, PG semantics)."""
        sets: list[list] = [[]]
        saw_construct = False

        def cross(item_sets: list[list]) -> None:
            nonlocal sets
            sets = [s0 + s1 for s0 in sets for s1 in item_sets]
            if len(sets) > 128:
                raise SqlError("too many grouping sets (max 128)")

        while True:
            t = self.peek()
            if t[0] == "name" and t[1] in ("rollup", "cube") \
                    and self.peek(1) == ("op", "("):
                kind = self.next()[1]
                saw_construct = True
                exprs = self._paren_expr_list()
                if kind == "rollup":
                    item = [exprs[:i] for i in range(len(exprs), -1, -1)]
                else:                      # cube: all subsets
                    if len(exprs) > 7:
                        raise SqlError("cube() supports at most 7 columns")
                    item = [[e for j, e in enumerate(exprs) if m >> j & 1]
                            for m in range((1 << len(exprs)) - 1, -1, -1)]
                cross(item)
            elif t[0] == "name" and t[1] == "grouping" \
                    and self.peek(1) == ("name", "sets"):
                self.next()
                self.next()
                saw_construct = True
                self.expect("op", "(")
                item = []
                while True:
                    if self.peek() == ("op", "("):
                        item.append(self._paren_expr_list(allow_empty=True))
                    else:
                        item.append([self.expr()])
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
                cross(item)
            else:
                e = self.expr()
                cross([[e]])
            if not self.accept("op", ","):
                break
        if saw_construct:
            s.grouping_sets = sets
        else:
            s.group_by = sets[0]

    def _paren_expr_list(self, allow_empty: bool = False) -> list:
        self.expect("op", "(")
        if allow_empty and self.accept("op", ")"):
            return []
        out = [self.expr()]
        while self.accept("op", ","):
            out.append(self.expr())
        self.expect("op", ")")
        return out

    def select_item(self) -> A.SelectItem:
        if self.peek() == ("op", "*"):
            self.next()
            return A.SelectItem(A.Star())
        if (self.peek()[0] == "name" and self.peek(1) == ("op", ".")
                and self.peek(2) == ("op", "*")):
            t = self.next()[1]
            self.next()
            self.next()
            return A.SelectItem(A.Star(table=t))
        e = self.expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.next()[1]
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return A.SelectItem(e, alias)

    def order_item(self) -> A.OrderItem:
        e = self.expr()
        desc = False
        if self.accept("kw", "desc"):
            desc = True
        else:
            self.accept("kw", "asc")
        nulls_first = None
        if self.accept("kw", "nulls"):
            if self.accept("kw", "first"):
                nulls_first = True
            else:
                self.expect("kw", "last")
                nulls_first = False
        return A.OrderItem(e, desc, nulls_first)

    # ---- FROM ----------------------------------------------------------
    def table_ref(self) -> A.TableRef:
        left = self.table_primary()
        while True:
            if self.at_kw("join", "inner", "left", "cross", "right", "full"):
                kind = "inner"
                if self.accept("kw", "left"):
                    self.accept("kw", "outer")
                    kind = "left"
                elif self.accept("kw", "right"):
                    self.accept("kw", "outer")
                    kind = "right"
                elif self.accept("kw", "full"):
                    self.accept("kw", "outer")
                    kind = "full"
                elif self.accept("kw", "cross"):
                    kind = "cross"
                else:
                    self.accept("kw", "inner")
                self.expect("kw", "join")
                right = self.table_primary()
                on = None
                if kind != "cross":
                    self.expect("kw", "on")
                    on = self.expr()
                if kind == "right":  # normalize: a RIGHT JOIN b == b LEFT JOIN a
                    left = A.JoinRef("left", right, left, on)
                else:
                    left = A.JoinRef(kind, left, right, on)
            else:
                return left

    def table_primary(self) -> A.TableRef:
        if self.accept("op", "("):
            if self.at_kw("with"):
                ctes = self.with_prefix()
                q = _substitute_ctes(self.select_or_union(), ctes)
            else:
                q = self.select_or_union()
            self.expect("op", ")")
            self.accept("kw", "as")
            alias = self.expect("name")[1]
            return A.SubqueryRef(q, alias)
        name = self.expect("name")[1]
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("name")[1]
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return A.BaseTable(name, alias)

    # ---- expressions (precedence climbing) ----------------------------
    def expr(self) -> A.ANode:
        return self.or_expr()

    def or_expr(self) -> A.ANode:
        e = self.and_expr()
        while self.accept("kw", "or"):
            e = A.Bin("or", e, self.and_expr())
        return e

    def and_expr(self) -> A.ANode:
        e = self.not_expr()
        while self.accept("kw", "and"):
            e = A.Bin("and", e, self.not_expr())
        return e

    def not_expr(self) -> A.ANode:
        if self.accept("kw", "not"):
            return A.Unary("not", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self) -> A.ANode:
        e = self.add_expr()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.next()
                op = "<>" if t[1] == "!=" else t[1]
                e = A.Bin(op, e, self.add_expr())
            elif self.at_kw("is"):
                self.next()
                negate = bool(self.accept("kw", "not"))
                self.expect("kw", "null")
                e = A.IsNullTest(e, negate)
            elif self.at_kw("between"):
                self.next()
                lo = self.add_expr()
                self.expect("kw", "and")
                hi = self.add_expr()
                e = A.Between(e, lo, hi)
            elif self.at_kw("in"):
                self.next()
                self.expect("op", "(")
                if self.at_kw("select"):
                    q = self.select_stmt()
                    self.expect("op", ")")
                    e = A.InSubquery(e, q)
                    continue
                vals = [self.expr()]
                while self.accept("op", ","):
                    vals.append(self.expr())
                self.expect("op", ")")
                e = A.InExpr(e, vals)
            elif self.at_kw("like"):
                self.next()
                e = A.LikeExpr(e, self.expect("str")[1])
            elif self.at_kw("not") and self.peek(1)[0] == "kw" and \
                    self.peek(1)[1] in ("between", "in", "like"):
                self.next()
                kw = self.next()[1]
                if kw == "between":
                    lo = self.add_expr()
                    self.expect("kw", "and")
                    hi = self.add_expr()
                    e = A.Between(e, lo, hi, negate=True)
                elif kw == "in":
                    self.expect("op", "(")
                    if self.at_kw("select"):
                        q = self.select_stmt()
                        self.expect("op", ")")
                        e = A.InSubquery(e, q, negate=True)
                        continue
                    vals = [self.expr()]
                    while self.accept("op", ","):
                        vals.append(self.expr())
                    self.expect("op", ")")
                    e = A.InExpr(e, vals, negate=True)
                else:
                    e = A.LikeExpr(e, self.expect("str")[1], negate=True)
            else:
                return e

    def add_expr(self) -> A.ANode:
        e = self.mul_expr()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in ("+", "-", "||"):
                self.next()
                rhs = self.mul_expr()
                # the official TPC-DS interval spelling: `date + 30 days`
                # (gram.y accepts the bare unit postfix only right after
                # an additive op, so `select 1 days` stays an alias)
                if t[1] in ("+", "-") and isinstance(rhs, A.Num) \
                        and self.peek()[0] == "name" \
                        and self.peek()[1] in ("day", "days", "week",
                                               "weeks", "month", "months",
                                               "year", "years"):
                    unit = self.next()[1].rstrip("s")
                    rhs = A.IntervalLit(rhs.text, unit)
                e = A.Bin(t[1], e, rhs)
            else:
                return e

    def mul_expr(self) -> A.ANode:
        e = self.unary_expr()
        while True:
            t = self.peek()
            if t[0] == "op" and t[1] in ("*", "/", "%"):
                self.next()
                e = A.Bin(t[1], e, self.unary_expr())
            else:
                return e

    def unary_expr(self) -> A.ANode:
        if self.accept("op", "-"):
            return A.Unary("-", self.unary_expr())
        if self.accept("op", "+"):
            return self.unary_expr()
        return self.primary()

    def primary(self) -> A.ANode:
        t = self.peek()
        if t == ("op", "("):
            self.next()
            if self.at_kw("select"):
                q = self.select_stmt()
                self.expect("op", ")")
                return A.ScalarSubquery(q)
            e = self.expr()
            self.expect("op", ")")
            return e
        if self.at_kw("exists"):
            self.next()
            self.expect("op", "(")
            q = self.select_stmt()
            self.expect("op", ")")
            return A.ExistsExpr(q)
        if t[0] == "num":
            self.next()
            return A.Num(t[1])
        if t[0] == "str":
            self.next()
            return A.Str(t[1])
        if self.at_kw("null"):
            self.next()
            return A.Null()
        if self.at_kw("true"):
            self.next()
            return A.Bool(True)
        if self.at_kw("false"):
            self.next()
            return A.Bool(False)
        if self.at_kw("left", "right") and self.peek(1) == ("op", "("):
            # left()/right() are reserved words (join syntax) but also
            # string functions when followed by an argument list
            name = self.next()[1]
            self.expect("op", "(")
            args = [self.expr()]
            while self.accept("op", ","):
                args.append(self.expr())
            self.expect("op", ")")
            return A.FuncCall(name, args)
        if self.at_kw("substring"):
            # SUBSTRING(x FROM a [FOR b]) and SUBSTRING(x, a[, b])
            self.next()
            self.expect("op", "(")
            args = [self.expr()]
            if self.accept("kw", "from"):
                args.append(self.expr())
                if self.accept("kw", "for"):
                    args.append(self.expr())
            else:
                while self.accept("op", ","):
                    args.append(self.expr())
            self.expect("op", ")")
            return A.FuncCall("substring", args)
        if self.at_kw("date"):
            self.next()
            return A.DateLit(self.expect("str")[1])
        if self.at_kw("interval"):
            self.next()
            v = self.expect("str")[1]
            unit = self.expect("name")[1].rstrip("s") \
                if self.peek()[0] == "name" else "day"
            return A.IntervalLit(v, unit)
        if self.at_kw("case"):
            return self.case_expr()
        if self.at_kw("cast"):
            self.next()
            self.expect("op", "(")
            arg = self.expr()
            self.expect("kw", "as")
            tname, typmod = self.type_name()
            self.expect("op", ")")
            return A.CastExpr(arg, tname, typmod)
        if self.at_kw("extract"):
            self.next()
            self.expect("op", "(")
            field = self.next()[1]
            self.expect("kw", "from")
            arg = self.expr()
            self.expect("op", ")")
            return A.ExtractExpr(field, arg)
        if t[0] == "name":
            # function call or (qualified) column
            if self.peek(1) == ("op", "("):
                fname = self.next()[1]
                self.next()
                star = False
                distinct = False
                args = []
                if self.accept("op", "*"):
                    star = True
                else:
                    distinct = bool(self.accept("kw", "distinct"))
                    if self.peek() != ("op", ")"):
                        args.append(self.expr())
                        while self.accept("op", ","):
                            args.append(self.expr())
                self.expect("op", ")")
                within = None
                if self.at_word("within") and self.peek(1) == ("kw", "group"):
                    self.next()
                    self.next()
                    self.expect("op", "(")
                    self.expect("kw", "order")
                    self.expect("kw", "by")
                    within = self.expr()
                    if self.accept("kw", "desc"):
                        raise SqlError(
                            "WITHIN GROUP (ORDER BY ... DESC) is not "
                            "supported; use 1-q with ascending order")
                    self.accept("kw", "asc")
                    self.expect("op", ")")
                over = None
                if self.accept("kw", "over"):
                    self.expect("op", "(")
                    over = A.WindowSpec()
                    if self.accept("kw", "partition"):
                        self.expect("kw", "by")
                        over.partition_by.append(self.expr())
                        while self.accept("op", ","):
                            over.partition_by.append(self.expr())
                    if self.accept("kw", "order"):
                        self.expect("kw", "by")
                        over.order_by.append(self.order_item())
                        while self.accept("op", ","):
                            over.order_by.append(self.order_item())
                    if self.at_word("rows", "range") \
                            and self.peek(1) != ("op", ")"):
                        mode = self.next()[1]
                        if self.accept("kw", "between"):
                            lo = self._frame_bound()
                            self.expect("kw", "and")
                            hi = self._frame_bound()
                        else:
                            lo = self._frame_bound()
                            hi = ("current", None)
                        over.frame = (mode, lo, hi)
                    self.expect("op", ")")
                return A.FuncCall(fname, args, star=star, distinct=distinct,
                                  over=over, within_order=within)
            parts = [self.next()[1]]
            while self.peek() == ("op", ".") and self.peek(1)[0] == "name":
                self.next()
                parts.append(self.next()[1])
            return A.Name(tuple(parts))
        raise SqlError(f"unexpected {t[1]!r} in expression")

    def case_expr(self) -> A.ANode:
        self.expect("kw", "case")
        whens = []
        while self.accept("kw", "when"):
            c = self.expr()
            self.expect("kw", "then")
            v = self.expr()
            whens.append((c, v))
        else_ = None
        if self.accept("kw", "else"):
            else_ = self.expr()
        self.expect("kw", "end")
        return A.CaseExpr(whens, else_)

    # ---- DDL / DML -----------------------------------------------------
    def type_name(self) -> tuple[str, tuple[int, ...]]:
        name = self.next()[1]
        if name == "double":
            self.accept("name", "precision")
            name = "double precision"
        typmod = ()
        if self.accept("op", "("):
            mods = [int(self.expect("num")[1])]
            while self.accept("op", ","):
                mods.append(int(self.expect("num")[1]))
            self.expect("op", ")")
            typmod = tuple(mods)
        return name, typmod

    def create_table(self):
        self.expect("kw", "create")
        if self.accept_word("resource"):
            self.expect_word("group")
            name = self.expect("name")[1]
            return A.ResourceGroupStmt("create", name,
                                       self.resgroup_options())
        if self.accept_word("writable"):
            self.expect_word("external")
            return self.create_external_table(True)
        if self.accept_word("external"):
            return self.create_external_table(False)
        if self.accept_word("extension"):
            ine = False
            if self.accept("kw", "if"):
                self.expect("kw", "not")
                self.expect("kw", "exists")
                ine = True
            return A.CreateExtensionStmt(self.expect("name")[1], ine)
        if self.accept_word("index"):
            ine = False
            if self.accept("kw", "if"):
                self.expect("kw", "not")
                self.expect("kw", "exists")
                ine = True
            name = self.expect("name")[1]
            self.expect("kw", "on")
            table = self.expect("name")[1]
            using = "btree"
            if self.accept_word("using"):
                using = self.next()[1]
            self.expect("op", "(")
            col = self.expect("name")[1]
            self.expect("op", ")")
            return A.CreateIndexStmt(name, table, col, using, ine)
        self.expect("kw", "table")
        ine = False
        if self.accept("kw", "if"):
            self.expect("kw", "not")
            self.expect("kw", "exists")
            ine = True
        name = self.expect("name")[1]
        self.expect("op", "(")
        cols = [self.column_def()]
        while self.accept("op", ","):
            cols.append(self.column_def())
        self.expect("op", ")")
        options = {}
        if self.accept("kw", "with"):
            self.expect("op", "(")
            while True:
                k = self.expect("name")[1]
                self.expect("op", "=")
                v = self.next()[1]
                options[k] = v
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        dist_kind, dist_keys = "hash", []
        if self.accept("kw", "distributed"):
            if self.accept("kw", "randomly"):
                dist_kind = "random"
            elif self.accept("kw", "replicated"):
                dist_kind = "replicated"
            else:
                self.expect("kw", "by")
                self.expect("op", "(")
                dist_keys.append(self.expect("name")[1])
                while self.accept("op", ","):
                    dist_keys.append(self.expect("name")[1])
                self.expect("op", ")")
        elif cols:
            dist_keys = [cols[0].name]  # GP default: first column
        pkind = pcol = None
        pdefs: list[A.PartitionDef] = []
        if self.accept("kw", "partition"):
            # PARTITION BY RANGE (col) (PARTITION p START (x) END (y)
            # [EVERY (n)], ..., DEFAULT PARTITION d) | PARTITION BY LIST
            # (col) (PARTITION p VALUES (a, b), ...) — the GP 6 syntax
            # subset (reference: src/backend/parser/gram.y partition rules)
            self.expect("kw", "by")
            if self.accept_word("range"):
                pkind = "range"
            else:
                self.expect_word("list")
                pkind = "list"
            self.expect("op", "(")
            pcol = self.expect("name")[1]
            self.expect("op", ")")
            self.expect("op", "(")
            pdefs.append(self.partition_def(pkind))
            while self.accept("op", ","):
                pdefs.append(self.partition_def(pkind))
            self.expect("op", ")")
        return A.CreateTableStmt(name, cols, dist_kind, dist_keys, options,
                                 ine, pkind, pcol, pdefs)

    def create_external_table(self, writable: bool) -> A.CreateExternalTableStmt:
        """CREATE [WRITABLE] EXTERNAL TABLE t (cols) { LOCATION ('url',...)
        | EXECUTE 'cmd' } [FORMAT 'csv' (delimiter ',' header null '')]
        [SEGMENT REJECT LIMIT n] — the GP external-table syntax subset
        (reference: src/backend/parser/gram.y CreateExternalStmt)."""
        self.expect("kw", "table")
        ine = False
        if self.accept("kw", "if"):
            self.expect("kw", "not")
            self.expect("kw", "exists")
            ine = True
        name = self.expect("name")[1]
        self.expect("op", "(")
        cols = [self.column_def()]
        while self.accept("op", ","):
            cols.append(self.column_def())
        self.expect("op", ")")
        urls: list[str] = []
        exec_cmd = None
        if self.accept_word("location"):
            self.expect("op", "(")
            urls.append(self.expect("str")[1])
            while self.accept("op", ","):
                urls.append(self.expect("str")[1])
            self.expect("op", ")")
        else:
            self.expect_word("execute")
            exec_cmd = self.expect("str")[1]
            if self.accept("kw", "on"):   # ON ALL is the only mode
                self.expect("kw", "all")
        fmt: dict = {}
        if self.accept_word("format"):
            kind = self.expect("str")[1].lower()
            if kind not in ("csv", "text"):
                raise SqlError(f"unsupported external format {kind!r}")
            fmt["kind"] = kind
            if self.accept("op", "("):
                while not self.accept("op", ")"):
                    k = self.next()[1]
                    if self.peek()[0] == "str":
                        fmt[k] = self.expect("str")[1]
                    else:
                        fmt[k] = "true"   # bare flag, e.g. HEADER
        reject_limit = None
        if self.accept_word("segment"):
            self.expect_word("reject")
            self.expect("kw", "limit")
            reject_limit = int(self.expect("num")[1])
        return A.CreateExternalTableStmt(
            name, cols, writable, urls, exec_cmd, fmt, reject_limit, ine)

    def partition_def(self, kind: str | None) -> A.PartitionDef:
        if self.accept_word("default"):
            self.expect("kw", "partition")
            return A.PartitionDef(self.expect("name")[1], default=True)
        self.expect("kw", "partition")
        name = self.expect("name")[1]
        if kind == "list" or (kind is None and self.at_kw("values")):
            self.expect("kw", "values")
            self.expect("op", "(")
            vals = [self.expr()]
            while self.accept("op", ","):
                vals.append(self.expr())
            self.expect("op", ")")
            return A.PartitionDef(name, values=vals)
        lo = hi = every = None
        if self.accept_word("start"):
            self.expect("op", "(")
            lo = self.expr()
            self.expect("op", ")")
        if self.accept("kw", "end"):
            self.expect("op", "(")
            hi = self.expr()
            self.expect("op", ")")
        if self.accept_word("every"):
            self.expect("op", "(")
            every = self.expr()
            self.expect("op", ")")
        return A.PartitionDef(name, lo=lo, hi=hi, every=every)

    def alter_table(self):
        self.expect_word("alter")
        if self.accept_word("resource"):
            # ALTER RESOURCE GROUP g SET <option> <value>
            self.expect_word("group")
            name = self.expect("name")[1]
            self.expect("kw", "set")
            opt = self.expect("name")[1]
            return A.ResourceGroupStmt("alter", name,
                                       {opt: int(self.expect("num")[1])})
        self.expect("kw", "table")
        table = self.expect("name")[1]
        if self.accept_word("add"):
            return A.AlterTableStmt(table, "add_partition",
                                    partition=self.partition_def(None))
        self.expect("kw", "drop")
        self.expect("kw", "partition")
        return A.AlterTableStmt(table, "drop_partition",
                                partition_name=self.expect("name")[1])

    def column_def(self) -> A.ColumnDef:
        name = self.expect("name")[1]
        tname, typmod = self.type_name()
        not_null = False
        if self.accept("kw", "not"):
            self.expect("kw", "null")
            not_null = True
        return A.ColumnDef(name, tname, typmod, not_null)

    def resgroup_options(self) -> dict:
        """WITH (concurrency=N, memory_limit_mb=M, cpu_weight=W)."""
        options: dict = {}
        if self.accept("kw", "with"):
            self.expect("op", "(")
            while True:
                k = self.expect("name")[1]
                self.expect("op", "=")
                options[k] = int(self.expect("num")[1])
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return options

    def drop_table(self):
        self.expect("kw", "drop")
        if self.accept_word("resource"):
            self.expect_word("group")
            return A.ResourceGroupStmt("drop", self.expect("name")[1])
        if self.accept_word("index"):
            ie = False
            if self.accept("kw", "if"):
                self.expect("kw", "exists")
                ie = True
            return A.DropIndexStmt(self.expect("name")[1], ie)
        self.expect("kw", "table")
        ie = False
        if self.accept("kw", "if"):
            self.expect("kw", "exists")
            ie = True
        return A.DropTableStmt(self.expect("name")[1], ie)

    def insert_stmt(self) -> A.InsertStmt:
        self.expect("kw", "insert")
        self.expect("kw", "into")
        table = self.expect("name")[1]
        columns = []
        if self.accept("op", "("):
            columns.append(self.expect("name")[1])
            while self.accept("op", ","):
                columns.append(self.expect("name")[1])
            self.expect("op", ")")
        if self.at_kw("select"):
            return A.InsertStmt(table, columns, [],
                                query=self.select_or_union())
        self.expect("kw", "values")
        rows = []
        while True:
            self.expect("op", "(")
            row = [self.expr()]
            while self.accept("op", ","):
                row.append(self.expr())
            self.expect("op", ")")
            rows.append(row)
            if not self.accept("op", ","):
                break
        return A.InsertStmt(table, columns, rows)

    def _frame_bound(self):
        """UNBOUNDED PRECEDING/FOLLOWING | CURRENT ROW | N PRECEDING/FOLLOWING"""
        if self.accept_word("unbounded"):
            kw = self.next()[1]
            if kw not in ("preceding", "following"):
                raise SqlError(f"expected PRECEDING/FOLLOWING, got {kw!r}")
            return ("unbounded_" + kw, None)
        if self.accept_word("current"):
            self.expect_word("row")
            return ("current", None)
        tok = self.expect("num")[1]
        if "." in tok:
            raise SqlError(f"frame offset must be an integer, got {tok!r}")
        n = int(tok)
        kw = self.next()[1]
        if kw not in ("preceding", "following"):
            raise SqlError(f"expected PRECEDING/FOLLOWING, got {kw!r}")
        return (kw, n)

    def copy_stmt(self) -> A.CopyStmt:
        self.expect("kw", "copy")
        table = self.expect("name")[1]
        self.expect("kw", "from")
        path = self.expect("str")[1]
        options = {}
        if self.accept("kw", "with"):
            self.expect("op", "(")
            while True:
                k = self.next()[1]
                v = (self.next()[1]
                     if self.peek()[0] in ("name", "str", "num", "kw")
                     else "true")
                options[k] = v
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return A.CopyStmt(table, path, options)


def _references_table(node, name: str) -> bool:
    if isinstance(node, A.BaseTable):
        return node.name == name
    if isinstance(node, A.ANode):
        for f in dataclasses.fields(node):
            if _references_table(getattr(node, f.name), name):
                return True
        return False
    if isinstance(node, (list, tuple)):
        return any(_references_table(v, name) for v in node)
    return False


def _split_recursive_cte(name: str, q, colnames):
    """base UNION [ALL] recursive -> RecursiveCTE: branches that scan
    ``name`` are recursive terms, the rest the base."""
    if not isinstance(q, A.UnionStmt):
        raise SqlError(
            f'recursive CTE "{name}" must be <base> UNION [ALL] <recursive>')
    if q.order_by or q.limit is not None:
        raise SqlError(
            f'recursive CTE "{name}" cannot carry ORDER BY/LIMIT')
    base, rec = [], []
    for b in q.selects:
        (rec if _references_table(b, name) else base).append(b)
    if not base:
        raise SqlError(f'recursive CTE "{name}" has no non-recursive term')
    if not rec:
        raise SqlError(f'recursive CTE "{name}" has no recursive term')

    def pack(bs):
        if len(bs) == 1:
            return bs[0]
        return A.UnionStmt(selects=bs, all=True)

    bq, rq = pack(base), pack(rec)
    if colnames:
        for part in (base + rec):
            _apply_cte_column_aliases(part, colnames, name)
    return A.RecursiveCTE(name, bq, rq, union_all=q.all)


def _substitute_ctes(node, ctes: dict):
    """Replace BaseTable references to CTE names with inlined SubqueryRefs.

    Generic dataclass walk over the AST; each reference gets its own deep
    copy of the CTE body (plans are mutated during binding).
    """
    if not ctes:
        return node

    def walk_val(v):
        if isinstance(v, A.BaseTable):
            q = ctes.get(v.name)
            if q is not None:
                # the body may itself reference OTHER ctes (a nested WITH
                # parsed before the outer ones were known) — substitute
                # inside the copy, excluding this name (no self-recursion)
                rest = {k: b for k, b in ctes.items() if k != v.name}
                body = _substitute_ctes(copy.deepcopy(q), rest)
                return A.SubqueryRef(body, v.alias or v.name)
            return v
        if isinstance(v, A.ANode):
            for f in dataclasses.fields(v):
                setattr(v, f.name, walk_val(getattr(v, f.name)))
            return v
        if isinstance(v, list):
            return [walk_val(x) for x in v]
        if isinstance(v, tuple):
            return tuple(walk_val(x) for x in v)
        return v

    return walk_val(node)


def _apply_cte_column_aliases(q, colnames: list, cte: str) -> None:
    """`WITH c(a, b) AS (...)`: rename the query's output columns."""
    target = q
    while isinstance(target, A.UnionStmt):
        # union output names come from the first branch (PG semantics)
        target = target.selects[0]
    items = target.items
    if any(isinstance(i.expr, A.Star) for i in items):
        raise SqlError(
            f'cannot apply column aliases to "{cte}": SELECT * in CTE body')
    if len(items) != len(colnames):
        raise SqlError(
            f'CTE "{cte}" has {len(items)} columns but {len(colnames)} '
            "aliases were given")
    for item, name in zip(items, colnames):
        item.alias = name


def parse(text: str) -> list[A.ANode]:
    return Parser(text).parse()


def parse_one(text: str) -> A.ANode:
    stmts = parse(text)
    if len(stmts) != 1:
        raise SqlError(f"expected one statement, got {len(stmts)}")
    return stmts[0]
