"""Statistics aggregate family as a pre-bind AST expansion.

Reference parity: the stddev/variance/covar/corr/regr_* aggregates the
reference ships as transition-function triples over float8 state arrays
(/root/reference/src/include/catalog/pg_aggregate.h:246,
/root/reference/src/backend/utils/adt/float.c float8_accum /
float8_regr_accum). The TPU-first translation is different in kind: each
statistic is EXPANDED before binding into arithmetic over the engine's
existing sum()/count() aggregates, so the two-phase partial/final
machinery, the dense/sort/fused-pallas paths, spill, and multihost
lockstep all apply with zero new executor state. The moment algebra (the
same one float8_accum uses internally):

    Sxx = sum(x^2) - sum(x)^2/n        var_pop  = Sxx/n
                                       var_samp = Sxx/(n-1)
    Sxy = sum(x*y) - sum(x)*sum(y)/n   covar_*  = Sxy/{n, n-1}
    corr = Sxy/sqrt(Sxx*Syy)           regr_slope = Sxy/Sxx  ...

Deviations from the reference, by design:
 - results are float64 (PG computes numeric for int inputs); inputs are
   cast to double precision up front, which also keeps scaled-decimal
   sums of squares from overflowing int64.
 - division by zero yields NULL engine-wide (ops/expr_eval.zero_invalid),
   which happens to give PG semantics for var_samp(n=1) -> NULL and
   corr with a constant column -> NULL; regr_r2 with Syy=0, Sxx!=0
   returns NULL where PG returns 1.

Two-argument aggregates follow PG's (Y, X) argument order and pair
semantics: only rows where BOTH arguments are non-null contribute —
each side is wrapped in CASE WHEN other IS NOT NULL so plain sum/count
see pair-restricted inputs.
"""

from __future__ import annotations

import copy
import dataclasses

from greengage_tpu.sql import ast as A
from greengage_tpu.sql.parser import SqlError

ONE_ARG = {"stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
           "var_pop"}
TWO_ARG = {"covar_pop", "covar_samp", "corr", "regr_count", "regr_avgx",
           "regr_avgy", "regr_sxx", "regr_syy", "regr_sxy", "regr_slope",
           "regr_intercept", "regr_r2"}
STAT_AGGS = ONE_ARG | TWO_ARG


def _num(v) -> A.ANode:
    return A.Num(str(v))


def _f64(x: A.ANode) -> A.ANode:
    return A.CastExpr(copy.deepcopy(x), "double precision")


def _mul(a, b):
    return A.Bin("*", a, b)


def _div(a, b):
    return A.Bin("/", a, b)


def _sub(a, b):
    return A.Bin("-", a, b)


def _sum(x):
    return A.FuncCall("sum", [x])


def _count(x):
    return A.FuncCall("count", [copy.deepcopy(x)])


def _sqrt(x):
    return A.FuncCall("sqrt", [x])


def _nonneg(x):
    """Clamp tiny negative fp residue in a centered sum of squares (the
    reference clamps the same way, float.c float8_stddev_samp)."""
    return A.CaseExpr(
        whens=[(A.Bin("<", x, _num(0)), _num(0))],
        else_=copy.deepcopy(x))


def _pairwise(x: A.ANode, other: A.ANode) -> A.ANode:
    """x cast to double, NULLed wherever `other` is NULL (PG pair
    semantics for two-argument aggregates)."""
    return A.CaseExpr(
        whens=[(A.IsNullTest(copy.deepcopy(other), negate=True), _f64(x))],
        else_=None)


def _sxx(xf: A.ANode, n: A.ANode) -> A.ANode:
    """sum(x^2) - sum(x)^2/n over an already-float argument AST."""
    sq = _sum(_mul(copy.deepcopy(xf), copy.deepcopy(xf)))
    sx = _sum(copy.deepcopy(xf))
    return _sub(sq, _div(_mul(sx, copy.deepcopy(sx)), n))


def _expand(name: str, args: list[A.ANode]) -> A.ANode:
    if name in ONE_ARG:
        if len(args) != 1:
            raise SqlError(f"{name}() takes exactly one argument")
        x = args[0]
        xf = _f64(x)
        n = _count(x)
        ss = _nonneg(_sxx(xf, copy.deepcopy(n)))
        denom = (copy.deepcopy(n) if name.endswith("_pop")
                 else _sub(copy.deepcopy(n), _num(1)))
        var = _div(ss, denom)
        if name.startswith("stddev"):
            return _sqrt(var)
        return var

    if len(args) != 2:
        raise SqlError(f"{name}() takes exactly two arguments")
    y, x = args                      # PG order: agg(Y, X)
    yp, xp = _pairwise(y, x), _pairwise(x, y)
    prod = _mul(copy.deepcopy(xp), copy.deepcopy(yp))
    n = _count(prod)
    sx, sy = _sum(copy.deepcopy(xp)), _sum(copy.deepcopy(yp))
    sxy = _sub(_sum(copy.deepcopy(prod)),
               _div(_mul(copy.deepcopy(sx), copy.deepcopy(sy)),
                    copy.deepcopy(n)))
    sxx = _nonneg(_sxx(xp, copy.deepcopy(n)))
    syy = _nonneg(_sxx(yp, copy.deepcopy(n)))
    if name == "regr_count":
        return n
    if name == "regr_avgx":
        return _div(sx, n)
    if name == "regr_avgy":
        return _div(sy, n)
    if name == "regr_sxx":
        return sxx
    if name == "regr_syy":
        return syy
    if name == "regr_sxy":
        return sxy
    if name == "covar_pop":
        return _div(sxy, n)
    if name == "covar_samp":
        return _div(sxy, _sub(n, _num(1)))
    if name == "corr":
        return _div(sxy, _sqrt(_mul(sxx, syy)))
    if name == "regr_slope":
        return _div(sxy, sxx)
    if name == "regr_intercept":
        slope = _div(copy.deepcopy(sxy), copy.deepcopy(sxx))
        return _sub(_div(sy, copy.deepcopy(n)),
                    _mul(slope, _div(sx, n)))
    if name == "regr_r2":
        return _div(_mul(copy.deepcopy(sxy), sxy), _mul(sxx, syy))
    raise SqlError(f"unknown statistics aggregate {name}")


def _rewrite(node):
    """Depth-first AST rewrite; nested SelectStmts are left alone (each
    gets its own expand_stat_aggs when it is bound)."""
    if isinstance(node, A.SelectStmt):
        return node
    if isinstance(node, A.FuncCall) and node.name in STAT_AGGS \
            and node.over is None:
        if node.star or node.distinct:
            raise SqlError(f"{node.name}() supports neither * nor DISTINCT")
        args = [_rewrite(a) for a in node.args]
        return _expand(node.name, args)
    if isinstance(node, A.ANode):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            setattr(node, f.name, _rewrite(v))
        return node
    if isinstance(node, list):
        return [_rewrite(v) for v in node]
    if isinstance(node, tuple):
        return tuple(_rewrite(v) for v in node)
    return node


def expand_stat_aggs(stmt: A.SelectStmt) -> None:
    """In-place expansion over the statement's expression positions that
    may hold aggregates (select items, HAVING, ORDER BY)."""
    for it in stmt.items:
        it.expr = _rewrite(it.expr)
    if stmt.having is not None:
        stmt.having = _rewrite(stmt.having)
    for ob in stmt.order_by:
        ob.expr = _rewrite(ob.expr)
