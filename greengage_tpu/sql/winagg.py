"""Window functions over grouped aggregates.

Reference parity: WindowAgg stacked above Agg in one plan
(nodeWindowAgg.c over nodeAgg.c) — the TPC-DS staple
`rank() over (order by sum(v) desc)`. Here the statement rewrites
pre-bind into two levels:

    inner:  the grouped aggregate select (group keys + every aggregate
            expression any window component references, aliased)
    outer:  the window functions over the inner's columns

so each level uses the engine's existing machinery (distributed two-phase
aggregation below, distributed windows above). HAVING stays with the
inner; DISTINCT/ORDER BY/LIMIT stay with the outer, their aggregate
references rewritten to the inner aliases."""

from __future__ import annotations

import copy
import dataclasses

from greengage_tpu.sql import ast as A

_LITERALS = (A.Num, A.Str, A.Null, A.Bool, A.DateLit, A.IntervalLit)


def expand_windows_over_aggs(stmt: A.SelectStmt):
    """-> replacement SelectStmt, or None when the statement doesn't mix
    grouped aggregation with window functions."""
    from greengage_tpu.sql.binder import (_ast_key, _ast_name,
                                          _contains_agg, _contains_window)

    has_aggs = bool(stmt.group_by) or stmt.grouping_sets is not None or any(
        _contains_agg(it.expr) for it in stmt.items) or (
        stmt.having is not None and _contains_agg(stmt.having))
    has_win = any(_contains_window(it.expr) for it in stmt.items)
    if not (has_aggs and has_win):
        return None

    inner_items: list[A.SelectItem] = []
    by_key: dict[str, str] = {}

    def ref(e: A.ANode) -> A.ANode:
        """Map a window-free expression to an inner alias reference."""
        if isinstance(e, _LITERALS):
            return copy.deepcopy(e)
        k = _ast_key(e)
        alias = by_key.get(k)
        if alias is None:
            alias = f"__wa{len(by_key)}"
            by_key[k] = alias
            inner_items.append(A.SelectItem(copy.deepcopy(e), alias))
        return A.Name((alias,))

    def conv(n):
        """Rewrite an outer expression: window calls keep their structure
        with every component mapped through ref(); window-free subtrees
        map whole (they evaluate in the grouped inner)."""
        if isinstance(n, A.FuncCall) and n.over is not None:
            spec = A.WindowSpec(
                partition_by=[ref(p) for p in n.over.partition_by],
                order_by=[A.OrderItem(ref(oi.expr), oi.desc, oi.nulls_first)
                          for oi in n.over.order_by],
                frame=copy.deepcopy(n.over.frame))
            return A.FuncCall(n.name, [ref(a) for a in n.args],
                              star=n.star, distinct=n.distinct, over=spec)
        if isinstance(n, A.ANode) and not _contains_window(n):
            return ref(n)

        if isinstance(n, A.ANode):
            for f in dataclasses.fields(n):
                setattr(n, f.name, conv(getattr(n, f.name)))
            return n
        if isinstance(n, list):
            return [conv(v) for v in n]
        if isinstance(n, tuple):
            return tuple(conv(v) for v in n)
        return n

    outer_items = []
    for it in stmt.items:
        name = it.alias or _ast_name(it.expr)
        outer_items.append(A.SelectItem(conv(it.expr), name))
    aliases = {it.alias for it in outer_items if it.alias}
    outer_order = []
    for oi in stmt.order_by:
        # bare output aliases and ordinals resolve against the OUTER
        # outputs (`order by rnk` names a window column); everything
        # else — group keys not in the select list, aggregate exprs —
        # routes through the inner via conv() and rides as a hidden
        # pass-through
        if (isinstance(oi.expr, A.Name) and oi.expr.parts[-1] in aliases) \
                or isinstance(oi.expr, A.Num):
            e = oi.expr
        else:
            e = conv(oi.expr)
        outer_order.append(A.OrderItem(e, oi.desc, oi.nulls_first))

    inner = A.SelectStmt(
        items=inner_items, from_=stmt.from_, where=stmt.where,
        group_by=stmt.group_by, having=stmt.having,
        grouping_sets=stmt.grouping_sets)
    return A.SelectStmt(
        items=outer_items, from_=[A.SubqueryRef(inner, "__w")],
        order_by=outer_order, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct)

