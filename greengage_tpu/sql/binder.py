"""Binder / semantic analyzer: AST -> typed logical plan.

The parse_analyze + subquery_planner front half of the reference
(src/backend/parser/analyze.c, optimizer/plan/planner.c) collapsed into one
pass: name resolution, type checking/coercion, aggregate extraction,
predicate pushdown, greedy equi-join ordering for comma-FROM, and the
string-dictionary lowering described in greengage_tpu/expr.py (literals ->
codes, LIKE -> LUTs, cross-dictionary equality -> translation LUTs).
"""

from __future__ import annotations

import dataclasses
import itertools
import re
import copy as _copy
import datetime
import operator

import numpy as np

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.catalog import PolicyKind
from greengage_tpu.planner import stats as _stats
from greengage_tpu.planner.logical import (
    Aggregate, ColInfo, Filter, Join, Limit, Plan, Project, Scan, Sort,
)
from greengage_tpu.sql import ast as A
from greengage_tpu.sql.parser import SqlError

_TYPE_MAP = {
    "int": T.INT32, "integer": T.INT32, "int4": T.INT32, "smallint": T.INT32,
    "bigint": T.INT64, "int8": T.INT64,
    "double precision": T.FLOAT64, "float8": T.FLOAT64, "float": T.FLOAT64,
    "real": T.FLOAT64,
    "date": T.DATE,
    "bool": T.BOOL, "boolean": T.BOOL,
    "text": T.TEXT, "varchar": T.TEXT, "char": T.TEXT, "character": T.TEXT,
    "bpchar": T.TEXT,
}


def type_from_name(name: str, typmod: tuple[int, ...]) -> T.SqlType:
    name = name.lower()
    if name in ("decimal", "numeric"):
        scale = typmod[1] if len(typmod) > 1 else 0
        return T.decimal(scale)
    if name in _TYPE_MAP:
        return _TYPE_MAP[name]
    raise SqlError(f"unknown type {name}")


class Scope:
    """Visible columns: list of (alias, {colname: ColInfo})."""

    def __init__(self):
        self.tables: list[tuple[str, dict[str, ColInfo]]] = []

    def add(self, alias: str, cols: dict[str, ColInfo]):
        if any(a == alias for a, _ in self.tables):
            raise SqlError(f'duplicate table alias "{alias}"')
        self.tables.append((alias, cols))

    def merged(self, other: "Scope") -> "Scope":
        s = Scope()
        s.tables = self.tables + other.tables
        return s

    def resolve(self, parts: tuple[str, ...]) -> ColInfo:
        if len(parts) == 2:
            for a, cols in self.tables:
                if a == parts[0]:
                    if parts[1] not in cols:
                        raise SqlError(f'column "{parts[0]}.{parts[1]}" does not exist')
                    return cols[parts[1]]
            raise SqlError(f'missing FROM-clause entry for table "{parts[0]}"')
        hits = [cols[parts[0]] for _, cols in self.tables if parts[0] in cols]
        if not hits:
            raise SqlError(f'column "{parts[0]}" does not exist')
        if len(hits) > 1:
            raise SqlError(f'column reference "{parts[0]}" is ambiguous')
        return hits[0]

    def all_cols(self) -> list[ColInfo]:
        if getattr(self, "empty_from", False):
            raise SqlError("SELECT * with no tables specified")
        return [c for _, cols in self.tables for c in cols.values()]

    def table_cols(self, alias: str) -> list[ColInfo]:
        for a, cols in self.tables:
            if a == alias:
                return list(cols.values())
        raise SqlError(f'unknown table "{alias}"')


class Binder:
    def __init__(self, catalog, store, subquery_executor=None,
                 optimizer: bool = True, scalar_device: bool = True):
        self.catalog = catalog
        self.store = store
        self._uid = itertools.count()
        self.consts: dict[str, np.ndarray] = {}   # LUT pool shipped to device
        self._scan_for: dict[str, "Scan"] = {}    # base col id -> its Scan
        # GUC 'scalar_device_enabled': lower raw-TEXT string-function
        # chains to device byte ops (E.RawStrOp); False = the legacy
        # per-row host chains (the microbench baseline)
        self.scalar_device = scalar_device
        # callable(SelectStmt) -> (python scalar | None, SqlType): runs an
        # uncorrelated scalar subquery at bind time (InitPlan analog)
        self.subquery_executor = subquery_executor
        # GUC 'optimizer' (the planner-selection analog): True routes
        # multi-relation FROMs through the Cascades-lite memo search
        # (planner/memo.py); False keeps the left-deep DP/greedy order
        self.optimizer = optimizer
        self.memo_used = False    # set when the memo produced a join tree

    def new_id(self, hint: str) -> str:
        return f"{hint}#{next(self._uid)}"

    # ------------------------------------------------------------------
    # entry
    # ------------------------------------------------------------------
    def bind_select(self, stmt) -> tuple[Plan, list[ColInfo]]:
        # bind NEVER mutates the caller's AST: the pre-bind expanders
        # (stat aggs, ordered sets, winagg, grouping sets) rewrite in
        # place, and callers bind the same statement twice (multihost
        # plan-hash + execute; plan caches keyed on the AST) — one
        # defensive copy here establishes the invariant for all of them

        stmt = _copy.deepcopy(stmt)
        if isinstance(stmt, A.UnionStmt):
            plan, outs = self._bind_union(stmt)
        else:
            plan, outs = self._bind_select(stmt)
        needed = set()
        _collect_needed(plan, needed)
        _prune_scans(plan, needed)
        return plan, outs

    # ------------------------------------------------------------------
    def _bind_select(self, stmt: A.SelectStmt) -> tuple[Plan, list[ColInfo]]:
        # statistics aggregates (stddev/variance/covar/corr/regr_*) expand
        # into sum/count moment algebra before anything else sees them
        # (sql/stataggs.py; pg_aggregate.h:246 family)
        from greengage_tpu.sql.stataggs import expand_stat_aggs

        expand_stat_aggs(stmt)
        # ordered-set aggregates rewrite the WHOLE statement (windowed
        # inner + order-statistic outer, sql/orderedset.py)
        from greengage_tpu.sql.orderedset import expand_ordered_set

        repl = expand_ordered_set(stmt)
        if repl is not None:
            return self._bind_select(repl)
        # windows over grouped aggregates: two-level rewrite (inner agg,
        # outer windows — sql/winagg.py, the WindowAgg-over-Agg stack)
        from greengage_tpu.sql.winagg import expand_windows_over_aggs

        repl = expand_windows_over_aggs(stmt)
        if repl is not None:
            return self._bind_select(repl)
        if stmt.grouping_sets is not None:
            return self._bind_grouping_sets(stmt)
        # peel subquery predicates (IN/EXISTS) off the WHERE — they become
        # semi/anti joins around the FROM plan (cdbsubselect.c pull-up)
        conjs = _split_and(stmt.where)
        normal, subq, corr_scalar = [], [], []
        for c in conjs:
            negate = False
            inner = c
            while isinstance(inner, A.Unary) and inner.op == "not":
                negate = not negate
                inner = inner.arg
            if isinstance(inner, (A.InSubquery, A.ExistsExpr)):
                subq.append((inner, negate != getattr(inner, "negate", False)))
            elif (isinstance(inner, A.Bin)
                  and inner.op in ("=", "<>", "<", "<=", ">", ">=")
                  and not negate
                  and (isinstance(inner.left, A.ScalarSubquery)
                       ^ isinstance(inner.right, A.ScalarSubquery))):
                # comparison against a scalar subquery: correlated ones are
                # decorrelated into a join; uncorrelated ones bind normally
                # (executed as InitPlans) via the `normal` path
                sub = inner.left if isinstance(inner.left, A.ScalarSubquery) else inner.right
                if self._is_correlated(sub.query):
                    corr_scalar.append(inner)
                    continue
                normal.append(c)
            else:
                normal.append(c)
        where = _join_and(normal)

        n_agg_items = sum(1 for it in stmt.items if _contains_agg(it.expr))
        plan, scope, leftover = self._bind_from(
            stmt.from_, where, group_by=stmt.group_by or None,
            naggs=n_agg_items)
        if leftover is not None:
            # sink each WHERE conjunct below the join sides it alone
            # references (inner/cross either side, outer probe side only) —
            # the qual-pushdown explicit JOIN ... ON syntax needs, which
            # also feeds selectivity into join estimates and exposes
            # pushable conjuncts to zone maps / dynamic partition pruning
            rest = []
            for c in _split_and(leftover):
                pred = self._predicate(c, scope)
                refs = _expr_col_ids(pred)
                sunk = False
                if refs:
                    plan, sunk = _sink_pred(plan, pred, refs)
                if not sunk:
                    rest.append(pred)
            if rest:
                plan = Filter(plan, rest[0] if len(rest) == 1
                              else E.BoolOp("and", tuple(rest)))
        for node, negate in subq:
            plan = self._bind_subquery_pred(node, negate, plan, scope)
        for cmp_ast in corr_scalar:
            plan = self._bind_corr_scalar(cmp_ast, plan, scope)

        # grouping-set branches: typed NULLs resolve against this FROM
        # scope; grouping() in a PLAIN grouped select folds to 0 (PG)
        self._resolve_typed_nulls(stmt, scope)
        if stmt.group_by and _contains_grouping(stmt):
            keys = {_ast_key(g) for g in stmt.group_by}
            for it in stmt.items:
                it.expr = _gs_rewrite(it.expr, keys, keys)
            if stmt.having is not None:
                stmt.having = _gs_rewrite(stmt.having, keys, keys)
            for oi in stmt.order_by:
                oi.expr = _gs_rewrite(oi.expr, keys, keys)

        # aggregate / window detection
        has_aggs = any(
            _contains_agg(it.expr) for it in stmt.items
        ) or (stmt.having is not None and _contains_agg(stmt.having)) \
            or stmt.group_by or stmt.forced_group
        has_windows = any(_contains_window(it.expr) for it in stmt.items)
        if has_aggs and has_windows:
            raise SqlError(
                "window functions over grouped aggregates are not supported yet")
        if stmt.having is not None and _contains_window(stmt.having):
            raise SqlError("window functions are not allowed in HAVING")
        if any(_contains_window(oi.expr) for oi in stmt.order_by):
            raise SqlError(
                "window functions in ORDER BY are not supported; use a "
                "select-list alias")

        if has_aggs:
            plan, agg_scope, rewrites = self._bind_aggregate(stmt, plan, scope)
            out_scope, sel_exprs = self._bind_select_items(stmt, agg_scope, rewrites)
        elif has_windows:
            if stmt.having is not None:
                raise SqlError("HAVING requires GROUP BY or aggregates")
            plan, win_rewrites = self._bind_windows(stmt, plan, scope)
            out_scope, sel_exprs = self._bind_select_items(
                stmt, scope, win_rewrites, allow_plain=True)
        else:
            if stmt.having is not None:
                raise SqlError("HAVING requires GROUP BY or aggregates")
            out_scope, sel_exprs = self._bind_select_items(stmt, scope, {})

        proj_cols = [c for c, _ in sel_exprs]

        # ORDER BY may reference non-projected expressions: aggregates/group
        # keys resolve through the rewrite map; plain input columns (PG
        # allows them for ungrouped queries) ride as hidden pass-throughs
        agg_rewrites = rewrites if has_aggs else {}
        src_to_out = {e.name: ci for ci, e in sel_exprs if isinstance(e, E.ColRef)}
        if stmt.distinct:
            # raw DISTINCT keys become transient-dictionary codes (equal
            # strings = equal codes; rendering decodes via the dictionary).
            # Before ORDER BY binding, so sort keys see coded columns.
            for i, (ci, e) in enumerate(sel_exprs):
                if ci.raw_ref is None:
                    continue
                coded = self._raw_to_codes(e)
                if coded is None:
                    raise SqlError(
                        "raw-encoded text cannot be used as a DISTINCT key")
                ci.dict_ref = _dict_ref_of(coded)
                ci.raw_ref = None
                ci.raw_chain = None
                sel_exprs[i] = (ci, coded)
        order_keys = []
        if stmt.order_by:
            for oi in stmt.order_by:
                e = None
                if agg_rewrites:
                    hit = (agg_rewrites.get(id(oi.expr))
                           or agg_rewrites.get(_ast_key(oi.expr)))
                    if hit is not None:
                        out_ci = src_to_out.get(hit.id)
                        if out_ci is not None:
                            e = _colref(out_ci)
                        else:
                            ci = ColInfo(self.new_id("ord"), hit.type, "?order?",
                                         hit.dict_ref, hidden=True,
                                         raw_ref=hit.raw_ref,
                                         raw_chain=getattr(hit, "raw_chain",
                                                           None))
                            sel_exprs.append((ci, _colref(hit)))
                            e = _colref(ci)
                if e is None:
                    try:
                        e = self._bind_order_expr(oi.expr, proj_cols, out_scope)
                    except SqlError:
                        if stmt.distinct:
                            raise
                        if has_aggs:
                            # expression OVER aggregates/keys not in the
                            # output (order by sum(x)/count(*), expanded
                            # stddev): bind against the agg rewrites and
                            # carry it as a hidden sort column
                            e = self._rewritten_expr(
                                oi.expr, agg_rewrites, scope)
                        else:
                            e = self._expr(oi.expr, scope)
                        ci = ColInfo(self.new_id("ord"), e.type, "?order?",
                                     _dict_ref_of(e), hidden=True,
                                     raw_ref=_raw_ref_of(e),
                                     raw_chain=_raw_chain_of(e))
                        sel_exprs.append((ci, e))
                        e = _colref(ci)
                if _raw_ref_of(e) is not None and not stmt.distinct \
                        and not has_aggs:
                    # raw sort key: convert the projected column's SOURCE
                    # expression (handles ordinals/aliases uniformly) and
                    # ride the transient-dictionary codes as a hidden
                    # column (codes + rank LUT sort correctly; surrogates
                    # don't)
                    src = None
                    if isinstance(e, E.ColRef):
                        src = next((ex for ci2, ex in sel_exprs
                                    if ci2.id == e.name), None)
                    coded = self._raw_to_codes(
                        src if src is not None else e)
                    ci = ColInfo(self.new_id("ord"), coded.type, "?order?",
                                 _dict_ref_of(coded), hidden=True)
                    sel_exprs.append((ci, coded))
                    e = _colref(ci)
                if not isinstance(e, E.ColRef):
                    # expression sort key over OUTPUT columns (order by
                    # sum_sales - avg_monthly_sales): the gather's host
                    # merge needs plain column keys, so re-express the
                    # key over the outputs' SOURCE exprs and ride it as
                    # a hidden projected column
                    sub = _subst_refs(e, {ci2.id: ex
                                          for ci2, ex in sel_exprs})
                    if sub is not None:
                        ci = ColInfo(self.new_id("ord"), e.type, "?order?",
                                     _dict_ref_of(e), hidden=True)
                        sel_exprs.append((ci, sub))
                        e = _colref(ci)
                order_keys.append((self._no_raw(e, "sort key"),
                                   oi.desc, oi.nulls_first))

        plan = Project(plan, sel_exprs)

        if stmt.distinct:
            keys = [(c, E.ColRef(c.id, c.type)) for c in proj_cols]
            plan = Aggregate(plan, keys, [])

        if order_keys:
            plan = Sort(plan, order_keys)
        if stmt.limit is not None or stmt.offset:
            plan = Limit(plan, stmt.limit, stmt.offset)
        return plan, proj_cols

    # ------------------------------------------------------------------
    # subquery predicates -> semi/anti joins (cdbsubselect.c pull-up analog)
    # ------------------------------------------------------------------
    def _bind_subquery_pred(self, node, negate: bool, plan: Plan, scope) -> Plan:
        from greengage_tpu.planner.logical import Join

        if isinstance(node, A.InSubquery):
            arg = self._expr(node.arg, scope)
            subplan, subouts = self._bind_select(node.query)
            if len(subouts) != 1:
                raise SqlError("subquery for IN must return one column")
            skey = _colref(subouts[0])
            lks, rks = self._align_join_keys([arg], [skey])
            kind = "anti" if negate else "semi"
            return Join(kind, plan, subplan, lks, rks, null_aware=negate)

        # EXISTS: correlation via equality predicates against the outer scope
        q = node.query
        if q.group_by or q.having:
            raise SqlError("GROUP BY/HAVING inside EXISTS is not supported")
        if q.offset:
            raise SqlError("OFFSET inside EXISTS is not supported")
        if q.limit == 0 or (q.items and any(_contains_agg(it.expr) for it in q.items)):
            # LIMIT 0: subquery is empty, EXISTS constant-false. Ungrouped
            # aggregate select list: exactly one row always, constant-true.
            const_true = q.limit != 0
            exists_val = const_true != negate
            if exists_val:
                return plan
            return Filter(plan, E.Literal(False, T.BOOL))
        # (any other LIMIT >= 1 can't change existence — ignored)

        subplan, sub_scope, _ = self._bind_from(q.from_, None)
        inner_only, corr_pairs, outer_only, residuals, bad = \
            _split_correlation(_split_and(q.where), scope, sub_scope)
        if bad:
            raise SqlError(
                "EXISTS correlation references columns visible in neither "
                "the subquery nor the outer query")
        if residuals and not corr_pairs:
            raise SqlError(
                "non-equality EXISTS correlation needs at least one "
                "equality conjunct to join on")
        if outer_only and negate:
            # not exists(P_outer AND Q) = NOT P_outer OR NOT exists(Q):
            # not expressible as a filter + anti join; bail honestly
            raise SqlError(
                "outer-only predicates inside NOT EXISTS are not supported")
        if inner_only:
            subplan = Filter(subplan, self._predicate(_join_and(inner_only), sub_scope))
        kind = "anti" if negate else "semi"
        if corr_pairs:
            lks = [self._expr(o, scope) for o, _ in corr_pairs]
            rks = [self._expr(i, sub_scope) for _, i in corr_pairs]
            lks, rks = self._align_join_keys(lks, rks)
            res_pred = None
            if residuals:
                # mixed-reference non-equality conjuncts (l2.x <> l1.x):
                # evaluated per candidate pair over the CSR expansion —
                # a probe row qualifies iff ANY pair passes (Q21 shape).
                # SUB scope first: an alias shadowed by the subquery must
                # resolve to the INNER table (SQL innermost-wins scoping)
                both = sub_scope.merged(scope)
                res_pred = self._predicate(_join_and(residuals), both)
            joined = Join(kind, plan, subplan, lks, rks, residual=res_pred)
        else:
            # uncorrelated EXISTS: constant-key semi join (matched iff sub
            # produced any row; duplicate constant keys are fine)
            one = E.Literal(1, T.INT32)
            joined = Join(kind, plan, subplan, [one], [one])
        if outer_only:
            joined = Filter(joined, self._predicate(_join_and(outer_only), scope))
        return joined

    # ------------------------------------------------------------------
    # correlated scalar subqueries -> join on grouped aggregate
    # ------------------------------------------------------------------
    def _is_correlated(self, q: A.SelectStmt) -> bool:
        """True if the subquery's WHERE references columns outside its own
        FROM (cheap probe bind of the sub scope, cached for the rewrite)."""
        try:
            _, sub_scope, _ = self._bind_from(q.from_, None)
        except SqlError:
            return False
        self._corr_probe = (id(q), sub_scope)
        for c in _split_and(q.where):
            for parts in _name_refs(c):
                if not _in_scope(parts, sub_scope):
                    return True
        return False

    def _bind_corr_scalar(self, cmp_ast: A.Bin, plan: Plan, scope) -> Plan:
        """Decorrelate ``outer_expr <op> (SELECT agg(...) FROM s WHERE
        s.k = outer.k ...)`` into: Aggregate(s GROUP BY k) joined to the
        outer plan on k, then a Filter applying <op> (nodeSubplan ->
        join+agg rewrite). A missing group means the scalar is NULL and the
        comparison drops the row — exactly the inner join's behavior — for
        sum/avg/min/max; a bare count() is 0 over an empty set, so it uses
        a LEFT join with the NULL count mapped to 0."""
        from greengage_tpu.planner.logical import Join

        if isinstance(cmp_ast.left, A.ScalarSubquery):
            sub, outer_ast, flip = cmp_ast.left, cmp_ast.right, True
        else:
            sub, outer_ast, flip = cmp_ast.right, cmp_ast.left, False
        q = sub.query
        if len(q.items) != 1 or not _contains_agg(q.items[0].expr):
            raise SqlError(
                "correlated scalar subqueries must compute one aggregate")
        if q.group_by or q.having or q.limit is not None or q.offset:
            raise SqlError(
                "GROUP BY/HAVING/LIMIT/OFFSET in a correlated scalar "
                "subquery is not supported")
        item = q.items[0].expr
        is_bare_count = (isinstance(item, A.FuncCall) and item.name == "count"
                         and item.over is None)
        if not is_bare_count and _contains_count(item):
            raise SqlError(
                "expressions over count() in correlated scalar subqueries "
                "are not supported (count of an empty set is 0, not NULL)")
        # classify the subquery's conjuncts against the outer scope,
        # reusing the probe bind's scope from _is_correlated when possible
        probe = getattr(self, "_corr_probe", None)
        if probe is not None and probe[0] == id(q):
            sub_scope = probe[1]
        else:
            _, sub_scope, _ = self._bind_from(q.from_, None)
        inner_only, corr_pairs, outer_only, residuals, bad = \
            _split_correlation(_split_and(q.where), scope, sub_scope)
        if bad or residuals:
            raise SqlError(
                "only equality correlation is supported in scalar subqueries")
        if not corr_pairs:
            raise SqlError("scalar subquery correlation not recognized")
        if outer_only and is_bare_count:
            raise SqlError(
                "outer-only predicates in a correlated count() subquery are "
                "not supported")
        # grouped aggregate over the correlation keys
        sub_stmt = A.SelectStmt(
            items=[A.SelectItem(q.items[0].expr, alias="__sv")]
            + [A.SelectItem(ie, alias=f"__ck{i}")
               for i, (_, ie) in enumerate(corr_pairs)],
            from_=q.from_,
            where=_join_and(inner_only),
            group_by=[ie for _, ie in corr_pairs],
        )
        subplan, subouts = self._bind_select(sub_stmt)
        val_ci, key_cis = subouts[0], subouts[1:]
        lks = [self._expr(o, scope) for o, _ in corr_pairs]
        rks = [_colref(ci) for ci in key_cis]
        lks, rks = self._align_join_keys(lks, rks)
        joined = Join("left" if is_bare_count else "inner",
                      plan, subplan, lks, rks)
        outer_e = self._expr(outer_ast, scope)
        sub_e = _colref(val_ci)
        if is_bare_count:
            # count over an empty correlated set is 0, not NULL
            sub_e = E.Case(
                whens=((E.IsNull(sub_e), E.Literal(0, T.INT64)),),
                else_=sub_e, type=T.INT64)
        le, re_ = (sub_e, outer_e) if flip else (outer_e, sub_e)
        le, re_ = self._coerce_pair(le, re_)
        out = Filter(joined, E.Cmp(cmp_ast.op, le, re_))
        if outer_only:
            out = Filter(out, self._predicate(_join_and(outer_only), scope))
        return out

    # ------------------------------------------------------------------
    # window functions
    # ------------------------------------------------------------------
    _WINFUNCS = {"row_number", "rank", "dense_rank", "sum", "count", "avg",
                 "min", "max", "lag", "lead", "first_value", "last_value",
                 "ntile"}
    # first_value/last_value are legal WITHOUT order by in PostgreSQL
    # (whole-frame semantics: the frame is the entire partition) — only
    # position-offset functions truly need an ordering
    _WIN_NEED_ORDER = {"lag", "lead", "ntile"}

    def _bind_windows(self, stmt, plan, scope):
        from greengage_tpu.planner.logical import Window

        calls: list[A.FuncCall] = []

        def collect(n):
            if isinstance(n, A.FuncCall) and n.over is not None:
                calls.append(n)
                return
            for ch in _ast_children(n):
                collect(ch)

        for it in stmt.items:
            collect(it.expr)

        def spec_key(over: A.WindowSpec) -> str:
            parts = [_ast_key(p) for p in over.partition_by]
            parts.append("|")
            for oi in over.order_by:
                parts.append(f"{_ast_key(oi.expr)}:{oi.desc}:{oi.nulls_first}")
            parts.append(f"|{over.frame}")
            return " ".join(parts)

        groups: dict[str, list[A.FuncCall]] = {}
        for fc in calls:
            groups.setdefault(spec_key(fc.over), []).append(fc)

        rewrites: dict = {}
        for fcs in groups.values():
            spec = fcs[0].over
            pkeys = [self._no_raw(self._win_raw_key(self._expr(p, scope)),
                                  "window partition key")
                     for p in spec.partition_by]
            okeys = [(self._win_order_key(
                          self._no_raw(self._win_raw_key(
                              self._expr(oi.expr, scope)),
                                       "window order key")),
                      oi.desc, oi.nulls_first)
                     for oi in spec.order_by]
            frame = self._bind_frame(spec.frame)
            wfuncs = []
            for fc in fcs:
                fname = fc.name
                if fname not in self._WINFUNCS:
                    raise SqlError(f"unknown window function {fname}")
                if fc.distinct:
                    raise SqlError("DISTINCT in window functions is not supported")
                if fname in self._WIN_NEED_ORDER and not spec.order_by:
                    raise SqlError(f"{fname}() requires OVER (... ORDER BY)")
                arg = None
                param = None
                if fname in ("row_number", "rank", "dense_rank"):
                    if fc.args or fc.star:
                        raise SqlError(f"{fname}() takes no arguments")
                    rtype = T.INT64
                elif fname == "ntile":
                    param = self._win_int_param(fc, 0, fname)
                    if param < 1:
                        raise SqlError("ntile() buckets must be positive")
                    rtype = T.INT64
                elif fname in ("lag", "lead"):
                    if not fc.args:
                        raise SqlError(f"{fname}() requires an argument")
                    # raw-TEXT args ride the transient dictionary: the
                    # function only moves the value, codes decode at
                    # finalize like any dict column
                    arg = self._win_raw_key(self._expr(fc.args[0], scope))
                    k = (self._win_int_param(fc, 1, fname)
                         if len(fc.args) > 1 else 1)
                    if k < 0:
                        raise SqlError(f"{fname}() offset must be >= 0")
                    default = None
                    if len(fc.args) > 2:
                        d = self._expr(fc.args[2], scope)
                        if not isinstance(d, E.Literal):
                            raise SqlError(
                                f"{fname}() default must be a literal")
                        default = self._coerce_literal(d, arg.type).value
                    param = (k, default)
                    rtype = arg.type
                elif fname in ("first_value", "last_value"):
                    if not fc.args:
                        raise SqlError(f"{fname}() requires an argument")
                    arg = self._win_raw_key(self._expr(fc.args[0], scope))
                    rtype = arg.type
                elif fc.star or not fc.args:
                    if fname != "count":
                        raise SqlError(f"{fname}(*) is not valid")
                    rtype = T.INT64
                else:
                    arg = self._expr(fc.args[0], scope)
                    if arg.type.kind is T.Kind.TEXT and fname in ("min", "max",
                                                                  "sum", "avg"):
                        raise SqlError(
                            f"window {fname}() over text is not supported yet")
                    rtype = E.agg_result_type(
                        "count" if fname == "count" else fname, arg.type)
                if fname in ("min", "max") and frame is not None                         and frame != (None, 0) and frame != (None, None):
                    raise SqlError(
                        f"window {fname}() supports only ROWS UNBOUNDED "
                        "PRECEDING frames (running or whole-partition)")
                if arg is not None:
                    self._no_raw(arg, "window function argument")
                ci = ColInfo(self.new_id(fname), rtype, fname,
                             _dict_ref_of(arg) if arg is not None and
                             fname in ("lag", "lead", "first_value",
                                       "last_value", "min", "max") else None)
                wfuncs.append((ci, fname, arg, bool(spec.order_by), param))
                rewrites[id(fc)] = ci
            plan = Window(plan, pkeys, okeys, wfuncs, frame)
        return plan, rewrites

    def _win_int_param(self, fc, idx, fname) -> int:
        a = fc.args[idx] if len(fc.args) > idx else None
        if not isinstance(a, A.Num) or "." in a.text:
            raise SqlError(f"{fname}() parameter must be an integer literal")
        return int(a.text)

    @staticmethod
    def _bind_frame(frame):
        """AST frame -> (preceding, following) row offsets with None =
        unbounded. Only ROWS frames change evaluation; the default RANGE
        UNBOUNDED PRECEDING..CURRENT ROW is the built-in peer semantics."""
        if frame is None:
            return None
        mode, lo, hi = frame
        if mode == "range":
            if lo == ("unbounded_preceding", None) and hi == ("current", None):
                return None   # the default frame
            raise SqlError(
                "only the default RANGE frame is supported; use ROWS")

        def bound(b, is_start):
            kind, n = b
            if kind == "unbounded_preceding":
                if not is_start:
                    raise SqlError("frame end cannot be UNBOUNDED PRECEDING")
                return None
            if kind == "unbounded_following":
                if is_start:
                    raise SqlError("frame start cannot be UNBOUNDED FOLLOWING")
                return None
            if kind == "current":
                return 0
            if kind == "preceding":
                return n if is_start else -n
            return -n if is_start else n   # following

        return (bound(lo, True), bound(hi, False))

    # ------------------------------------------------------------------
    # UNION
    # ------------------------------------------------------------------
    # GROUPING SETS / ROLLUP / CUBE
    # ------------------------------------------------------------------
    def _bind_grouping_sets(self, stmt: A.SelectStmt):
        """Desugar to UNION ALL of per-set grouped selects — the MPP-honest
        translation (each branch is an independent distributed aggregate;
        the reference executes the same shape via its own Append-of-Agg
        plans for grouping extensions, gram.y:12457 -> planner groupingsets
        paths). Keys absent from a set project as typed NULLs; grouping()
        folds to a per-branch constant bitmask."""

        universe: dict[str, A.ANode] = {}
        for s in stmt.grouping_sets:
            for e in s:
                universe.setdefault(_ast_key(e), e)
        # ORDER BY exprs containing aggregates or grouping() cannot bind at
        # the union level (they reference branch-internal state): lift each
        # into a hidden helper select item ordered by name
        order_by = list(stmt.order_by)
        helpers = []
        for i, oi in enumerate(order_by):
            if _contains_agg(oi.expr) or _has_grouping_call(oi.expr):
                name = f"?gsord{i}?"
                stmt.items.append(A.SelectItem(oi.expr, alias=name))
                helpers.append(name)
                order_by[i] = A.OrderItem(A.Name((name,)), oi.desc,
                                          oi.nulls_first)
        selects = []
        for s in stmt.grouping_sets:
            sub = _copy.deepcopy(stmt)
            sub.grouping_sets = None
            sub.group_by = _copy.deepcopy(s)
            sub.order_by = []
            sub.limit = None
            sub.offset = 0
            sub.distinct = False
            sub.forced_group = True
            present = {_ast_key(e) for e in s}
            for it in sub.items:
                it.expr = _gs_rewrite(it.expr, present, set(universe))
            if sub.having is not None:
                sub.having = _gs_rewrite(sub.having, present, set(universe))
            selects.append(sub)
        u = A.UnionStmt(selects=selects, all=not stmt.distinct,
                        order_by=order_by, limit=stmt.limit,
                        offset=stmt.offset)
        plan, outs = self._bind_union(u)
        if helpers:
            for c in outs:
                if c.name in helpers:
                    c.hidden = True
        return plan, outs

    def _resolve_typed_nulls(self, stmt, scope) -> None:
        """Pre-resolve TypedNullOf nodes against the FROM scope (the agg
        output scope their bind position sees no longer has the source
        columns). Raw TEXT keys resolve through their transient dictionary
        so NULL branches stay dictionary-compatible across the union."""
        def walk(n):
            if isinstance(n, A.TypedNullOf):
                if getattr(n, "rtype", None) is None:
                    inner = self._expr(n.arg, scope)
                    conv = self._raw_to_codes(inner)
                    if conv is not None:
                        inner = conv
                    n.rtype = inner.type
                    n.rdict = _dict_ref_of(inner)
                return
            if isinstance(n, A.SelectStmt):
                return
            for c in _ast_children(n):
                walk(c)

        for it in stmt.items:
            walk(it.expr)
        if stmt.having is not None:
            walk(stmt.having)

    # ------------------------------------------------------------------
    def _bind_union(self, stmt: A.UnionStmt):
        from greengage_tpu.planner.logical import Aggregate, Limit, Sort, Union

        branches = [self._bind_select(s) for s in stmt.selects]
        arity = len(branches[0][1])
        for _, outs in branches[1:]:
            if len(outs) != arity:
                raise SqlError("UNION branches must have the same column count")
        # per-position result types (+ TEXT dictionary compatibility)
        union_cols = []
        for i in range(arity):
            t = branches[0][1][i].type
            if any(outs_[i].raw_ref is not None for _, outs_ in branches):
                raise SqlError("raw-encoded text is not supported in UNION")
            dref = branches[0][1][i].dict_ref
            for _, outs in branches[1:]:
                ot = outs[i].type
                if ot.kind is T.Kind.TEXT and t.kind is T.Kind.TEXT:
                    if outs[i].dict_ref != dref:
                        raise SqlError(
                            "UNION over text columns from different "
                            "dictionaries is not supported yet")
                elif ot != t:
                    t = T.promote(t, ot)
            union_cols.append(ColInfo(self.new_id(branches[0][1][i].name), t,
                                      branches[0][1][i].name, dref))
        # cast branches to the union types where needed
        inputs = []
        for plan, outs in branches:
            exprs = []
            for uc, oc in zip(union_cols, outs):
                e = _colref(oc)
                if oc.type != uc.type:
                    e = E.Cast(e, uc.type)
                exprs.append((ColInfo(self.new_id(uc.name), uc.type, uc.name,
                                      oc.dict_ref), e))
            inputs.append(Project(plan, exprs))
        plan = Union(inputs, union_cols)
        # positional wiring: Union's cols adopt each branch's projected ids
        plan.branch_ids = [[c.id for c, _ in p.exprs] for p in inputs]
        outs = union_cols
        if not stmt.all:
            keys = [(c, E.ColRef(c.id, c.type)) for c in union_cols]
            plan = Aggregate(plan, keys, [])
            outs = [c for c, _ in keys]
        if stmt.order_by:
            keys = []
            for oi in stmt.order_by:
                e = self._bind_order_expr(oi.expr, outs, None)
                keys.append((self._no_raw(e, "sort key"), oi.desc, oi.nulls_first))
            plan = Sort(plan, keys)
        if stmt.limit is not None or stmt.offset:
            plan = Limit(plan, stmt.limit, stmt.offset)
        return plan, outs

    # ------------------------------------------------------------------
    # FROM binding with pushdown + greedy join ordering
    # ------------------------------------------------------------------
    def _bind_from(self, from_, where, group_by=None, naggs=0):
        """``group_by``/``naggs`` describe the aggregation that will sit
        above this FROM (when the caller is a grouped SELECT): the memo
        search folds its completion cost into join-order selection."""
        if not from_:
            # FROM-less SELECT (PG's Result node): one-row constant
            # relation, live on segment 0 — lets `select 1` work as a
            # subquery / union branch / recursive base term
            from greengage_tpu.planner.logical import ConstRel

            plan = ConstRel()
            scope = Scope()
            scope.add("", {})
            scope.empty_from = True   # Star over this scope must error
            leftover = where
            return plan, scope, leftover
        items = [self._bind_table_ref(t) for t in from_]

        conjuncts = _split_and(where) if where is not None else []

        if len(items) == 1:
            plan, scope = items[0]
            plan = self._push_filters(plan, scope, conjuncts)
            return plan, scope, None

        # comma-FROM join ordering: Selinger-style DP over left-deep trees
        # when statistics exist (CJoinOrderDP.cpp analog, <= 10 relations),
        # falling back to the r1 greedy order (CJoinOrderGreedy analog)
        remaining = list(items)
        conds = list(conjuncts)
        # push single-table predicates first
        for i, (p, s) in enumerate(remaining):
            p2, conds = self._push_single_table(p, s, conds)
            remaining[i] = (p2, s)

        # keep SELECT * / scope resolution in FROM-clause order regardless
        # of the join order the optimizer picks
        orig_scopes = [sc for _, sc in remaining]

        if self.optimizer:
            # Cascades-lite memo: bushy trees + distribution-property DP.
            # ORCA's fallback-on-failure semantics (optimizer_trace_fallback
            # / planner takes over when ORCA errors): ANY memo failure
            # degrades to the left-deep DP/greedy order below instead of
            # failing the statement
            try:
                tree = self._memo_join_tree(remaining, conds, group_by,
                                            naggs)
            except Exception:
                tree = None
            if tree is not None:
                self.memo_used = True
                plan, scope, conds = self._build_join_tree(
                    tree, remaining, conds)
                leftover = _join_and(conds)
                out_scope = Scope()
                for sc in orig_scopes:
                    out_scope = out_scope.merged(sc)
                return plan, out_scope, leftover

        order = self._dp_join_order(remaining, conds)
        if order is not None:
            remaining = [remaining[i] for i in order]

        plan, scope = remaining.pop(0)
        while remaining:
            picked = None
            for i, (rp, rs) in enumerate(remaining):
                eq, rest = _extract_equi(conds, scope, rs)
                if eq:
                    picked = (i, rp, rs, eq, rest)
                    break
            if picked is None:  # no equi edge: cross join the next one
                rp, rs = remaining.pop(0)
                join = Join("cross", plan, rp, [], [])
                scope = scope.merged(rs)
                plan = join
                continue
            i, rp, rs, eq, conds = picked
            remaining.pop(i)
            lkeys = [self._expr(lhs, scope) for lhs, _ in eq]
            rkeys = [self._expr(rhs, rs) for _, rhs in eq]
            lkeys, rkeys = self._align_join_keys(lkeys, rkeys)
            plan = Join("inner", plan, rp, lkeys, rkeys)
            scope = scope.merged(rs)
        leftover = _join_and(conds)
        out_scope = Scope()
        for sc in orig_scopes:
            out_scope = out_scope.merged(sc)
        return plan, out_scope, leftover

    # ------------------------------------------------------------------
    # memo search (the ORCA engine entry; planner/memo.py)
    # ------------------------------------------------------------------
    def _memo_join_tree(self, items, conds, group_by=None, naggs=0):
        """-> nested index tree from the Cascades-lite memo, or None when
        it doesn't apply (missing stats, edge cols without NDV, too many
        or disconnected relations — the fallback DP/greedy takes over)."""
        from greengage_tpu.planner import cost as C
        from greengage_tpu.planner import memo as M

        rels = []
        col_stats = []
        for plan, scope in items:
            info = self._rel_card(plan)
            if info is None:
                return None
            rows, stats = info
            node = plan
            while isinstance(node, Filter):
                node = node.child
            schema = self.catalog.get(node.table)
            pol = schema.policy
            dist: tuple = ()
            replicated = False
            if pol.kind is PolicyKind.HASH:
                by_name = {c.name: c.id for c in node.cols}
                if all(k in by_name for k in pol.keys):
                    dist = tuple(by_name[k] for k in pol.keys)
            elif pol.kind is PolicyKind.REPLICATED:
                replicated = True
            rels.append(M.RelInfo(rows, C.row_width(plan.out_cols()),
                                  dist, replicated))
            col_stats.append(stats)

        edges: dict[tuple, M.EdgeInfo] = {}
        for c in conds:
            hit = self._edge_of(c, items)
            if hit is None:
                continue
            i, j, li, ri, kinds = hit
            si, sj = col_stats[i].get(li), col_stats[j].get(ri)
            if si is None or sj is None or si.ndv <= 0 or sj.ndv <= 0:
                return None
            key = (min(i, j), max(i, j))
            e = edges.get(key)
            if e is None:
                e = edges[key] = M.EdgeInfo(key[0], key[1])
            pair = (li, ri) if i == key[0] else (ri, li)
            e.pairs.append(pair)
            # histogram join calculus with NDV-division fallback — memo
            # edge costs see the same estimate the parallelizer uses
            ksel = _stats.join_selectivity(si, sj, kinds)
            if ksel is None:
                ksel = 1.0 / max(si.ndv, sj.ndv)
            e.sel *= ksel * (1.0 - si.null_frac) * (1.0 - sj.null_frac)
        if not edges:
            return None
        nseg = self.catalog.segments.numsegments

        # the GROUP BY above this FROM, resolved to bound col ids: joint
        # join-order + agg-placement optimization (AggInfo docstring).
        # Only simple column group keys qualify — computed keys can't match
        # a distribution property anyway.
        agg = None
        if group_by:
            gcols, ndv_prod = [], 1.0
            for g in group_by:
                hit = None
                if isinstance(g, A.Name):
                    for idx, (_, scope) in enumerate(items):
                        try:
                            ci = scope.resolve(g.parts)
                            hit = (idx, ci.id)
                            break
                        except SqlError:
                            continue
                if hit is None:
                    gcols = None
                    break
                idx, cid = hit
                cs = col_stats[idx].get(cid)
                if cs is None or cs.ndv <= 0:
                    gcols = None
                    break
                gcols.append(cid)
                ndv_prod *= max(cs.ndv, 1.0)
            if gcols:
                agg = M.AggInfo(tuple(gcols), ndv_prod, max(naggs, 1))
        return M.optimize(rels, list(edges.values()), nseg, agg)

    def _build_join_tree(self, tree, items, conds):
        """Materialize the memo's nested index tree into Join nodes,
        consuming the equi conjuncts that each join edge uses."""
        conds = list(conds)

        def rec(t):
            nonlocal conds
            if not isinstance(t, tuple):
                return items[t]
            lp, ls = rec(t[0])
            rp, rs = rec(t[1])
            eq, conds = _extract_equi(conds, ls, rs)
            merged = ls.merged(rs)
            if not eq:
                return Join("cross", lp, rp, [], []), merged
            lkeys = [self._expr(l, ls) for l, _ in eq]
            rkeys = [self._expr(r, rs) for _, r in eq]
            lkeys, rkeys = self._align_join_keys(lkeys, rkeys)
            return Join("inner", lp, rp, lkeys, rkeys), merged

        plan, scope = rec(tree)
        return plan, scope, conds

    # ------------------------------------------------------------------
    # DP join ordering (System R over left-deep trees)
    # ------------------------------------------------------------------
    def _dp_join_order(self, items, conds):
        """-> permutation of item indices minimizing the classic sum of
        intermediate cardinalities, or None (no stats / too many / cross
        products involved). Cardinalities: filtered base rows x product of
        1/max(NDV) per equi edge — the same estimates the planner uses, so
        the chosen order matches its costing."""
        n = len(items)
        if n < 3 or n > 10:
            return None
        cards = []
        col_stats = []
        for plan, scope in items:
            info = self._rel_card(plan)
            if info is None:
                return None
            cards.append(info[0])
            col_stats.append(info[1])
        # equi edges: (i, j, sel)
        edges: dict[tuple, float] = {}
        for c in conds:
            pair = self._edge_of(c, items)
            if pair is None:
                continue
            i, j, li, ri, _kind = pair
            si = col_stats[i].get(li)
            sj = col_stats[j].get(ri)
            if si is None or sj is None or si.ndv <= 0 or sj.ndv <= 0:
                return None
            sel = 1.0 / max(si.ndv, sj.ndv)
            key = (min(i, j), max(i, j))
            edges[key] = edges.get(key, 1.0) * sel
        if not edges:
            return None

        def joined_card(card, S, j):
            sel = 1.0
            connected = False
            for i in range(n):
                if S & (1 << i):
                    e = edges.get((min(i, j), max(i, j)))
                    if e is not None:
                        sel *= e
                        connected = True
            if not connected:
                return None
            return card * cards[j] * sel

        # dp[mask] = (total cost, out card, order tuple), left-deep only;
        # each round's frontier holds all masks of one popcount, so a plain
        # per-round min per mask is the full Selinger DP
        frontier = {1 << i: (0.0, cards[i], (i,)) for i in range(n)}
        for _ in range(n - 1):
            nxt: dict[int, tuple] = {}
            for mask, (cost, card, order) in frontier.items():
                for j in range(n):
                    if mask & (1 << j):
                        continue
                    jc = joined_card(card, mask, j)
                    if jc is None:
                        continue   # avoid cross products
                    m2 = mask | (1 << j)
                    c2 = cost + jc
                    cur = nxt.get(m2)
                    if cur is None or c2 < cur[0]:
                        nxt[m2] = (c2, jc, order + (j,))
            frontier = nxt
        full = (1 << n) - 1
        if full not in frontier:
            return None   # not fully connectable without cross joins
        return list(frontier[full][2])

    def _rel_card(self, plan):
        """(filtered row estimate, {col id -> ColumnStats}) for a base
        relation (possibly already wrapped in pushed Filters)."""
        from greengage_tpu.planner import cost as C

        filters = []
        node = plan
        while isinstance(node, Filter):
            filters.append(node.predicate)
            node = node.child
        if not isinstance(node, Scan):
            return None
        schema = self.catalog.get(node.table)
        ts = getattr(schema, "stats", None)
        if ts is None or ts.rows <= 0:
            return None
        by_id = {c.id: c.name for c in node.cols}
        stats_by_id = {cid: ts.columns.get(nm) for cid, nm in by_id.items()}

        def lookup(cid):
            return stats_by_id.get(cid)

        rows = float(ts.rows)
        for pred in filters:
            rows *= C.filter_selectivity(pred, lookup)
        return max(rows, 1.0), stats_by_id

    def _edge_of(self, cond, items):
        """cond is an equi edge between two distinct items ->
        (i, j, left col id, right col id) or None."""
        if not (isinstance(cond, A.Bin) and cond.op == "="):
            return None

        def side(ast):
            if not isinstance(ast, A.Name):
                return None
            for idx, (_, scope) in enumerate(items):
                try:
                    ci = scope.resolve(ast.parts)
                    return idx, ci.id, ci.type.kind
                except SqlError:
                    continue
            return None

        a, b = side(cond.left), side(cond.right)
        if a is None or b is None or a[0] == b[0]:
            return None
        return a[0], b[0], a[1], b[1], (a[2], b[2])

    def _bind_table_ref(self, t: A.TableRef):
        if isinstance(t, A.BaseTable):
            schema = self.catalog.get(t.name)
            cols = {}
            out = []
            for c in schema.columns:
                is_text = c.type.kind is T.Kind.TEXT
                is_raw = is_text and c.encoding == "raw"
                ci = ColInfo(
                    self.new_id(c.name), c.type, c.name,
                    dict_ref=(t.name, c.name) if is_text and not is_raw else None,
                    raw_ref=(t.name, c.name) if is_raw else None,
                )
                cols[c.name] = ci
                out.append(ci)
            scan = Scan(t.name, out)
            if schema.is_partitioned:
                # all child storage tables; the planner statically prunes
                # this set from pushed conjuncts (PartitionSelector role)
                scan.parts = tuple(schema.storage_tables())
                scan.parts_total = len(schema.partitions)
            for ci in out:
                self._scan_for[ci.id] = scan
            scope = Scope()
            scope.add(t.alias or t.name, cols)
            return scan, scope
        if isinstance(t, A.SubqueryRef):
            if isinstance(t.query, A.UnionStmt):
                plan, outs = self._bind_union(t.query)
            else:
                plan, outs = self._bind_select(t.query)
            scope = Scope()
            scope.add(t.alias, {c.name: c for c in outs})
            return plan, scope
        if isinstance(t, A.JoinRef):
            if t.kind == "full":
                return self._bind_full_join(t)
            lp, ls = self._bind_table_ref(t.left)
            rp, rs = self._bind_table_ref(t.right)
            merged = ls.merged(rs)
            if t.kind == "cross":
                return Join("cross", lp, rp, [], []), merged
            conjuncts = _split_and(t.on)
            eq, rest = _extract_equi(conjuncts, ls, rs)
            if not eq:
                raise SqlError("join requires at least one equality condition")
            lkeys = [self._expr(l, ls) for l, _ in eq]
            rkeys = [self._expr(r, rs) for _, r in eq]
            lkeys, rkeys = self._align_join_keys(lkeys, rkeys)
            residual = _join_and(rest)
            join = Join(t.kind, lp, rp, lkeys, rkeys,
                        residual=self._predicate(residual, merged) if residual else None)
            return join, merged
        raise SqlError(f"unsupported FROM item {type(t).__name__}")

    def _bind_full_join(self, t: A.JoinRef):
        """FULL OUTER JOIN as a union rewrite:
            A FULL JOIN B ON k  ==  (A LEFT JOIN B ON k)
                                    UNION ALL
                                    (NULL-extended B ANTI JOIN A ON k)
        Each side is bound twice (fresh column ids per instance); the two
        branches are positionally wired through a Union whose output columns
        carry the original table aliases so name resolution sees one joined
        scope. Matches nodeHashjoin.c's HJ_FILL_OUTER handling by plan shape
        rather than kernel state.
        """
        from greengage_tpu.planner.logical import Union

        conjuncts = _split_and(t.on)
        lp, ls = self._bind_table_ref(t.left)
        rp, rs = self._bind_table_ref(t.right)
        eq, rest = _extract_equi(conjuncts, ls, rs)
        if not eq:
            raise SqlError("join requires at least one equality condition")
        if rest:
            raise SqlError(
                "FULL JOIN supports only equality conditions in ON")
        lkeys = [self._expr(l, ls) for l, _ in eq]
        rkeys = [self._expr(r, rs) for _, r in eq]
        lkeys, rkeys = self._align_join_keys(lkeys, rkeys)
        branch1 = Join("left", lp, rp, lkeys, rkeys)

        # second instances for the anti branch (B rows with no A match)
        lp2, ls2 = self._bind_table_ref(t.left)
        rp2, rs2 = self._bind_table_ref(t.right)
        lkeys2 = [self._expr(l, ls2) for l, _ in eq]
        rkeys2 = [self._expr(r, rs2) for _, r in eq]
        rkeys2, lkeys2 = self._align_join_keys(rkeys2, lkeys2)
        branch2 = Join("anti", rp2, lp2, rkeys2, lkeys2)

        # flattened output: left cols then right cols, preserving alias
        # structure. (alias, name, branch-1 col, branch-2 col-or-None)
        slots = []
        for (a1, cols1), (a2, cols2) in zip(ls.tables, ls2.tables):
            for n, c in cols1.items():
                slots.append((a1, n, c, None))  # left side: NULL in branch 2
        for (a1, cols1), (a2, cols2) in zip(rs.tables, rs2.tables):
            for n, c in cols1.items():
                slots.append((a1, n, c, cols2[n]))

        union_cols = []
        b1_exprs, b2_exprs = [], []
        out_scope = Scope()
        per_alias: dict[str, dict[str, ColInfo]] = {}
        for alias, name, c1, c2 in slots:
            if c1.raw_ref is not None:
                raise SqlError(
                    "raw-encoded text is not supported in FULL JOIN")
            uc = ColInfo(self.new_id(name), c1.type, name, c1.dict_ref)
            union_cols.append(uc)
            per_alias.setdefault(alias, {})[name] = uc
            b1_exprs.append((ColInfo(self.new_id(name), c1.type, name,
                                     c1.dict_ref), _colref(c1)))
            e2 = (E.Literal(None, c1.type) if c2 is None else _colref(c2))
            b2_exprs.append((ColInfo(self.new_id(name), c1.type, name,
                                     c1.dict_ref), e2))
        for alias, cols in per_alias.items():
            out_scope.add(alias, cols)
        inputs = [Project(branch1, b1_exprs), Project(branch2, b2_exprs)]
        plan = Union(inputs, union_cols)
        plan.branch_ids = [[c.id for c, _ in p.exprs] for p in inputs]
        return plan, out_scope

    def _align_join_keys(self, lkeys, rkeys):
        """Type-align join key pairs; TEXT pairs from different dictionaries
        get a translation LUT on the right side."""
        out_l, out_r = [], []
        for lk, rk in zip(lkeys, rkeys):
            # raw TEXT join keys ride their transient dictionaries; the
            # cross-dictionary translation below then applies as usual
            if _raw_ref_of(lk) is not None:
                lk = self._raw_to_codes(lk)
            if _raw_ref_of(rk) is not None:
                rk = self._raw_to_codes(rk)
            lt, rt = lk.type, rk.type
            if lt.kind is T.Kind.TEXT and rt.kind is T.Kind.TEXT:
                ld = _dict_ref_of(lk)
                rd = _dict_ref_of(rk)
                if ld != rd and ld is not None and rd is not None:
                    left_dict = self.store.dictionary(*ld)
                    right_dict = self.store.dictionary(*rd)
                    lut = np.array(
                        [left_dict.lookup(v) for v in right_dict.values] + [-1],
                        dtype=np.int32,
                    )
                    tid = self._const(lut)
                    rk = E.Lut(rk, tid, type=T.TEXT)
                    # translated codes live in the LEFT dictionary's code
                    # space: motion/join hashing must use the left dict's
                    # hash LUT (code -1 = absent -> sentinel row)
                    object.__setattr__(rk, "_dict_ref", ld)
            elif lt != rt:
                common = T.promote(lt, rt)
                if lt != common:
                    lk = E.Cast(lk, common)
                if rt != common:
                    rk = E.Cast(rk, common)
            out_l.append(lk)
            out_r.append(rk)
        return out_l, out_r

    def _push_filters(self, plan, scope, conjuncts):
        """Bind WHERE conjuncts over a single FROM item, sinking each
        below any explicit-JOIN sides it alone references (see
        _sink_pred) — unsinkable conjuncts gather in one Filter on top."""
        rest = []
        for c in conjuncts:
            pred = self._predicate(c, scope)
            refs = _expr_col_ids(pred)
            sunk = False
            if refs:
                plan, sunk = _sink_pred(plan, pred, refs)
            if not sunk:
                rest.append(pred)
        if rest:
            plan = Filter(plan, rest[0] if len(rest) == 1
                          else E.BoolOp("and", tuple(rest)))
        return plan

    def _push_single_table(self, plan, scope, conds):
        mine, rest = [], []
        names = {c.name for c in scope.all_cols()} | {
            f"{a}.{n}" for a, cols in scope.tables for n in cols
        }
        for c in conds:
            refs = _name_refs(c)
            if refs and all(self._resolvable(r, scope) for r in refs):
                mine.append(c)
            else:
                rest.append(c)
        if mine:
            plan = Filter(plan, self._predicate(_join_and(mine), scope))
        return plan, rest

    def _resolvable(self, parts, scope) -> bool:
        try:
            scope.resolve(parts)
            return True
        except SqlError:
            return False

    # ------------------------------------------------------------------
    # aggregation
    # ------------------------------------------------------------------
    def _bind_aggregate(self, stmt, plan, scope):
        # 1. bind group key exprs
        group_exprs = []
        for g in stmt.group_by:
            if isinstance(g, A.Num):   # ordinal
                idx = int(g.text) - 1
                g = stmt.items[idx].expr
            group_exprs.append((g, self._expr(g, scope)))

        # 2. collect aggregate calls across select/having/order
        agg_nodes: list[A.FuncCall] = []

        seen_keys: dict[str, A.FuncCall] = {}

        def collect(n):
            if isinstance(n, A.FuncCall) and n.over is None and \
                    n.name in ("count", "sum", "avg", "min", "max"):
                # dedupe textually-identical aggregates (ORDER BY repeats)
                k = _ast_key(n)
                if k in seen_keys:
                    dup_map[id(n)] = seen_keys[k]
                else:
                    seen_keys[k] = n
                    agg_nodes.append(n)
                return
            for ch in _ast_children(n):
                collect(ch)

        dup_map: dict[int, A.FuncCall] = {}

        for it in stmt.items:
            collect(it.expr)
        if stmt.having is not None:
            collect(stmt.having)
        for oi in stmt.order_by:
            collect(oi.expr)

        # 3. build input projection: group keys + agg args
        proj: list[tuple[ColInfo, E.Expr]] = []
        key_cols: list[tuple[ColInfo, E.Expr]] = []
        for gast, ge in group_exprs:
            conv = self._raw_to_codes(ge)
            if conv is not None:
                ge = conv
            ci = ColInfo(self.new_id("g"), ge.type, _ast_name(gast), _dict_ref_of(ge))
            proj.append((ci, ge))
            key_cols.append((ci, E.ColRef(ci.id, ci.type)))

        aggs: list[tuple[ColInfo, E.Agg]] = []
        agg_map: dict[int, ColInfo] = {}
        distinct_args: list[ColInfo] = []
        for fc in agg_nodes:
            if fc.star:
                arg = None
                arg_ref = None
                atype = None
            else:
                ae = self._expr(fc.args[0], scope)
                if fc.name in ("min", "max"):
                    # raw text -> transient dictionary codes, then TEXT
                    # codes -> lexicographic rank space (first-seen codes
                    # don't order; ranks do and decode via the sorted
                    # dictionary)
                    conv = self._raw_to_codes(ae)
                    if conv is not None:
                        ae = conv
                    if ae.type.kind is T.Kind.TEXT:
                        ae = self._text_rank_expr(ae)
                    self._no_raw(ae, f"{fc.name}() argument")
                if fc.name != "count":
                    # count(chain) is fine (validity passes through); any
                    # value-dependent aggregate would sum surrogates
                    self._no_rawchain(ae, f"{fc.name}() argument")
                atype = ae.type
                ci_in = ColInfo(self.new_id("a_in"), ae.type, "arg", _dict_ref_of(ae))
                proj.append((ci_in, ae))
                arg_ref = E.ColRef(ci_in.id, ci_in.type)
                if _dict_ref_of(ae) is not None:
                    object.__setattr__(arg_ref, "_dict_ref", _dict_ref_of(ae))
            func = "count_star" if fc.star else fc.name
            rtype = E.agg_result_type(func, atype)
            agg = E.Agg(func, arg_ref, fc.distinct, rtype)
            # TEXT min/max results decode through the argument's (rank)
            # dictionary
            ci = ColInfo(self.new_id(func), rtype, func,
                         dict_ref=(_dict_ref_of(ae)
                                   if rtype.kind is T.Kind.TEXT and not fc.star
                                   else None))
            aggs.append((ci, agg))
            agg_map[id(fc)] = ci
            if fc.distinct:
                if fc.star:
                    raise SqlError("count(distinct *) is not valid")
                distinct_args.append(
                    ColInfo(ci_in.id, ci_in.type, ci_in.name, ci_in.dict_ref))

        if not agg_nodes and not group_exprs:
            # GROUP BY () with no aggregate calls (grouping-sets desugar
            # branch, forced_group): anchor the global one-row group with
            # an internal count(*) no output references — the executor's
            # scalar-aggregate path then applies unchanged
            synth = ColInfo(self.new_id("count"), T.INT64, "count")
            aggs.append((synth, E.Agg("count_star", None, False, T.INT64)))
        if not proj:
            dummy = ColInfo(self.new_id("one"), T.INT32, "one")
            proj.append((dummy, E.Literal(1, T.INT32)))
        plan = Project(plan, proj)
        plain_aggs = [(ci, a) for ci, a in aggs if not a.distinct]
        dist_aggs = [(ci, a) for ci, a in aggs if a.distinct]
        if dist_aggs and len(dist_aggs) > 1:
            raise SqlError(
                "multiple DISTINCT aggregates in one query are not "
                "supported yet")
        if dist_aggs and plain_aggs:
            # MIXED distinct + plain: split-and-rejoin (the reference plans
            # this with multiple agg levels): plan A aggregates the plain
            # functions, plan B dedupes the distinct argument then
            # aggregates it; A join B on the group keys reassembles one row
            # per group. Both branches share the projected input subtree.
            ci_d, agg_d = dist_aggs[0]
            dci = distinct_args[0]
            plan_a = Aggregate(plan, key_cols, plain_aggs)
            # NOTE the id invariant: an Aggregate's group-key exprs must
            # reference the SAME ids its key ColInfos carry, so the final
            # phase of a two-phase plan resolves them against the partial's
            # output. Both branches therefore reuse key_cols; the join's
            # duplicate output ids carry equal values by the join equality.
            dedupe = Aggregate(plan, list(key_cols) + [
                (dci, E.ColRef(dci.id, dci.type))], [])
            plan_b = Aggregate(
                dedupe,
                [(kc, E.ColRef(kc.id, kc.type)) for kc, _ in key_cols],
                [(ci_d, E.Agg(agg_d.func, E.ColRef(dci.id, dci.type),
                              False, agg_d.type))])
            if key_cols:
                # NULL-safe rejoin: GROUP BY treats NULL keys as one group,
                # but join equality drops NULLs — so each key joins as
                # (COALESCE(k, 0), k IS NULL) pairs, which match NULL
                # groups to each other and never collide with real zeros
                def null_safe(kc):
                    ref = _colref(kc)
                    coalesced = E.Case(
                        ((E.IsNull(ref), _zero_lit(kc.type)),), ref, kc.type)
                    if kc.dict_ref is not None:
                        # TEXT: codes hash through the dictionary LUT;
                        # code -1 hits the sentinel row
                        object.__setattr__(coalesced, "_dict_ref", kc.dict_ref)
                    return [coalesced, E.IsNull(ref)]

                lks = [e for kc, _ in key_cols for e in null_safe(kc)]
                rks = [e for kc, _ in key_cols for e in null_safe(kc)]
                lks, rks = self._align_join_keys(lks, rks)
                plan = Join("inner", plan_a, plan_b, lks, rks)
            else:
                one = E.Literal(1, T.INT32)
                plan = Join("inner", plan_a, plan_b, [one], [one])
        elif dist_aggs:
            # DISTINCT only: dedupe (group keys, arg) first, then aggregate
            # plain over the distinct combinations (the classic two-level
            # rewrite)
            dci = distinct_args[0]
            dedupe_keys = list(key_cols) + [
                (dci, E.ColRef(dci.id, dci.type))]
            plan = Aggregate(plan, dedupe_keys, [])
            ci, agg = dist_aggs[0]
            aggs = [(ci, E.Agg(agg.func, agg.arg, False, agg.type))]
            plan = Aggregate(plan, key_cols, aggs)
        else:
            plan = Aggregate(plan, key_cols, aggs)

        # 4. scope over agg outputs; rewrites: ast node -> ColInfo
        out_scope = Scope()
        cols = {}
        rewrites: dict = {}
        for (gast, _), (ci, _) in zip(group_exprs, key_cols):
            rewrites[_ast_key(gast)] = ci
            cols[ci.name] = ci
        for fc in agg_nodes:
            rewrites[id(fc)] = agg_map[id(fc)]
        for dup_id, canon in dup_map.items():
            rewrites[dup_id] = agg_map[id(canon)]
        out_scope.add("", cols)

        if stmt.having is not None:
            pred = self._rewritten_predicate(stmt.having, rewrites, scope)
            plan = Filter(plan, pred)
        return plan, out_scope, rewrites

    def _bind_select_items(self, stmt, scope, rewrites, allow_plain=False):
        sel_exprs: list[tuple[ColInfo, E.Expr]] = []
        for it in stmt.items:
            if isinstance(it.expr, A.Star):
                if rewrites and not allow_plain:
                    raise SqlError("* not allowed with GROUP BY")
                cols = (scope.table_cols(it.expr.table) if it.expr.table
                        else scope.all_cols())
                for c in cols:
                    ci = ColInfo(self.new_id(c.name), c.type, c.name, c.dict_ref,
                                 raw_ref=c.raw_ref,
                                 raw_chain=getattr(c, "raw_chain", None))
                    sel_exprs.append((ci, E.ColRef(c.id, c.type)))
                continue
            e = self._rewritten_expr(it.expr, rewrites, scope, allow_plain)
            e = self._text_literal_to_dict(e)
            name = it.alias or _ast_name(it.expr)
            if isinstance(e, E.RawChain) and e.type.kind is not T.Kind.TEXT:
                raise SqlError(
                    "numeric functions of raw-encoded text are only "
                    "supported in WHERE")
            ci = ColInfo(self.new_id(name), e.type, name, _dict_ref_of(e),
                         raw_ref=_raw_ref_of(e), raw_chain=_raw_chain_of(e))
            if _raw_chain_of(e):
                # projected raw-text chain: the surrogate decodes + applies
                # the chain per row at result finalize — a host fallback
                self._count_scalar(device=False)
            sel_exprs.append((ci, e))
        return scope, sel_exprs

    def _text_literal_to_dict(self, e: E.Expr) -> E.Expr:
        """A projected TEXT constant has no device representation of its
        own: lower it to code 0 of a one-entry derived dictionary (the
        same mechanism string-function results ride)."""
        if isinstance(e, E.Literal) and e.type.kind is T.Kind.TEXT \
                and isinstance(e.value, str):
            ref = self.store.derived_dictionary([e.value])
            lit = E.Literal(0, T.TEXT)
            object.__setattr__(lit, "_dict_ref", ref)
            return lit
        return e

    def _raw_to_codes(self, e: E.Expr):
        """Raw-TEXT expression -> dictionary-coded expression under the
        column's transient per-version dictionary (TableStore
        .raw_dictionary). This is how raw columns become usable as
        GROUP BY / ORDER BY / DISTINCT / join keys: the device sees int32
        codes with full dictionary services (hash LUTs, rank LUTs,
        translation, decode). Returns None when ``e`` is not raw."""
        rr = _raw_ref_of(e)
        if rr is None:
            return None
        base = e.arg if isinstance(e, E.RawChain) else e
        if not isinstance(base, E.ColRef) or base.name not in self._scan_for:
            raise SqlError(
                "raw-encoded text keys are only supported directly on "
                "base-table columns")
        scan = self._scan_for[base.name]
        vname = "@rc:" + rr[1]
        ref = self.store.raw_dictionary(rr[0], rr[1])
        coded: E.Expr = self._raw_aux_col(scan, vname, T.TEXT, dict_ref=ref)
        for step in (_raw_chain_of(e) or ()):
            from greengage_tpu.utils import strfuncs

            kind = strfuncs.SPECS[step[0]][2]
            coded = self._lower_str_step(coded, tuple(step), kind)
        return coded

    def _win_raw_key(self, e: E.Expr) -> E.Expr:
        """Raw-TEXT window partition/order keys re-code into the column's
        transient per-version dictionary (the same service ORDER BY uses,
        _raw_to_codes) — the device then sees bounded int32 codes with
        full dictionary services, so `ntile(4) over (order by
        raw_text_col)` rides the gather-free rank machinery instead of
        being rejected (or funneled) as raw."""
        conv = self._raw_to_codes(e)
        return conv if conv is not None else e

    def _win_order_key(self, e: E.Expr) -> E.Expr:
        """Dict-TEXT window order keys re-code into RANK space at bind
        time: ranks order lexicographically AND are small bounded ints,
        which lets the planner's in-place global ranking pack them
        (planner._ordered_global_spec) instead of funneling TEXT keys."""
        if e.type.kind is T.Kind.TEXT and _dict_ref_of(e) is not None \
                and not isinstance(e, E.RawChain) \
                and _raw_ref_of(e) is None:
            n = len(self.store.dictionary(*_dict_ref_of(e)))
            r = self._text_rank_expr(e)
            object.__setattr__(r, "_rank_space", True)
            # ranks span [0, n-1]; a power-of-two dictionary must not
            # burn an extra bit of the 64-bit packing budget
            object.__setattr__(r, "_rank_bits",
                               max((n - 1).bit_length(), 1))
            return r
        return e

    def _text_rank_expr(self, ae: E.Expr) -> E.Expr:
        """min/max over TEXT: first-seen dictionary codes do not order
        lexicographically, so re-code into rank space — a LUT onto the
        sorted dictionary, whose output dict_ref is the sorted values
        (ranks decode directly). Fixes min/max returning arbitrary
        first-seen strings."""
        d = _dict_ref_of(ae)
        if d is None:
            raise SqlError(
                "min/max over text requires a dictionary-backed column")
        dic = self.store.dictionary(*d)
        order = np.argsort(np.asarray(dic.values, dtype=object))
        rank = np.empty(len(order), dtype=np.int32)
        rank[order] = np.arange(len(order), dtype=np.int32)
        ref = self.store.derived_dictionary([dic.values[i] for i in order])
        lut = np.concatenate([rank, [np.int32(-1)]]).astype(np.int32)
        e = E.Lut(ae, self._const(lut), type=T.TEXT)
        object.__setattr__(e, "_dict_ref", ref)
        return e

    def _no_rawchain(self, e: E.Expr, what: str) -> E.Expr:
        # chain carriers are RawChain nodes OR ColRefs whose subquery
        # projection attached a chain (the surrogate decodes only at
        # finalize, so any value-consuming context would see garbage)
        if isinstance(e, E.RawChain) or _raw_chain_of(e) is not None:
            raise SqlError(
                f"string functions of raw-encoded text cannot be used in "
                f"{what} (supported: WHERE comparisons, output columns)")
        return e

    def _no_raw(self, e: E.Expr, what: str) -> E.Expr:
        if _raw_ref_of(e) is not None:
            raise SqlError(
                f"raw-encoded text cannot be used as a {what} (re-create "
                "the column as dictionary-encoded)")
        return e

    def _bind_order_expr(self, ast, proj_cols, scope):
        if isinstance(ast, A.Num) and re.fullmatch(r"\d+", ast.text):
            idx = int(ast.text) - 1
            if not 0 <= idx < len(proj_cols):
                raise SqlError(f"ORDER BY position {idx+1} out of range")
            c = proj_cols[idx]
            return _colref(c)
        if isinstance(ast, A.Name):
            # match output alias; qualified names fall back to the bare
            # column name (the projection renamed it on the way out)
            for c in proj_cols:
                if c.name == ast.parts[-1]:
                    return _colref(c)
        # expression over output columns
        s = Scope()
        s.add("", {c.name: c for c in proj_cols})
        try:
            return self._expr(ast, s)
        except SqlError:
            raise SqlError("ORDER BY must reference output columns")

    # ------------------------------------------------------------------
    # expression binding
    # ------------------------------------------------------------------
    def _predicate(self, ast, scope) -> E.Expr:
        e = self._expr(ast, scope)
        if e.type.kind is not T.Kind.BOOL:
            raise SqlError("predicate must be boolean")
        return e

    def _rewritten_expr(self, ast, rewrites, scope, allow_plain=False) -> E.Expr:
        if rewrites:
            hit = rewrites.get(id(ast)) or rewrites.get(_ast_key(ast))
            if hit is not None:
                return _colref(hit)
            if isinstance(ast, A.FuncCall) and ast.over is None:
                if ast.name in ("count", "sum", "avg", "min", "max"):
                    raise SqlError("unmatched aggregate")  # should be in rewrites
                # scalar function OVER aggregates: round(sum(x), 2)
                args = [self._rewritten_expr(a, rewrites, scope, allow_plain)
                        for a in ast.args]
                special = self._bind_device_scalar(ast.name, args)
                if special is not None:
                    return special
                from greengage_tpu.utils import strfuncs

                if ast.name in strfuncs.SPECS and ast.name != "concat":
                    return self._bind_string_func(ast.name, args)
                return self._typed_scalar_func(ast.name, len(ast.args), args)
            if isinstance(ast, A.Name):
                if allow_plain:
                    return self._expr(ast, scope)
                raise SqlError(
                    f'column "{".".join(ast.parts)}" must appear in GROUP BY')
            if isinstance(ast, (A.Num, A.Str, A.Null, A.Bool, A.DateLit,
                                A.ParamRef)):
                return self._expr(ast, scope)
            if isinstance(ast, A.ExtractExpr):
                # the standard EXTRACT(field FROM expr) spelling over
                # aggregate/group-key references
                return self._bind_extract(
                    ast.field,
                    self._rewritten_expr(ast.arg, rewrites, scope,
                                         allow_plain))
            clone = _ast_rebind(ast, lambda ch: self._rewritten_expr(
                ch, rewrites, scope, allow_plain))
            if clone is not None:
                return clone
            return self._expr(ast, scope)
        return self._expr(ast, scope)

    def _rewritten_predicate(self, ast, rewrites, scope) -> E.Expr:
        e = self._rewritten_expr(ast, rewrites, scope)
        if e.type.kind is not T.Kind.BOOL:
            raise SqlError("HAVING must be boolean")
        return e

    def _expr(self, ast, scope) -> E.Expr:
        if isinstance(ast, A.Name):
            c = scope.resolve(ast.parts)
            return _colref(c)
        if isinstance(ast, A.Num):
            if "." in ast.text:
                frac = len(ast.text.split(".")[1])
                return E.Literal(T.decimal_to_int(ast.text, frac), T.decimal(frac))
            v = int(ast.text)
            return E.Literal(v, T.literal_type(v))
        if isinstance(ast, A.ParamRef):
            # hoisted literal (sql/paramize.py): typed slot read from the
            # statement's parameter vector at execution; the hoisted value
            # rides along for ESTIMATION only (planner/cost.py) — the
            # generic plan is seeded by the statement that populated it
            p = E.Param(ast.idx, ast.ptype)
            if ast.est_value is not None:
                object.__setattr__(p, "_est_value", ast.est_value)
            return p
        if isinstance(ast, A.Str):
            return E.Literal(ast.value, T.TEXT)  # coerced by context
        if isinstance(ast, A.Null):
            return E.Literal(None, T.INT32)
        if isinstance(ast, A.TypedNullOf):
            if getattr(ast, "rtype", None) is None:
                raise SqlError("internal: TypedNullOf reached binding "
                               "without pre-resolution")
            lit = E.Literal(None, ast.rtype)
            if ast.rdict is not None:
                object.__setattr__(lit, "_dict_ref", ast.rdict)
            return lit
        if isinstance(ast, A.Bool):
            return E.Literal(ast.value, T.BOOL)
        if isinstance(ast, A.DateLit):
            return E.Literal(T.date_to_days(ast.value), T.DATE)
        if isinstance(ast, A.IntervalLit):
            raise SqlError("interval is only supported in date +/- interval")
        if isinstance(ast, A.ScalarSubquery):
            if self.subquery_executor is None:
                raise SqlError("scalar subqueries are not available here")
            value, t = self.subquery_executor(ast.query)
            return E.Literal(value, t)
        if isinstance(ast, A.ExistsExpr) or isinstance(ast, A.InSubquery):
            raise SqlError(
                "IN/EXISTS subqueries are only supported as top-level WHERE "
                "conjuncts")
        if isinstance(ast, A.Unary):
            if ast.op == "not":
                return E.Not(self._predicate(ast.arg, scope))
            a = self._expr(ast.arg, scope)
            if isinstance(a, E.Literal) and a.value is not None:
                return E.Literal(-a.value, a.type)
            return E.BinOp("-", E.Literal(0, a.type), a, a.type)
        if isinstance(ast, A.Bin):
            if ast.op == "||":
                return self._bind_concat(ast, scope)
            if ast.op in ("and", "or"):
                return E.BoolOp(ast.op, (self._predicate(ast.left, scope),
                                         self._predicate(ast.right, scope)))
            if ast.op in ("=", "<>", "<", "<=", ">", ">="):
                return self._bind_cmp(ast, scope)
            return self._bind_arith(ast, scope)
        if isinstance(ast, A.IsNullTest):
            return E.IsNull(self._expr(ast.arg, scope), ast.negate)
        if isinstance(ast, A.Between):
            arg = ast.arg
            lo = A.Bin(">=", arg, ast.lo)
            hi = A.Bin("<=", arg, ast.hi)
            e = E.BoolOp("and", (self._bind_cmp(lo, scope), self._bind_cmp(hi, scope)))
            return E.Not(e) if ast.negate else e
        if isinstance(ast, A.InExpr):
            arg = self._expr(ast.arg, scope)
            if _raw_ref_of(arg) is not None:
                vals = []
                for v in ast.values:
                    lit = self._expr(v, scope)
                    if not isinstance(lit, E.Literal):
                        raise SqlError("IN list must be literals")
                    vals.append(lit.value)
                if isinstance(arg, E.RawChain):
                    e = None
                    if vals and all(isinstance(v, str) for v in vals):
                        devs = []
                        for v in vals:
                            d0 = self._raw_strop(
                                arg, arg.chain, "cmp",
                                literal=v.encode("utf-8"))
                            if d0 is None:
                                devs = None
                                break
                            devs.append(d0)
                        if devs:
                            e = (devs[0] if len(devs) == 1
                                 else E.BoolOp("or", tuple(devs)))
                    if e is None:
                        e = self._host_pred(arg, {
                            "op": "chain",
                            "chain": [list(s) for s in arg.chain],
                            "cmp": "in", "value": vals})
                else:
                    e = None
                    if vals and all(self._device_raw_eq_ok(arg, v)
                                    for v in vals):
                        devs = [self._device_raw_pred(arg, "eq", v)
                                for v in vals]
                        # eq_ok pre-screens every value so no aux column
                        # stages for a list the host path ends up serving;
                        # the None check guards against the two predicates
                        # ever drifting apart
                        if all(d is not None for d in devs):
                            e = (devs[0] if len(devs) == 1
                                 else E.BoolOp("or", tuple(devs)))
                    if e is None:
                        e = self._host_pred(arg, {"op": "in", "values": vals})
                return E.Not(e) if ast.negate else e
            d = _dict_ref_of(arg) if arg.type.kind is T.Kind.TEXT else None
            dictionary = self.store.dictionary(*d) if d else None
            vals = []
            for v in ast.values:
                lit = self._expr(v, scope)
                if not isinstance(lit, E.Literal):
                    raise SqlError("IN list must be literals")
                if dictionary is not None:
                    vals.append(dictionary.lookup(lit.value))  # -1 = matches nothing
                else:
                    vals.append(self._coerce_literal(lit, arg.type).value)
            e = E.InList(arg, tuple(vals))
            return E.Not(e) if ast.negate else e
        if isinstance(ast, A.LikeExpr):
            arg = self._expr(ast.arg, scope)
            if arg.type.kind is not T.Kind.TEXT:
                raise SqlError("LIKE requires a text column")
            if isinstance(arg, E.RawChain):
                p = ast.pattern
                e = None
                if "_" not in p and "\\" not in p:
                    # chain + %-pattern: byte-op the chain's view, then
                    # RawLike's greedy matching inside it — all on device
                    e = self._raw_strop(
                        arg, arg.chain, "like",
                        parts=tuple(s.encode("utf-8")
                                    for s in p.split("%") if s),
                        anchored_start=not p.startswith("%"),
                        anchored_end=not p.endswith("%"))
                if e is None:
                    e = self._host_pred(arg, {
                        "op": "chain", "chain": [list(s) for s in arg.chain],
                        "cmp": "like", "value": ast.pattern})
                return E.Not(e) if ast.negate else e
            if _raw_ref_of(arg) is not None:
                p = ast.pattern
                e = None
                if (p.endswith("%") and "%" not in p[:-1] and "_" not in p
                        and "\\" not in p):
                    # pure prefix pattern: device integer compares
                    e = self._device_raw_pred(arg, "prefix", p[:-1])
                elif "%" not in p and "_" not in p and "\\" not in p:
                    # no wildcards at all: LIKE == equality
                    e = self._device_raw_pred(arg, "eq", p)
                if e is None and "_" not in p and "\\" not in p:
                    # general %-pattern (contains/suffix/multi-part):
                    # byte-matrix matching over the staged wide window
                    e = self._device_raw_like(arg, p)
                if e is None:
                    e = self._host_pred(arg,
                                        {"op": "like", "pattern": ast.pattern})
                return E.Not(e) if ast.negate else e
            d = _dict_ref_of(arg)
            if d is None:
                raise SqlError("LIKE requires a dictionary-backed column")
            dictionary = self.store.dictionary(*d)
            rx = _like_to_regex(ast.pattern)
            lut = np.array([bool(rx.fullmatch(v)) for v in dictionary.values] + [False])
            e = E.Lut(arg, self._const(lut), type=T.BOOL)
            return E.Not(e) if ast.negate else e
        if isinstance(ast, A.CaseExpr):
            whens = []
            vals = []
            for c, v in ast.whens:
                whens.append(self._predicate(c, scope))
                vals.append(self._no_rawchain(self._expr(v, scope),
                                              "CASE branches"))
            else_e = self._no_rawchain(self._expr(ast.else_, scope),
                                       "CASE branches") \
                if ast.else_ is not None else None
            out_t = vals[0].type
            for v in vals[1:]:
                out_t = T.promote(out_t, v.type)
            if else_e is not None and else_e.type != out_t:
                out_t = T.promote(out_t, else_e.type)
            return E.Case(tuple(zip(whens, vals)), else_e, out_t)
        if isinstance(ast, A.CastExpr):
            a = self._no_rawchain(self._expr(ast.arg, scope), "CAST")
            target = type_from_name(ast.type_name, ast.typmod)
            if isinstance(a, E.Literal):
                return self._coerce_literal(a, target)
            return E.Cast(a, target)
        if isinstance(ast, A.ExtractExpr):
            return self._bind_extract(ast.field, self._expr(ast.arg, scope))
        if isinstance(ast, A.FuncCall):
            if ast.name in ("count", "sum", "avg", "min", "max"):
                raise SqlError(f"aggregate {ast.name}() not allowed here")
            from greengage_tpu.utils import strfuncs

            special = self._bind_device_scalar(
                ast.name, [self._expr(a, scope) for a in ast.args])
            if special is not None:
                return special
            if ast.name in strfuncs.SPECS and ast.name != "concat":
                return self._bind_string_func(
                    ast.name, [self._expr(a, scope) for a in ast.args])
            return self._bind_scalar_func(ast, scope)
        raise SqlError(f"cannot bind {type(ast).__name__}")

    # ---- device scalar library (ops/scalar.py) -------------------------
    def _bind_extract(self, field: str, a: E.Expr) -> E.Expr:
        from greengage_tpu.ops import scalar as scalar_ops

        f = field.lower()
        if f not in scalar_ops.extract_fields():
            raise SqlError(f"extract({f}) unsupported")
        if a.type.kind is not T.Kind.DATE:
            raise SqlError("extract() requires a date")
        rt = scalar_ops.FIELD_RESULT[f]
        if isinstance(a, E.Literal):
            # constant-fold via the same civil algebra (1-row host eval)
            if a.value is None:
                return E.Literal(None, rt)
            return E.Literal(self._fold_func(E.Func(f"extract_{f}", (a,), rt)),
                             rt)
        self._count_scalar(device=True)
        return E.Func(f"extract_{f}", (a,), rt)

    def _fold_func(self, e: E.Func):
        """Evaluate a device scalar Func over literal args on the host (a
        1-row trace through the same registry implementation — bind-time
        constant folding that can never drift from device semantics)."""
        import jax.numpy as jnp
        import numpy as np_

        from greengage_tpu.ops import scalar as scalar_ops

        args = [(jnp.asarray([a.value], dtype=a.type.np_dtype), None)
                for a in e.args]
        v, _valid = scalar_ops.lookup(e.name).apply(e, args, 1)
        return np_.asarray(v)[0].item()

    def _bind_device_scalar(self, name: str, args: list) -> E.Expr | None:
        """Lower the non-strfuncs device scalar forms (ops/scalar.py):
        date_trunc/date_part, coalesce/nullif/greatest/least, and the
        DECIMAL-exact round/trunc/mod. -> None when ``name`` isn't one of
        them (caller falls through to strfuncs / the extension registry)."""
        name = name.lower()
        if name == "date_trunc":
            from greengage_tpu.ops import scalar as scalar_ops

            if len(args) != 2:
                raise SqlError("date_trunc() takes (field, date)")
            f = self._req_text_lit(args[0], "date_trunc() field").lower()
            if f not in scalar_ops.trunc_fields():
                raise SqlError(f"date_trunc({f!r}) unsupported")
            d = args[1]
            if d.type.kind is not T.Kind.DATE:
                raise SqlError("date_trunc() requires a date")
            e = E.Func("date_trunc", (d,), T.DATE, params=(f,))
            if isinstance(d, E.Literal):
                return (E.Literal(None, T.DATE) if d.value is None
                        else E.Literal(self._fold_func(e), T.DATE))
            self._count_scalar(device=True)
            return e
        if name == "date_part":
            if len(args) != 2:
                raise SqlError("date_part() takes (field, date)")
            return self._bind_extract(
                self._req_text_lit(args[0], "date_part() field"), args[1])
        if name == "coalesce":
            if not args:
                raise SqlError("coalesce() requires arguments")
            args = self._common_type(args, "coalesce")
            if len(args) == 1:
                return args[0]
            e = E.Func("coalesce", tuple(args), args[0].type)
            d = _dict_ref_of(args[0])
            if d is not None:
                object.__setattr__(e, "_dict_ref", d)
            self._count_scalar(device=True)
            return e
        if name == "nullif":
            if len(args) != 2:
                raise SqlError("nullif() takes two arguments")
            le, re_ = args
            # TEXT vs a literal ABSENT from the dictionary: equality can
            # never hold, so nullif folds to its first argument (coercing
            # through _coerce_pair would leave the -1 sentinel code,
            # which decodes to NULL — a silently wrong value)
            if isinstance(le, E.Literal) and isinstance(re_, E.Literal) \
                    and le.type.kind is T.Kind.TEXT \
                    and re_.type.kind is T.Kind.TEXT:
                if le.value is None or le.value == re_.value:
                    return E.Literal(None, T.TEXT)
                return self._text_literal_to_dict(le)
            for a, b in ((le, re_), (re_, le)):
                if isinstance(b, E.Literal) and isinstance(b.value, str) \
                        and b.type.kind is T.Kind.TEXT \
                        and _dict_ref_of(a) is not None \
                        and self.store.dictionary(
                            *_dict_ref_of(a)).lookup(b.value) < 0:
                    return self._text_literal_to_dict(le) \
                        if isinstance(le, E.Literal) else le
            le, re_ = self._coerce_pair(le, re_)
            e = E.Func("nullif", (le, re_), le.type)
            # a coerced first-argument literal carries codes in the OTHER
            # side's dictionary space — decode through that
            d = _dict_ref_of(le) or _dict_ref_of(re_)
            if d is not None and le.type.kind is T.Kind.TEXT:
                object.__setattr__(e, "_dict_ref", d)
            self._count_scalar(device=True)
            return e
        if name in ("greatest", "least"):
            if len(args) < 2:
                raise SqlError(f"{name}() requires at least two arguments")
            args = self._common_type(args, name)
            if args[0].type.kind is T.Kind.TEXT:
                raise SqlError(f"{name}() over text is not supported")
            self._count_scalar(device=True)
            return E.Func(name, tuple(args), args[0].type)
        if name in ("round", "trunc") and args \
                and args[0].type.kind is T.Kind.DECIMAL:
            if len(args) > 2:
                raise SqlError(f"{name}() takes at most two arguments")
            digits = 0
            if len(args) == 2:
                lit = args[1]
                if not isinstance(lit, E.Literal) or lit.type.kind not in (
                        T.Kind.INT32, T.Kind.INT64):
                    raise SqlError(
                        f"{name}() digits must be an integer literal")
                digits = int(lit.value)
            s = args[0].type.scale
            rt = T.decimal(max(digits, 0))
            self._count_scalar(device=True)
            return E.Func(f"{name}_dec", (args[0],), rt, params=(s, digits))
        if name == "mod" and len(args) == 2 and any(
                a.type.kind is T.Kind.DECIMAL for a in args):
            for a in args:
                if a.type.kind is T.Kind.DECIMAL:
                    continue
                if not a.type.is_integer:
                    raise SqlError("mod() over decimals takes numeric args")
            ls = args[0].type.scale if args[0].type.kind is T.Kind.DECIMAL else 0
            rs = args[1].type.scale if args[1].type.kind is T.Kind.DECIMAL else 0
            out = max(ls, rs)
            self._count_scalar(device=True)
            return E.Func("mod_dec", tuple(args), T.decimal(out),
                          params=(ls, rs, out))
        return None

    @staticmethod
    def _req_text_lit(e: E.Expr, what: str) -> str:
        if not (isinstance(e, E.Literal) and isinstance(e.value, str)):
            raise SqlError(f"{what} must be a string literal")
        return e.value

    def _common_type(self, args: list, fname: str) -> list:
        """Coerce a variadic argument list to one common type (coalesce /
        greatest / least): promote across numerics/dates, pin TEXT
        literals to the first dictionary-bearing argument's code space."""
        t = args[0].type
        for a in args[1:]:
            if a.type.kind is T.Kind.TEXT and t.kind is T.Kind.TEXT:
                continue
            t = T.promote(t, a.type)
        if t.kind is T.Kind.TEXT:
            args = [self._raw_to_codes(a) or a
                    if _raw_ref_of(a) is not None else a for a in args]
            d = next((x for x in (_dict_ref_of(a) for a in args)
                      if x is not None), None)
            if d is None:
                raise SqlError(
                    f"{fname}() over text requires a "
                    "dictionary-backed column argument")
            for a in args:
                if not isinstance(a, E.Literal) and _dict_ref_of(a) != d:
                    raise SqlError(
                        f"{fname}() over text columns from different "
                        "dictionaries is not supported")
            dic = self.store.dictionary(*d)
            lits = [a.value for a in args
                    if isinstance(a, E.Literal) and isinstance(a.value, str)]
            missing = [v for v in dict.fromkeys(lits) if dic.lookup(v) < 0]
            if missing:
                # a fallback literal ABSENT from the column's dictionary:
                # its -1 sentinel code would decode back to NULL — the
                # exact value coalesce exists to supply. Re-code every
                # argument into a derived dictionary that contains it.
                ref = self.store.derived_dictionary(
                    list(dic.values) + missing)
                dd = self.store.dictionary(*ref)
                trans = np.array([dd.lookup(v) for v in dic.values] + [-1],
                                 dtype=np.int32)
                tid = self._const(trans)
                d, dic = ref, dd
                out = []
                for a in args:
                    if isinstance(a, E.Literal):
                        out.append(a)
                    else:
                        lut = E.Lut(a, tid, type=T.TEXT)
                        object.__setattr__(lut, "_dict_ref", ref)
                        out.append(lut)
                args = out
            out = []
            for a in args:
                if isinstance(a, E.Literal) and a.value is not None \
                        and isinstance(a.value, str):
                    a = E.Literal(dic.lookup(a.value), T.TEXT)
                out.append(a)
            for a in out:
                object.__setattr__(a, "_dict_ref", d)
            return out
        out = []
        for a in args:
            if isinstance(a, E.Literal):
                out.append(self._coerce_literal(a, t))
            elif a.type != t:
                out.append(E.Cast(a, t))
            else:
                out.append(a)
        return out

    def _count_scalar(self, device: bool) -> None:
        from greengage_tpu.runtime.logger import counters

        if device:
            counters.inc("scalar_device_total")
        else:
            counters.inc("scalar_host_fallback_total")

    # ---- string functions ---------------------------------------------
    def _bind_string_func(self, name: str, args: list) -> E.Expr:
        """Lower a SQL string function; strategy depends on the subject's
        encoding — see utils/strfuncs.py. Extra arguments must be literals
        (the per-distinct-value/host-chain strategies evaluate them once)."""
        from greengage_tpu.utils import strfuncs

        lo, hi, kind = strfuncs.SPECS[name]
        if len(args) < lo or (hi is not None and len(args) > hi):
            raise SqlError(f"wrong number of arguments for {name}()")
        subject, extras = args[0], args[1:]
        lits = []
        for a in extras:
            if not isinstance(a, E.Literal):
                raise SqlError(
                    f"{name}(): arguments after the string must be literals")
            lits.append(a.value)
        if subject.type.kind is not T.Kind.TEXT:
            raise SqlError(f"{name}() requires a text argument")
        if name in ("substring", "substr") and len(lits) == 2 \
                and isinstance(lits[1], (int, float)) and lits[1] < 0:
            raise SqlError("negative substring length not allowed")
        return self._lower_str_step(subject, (name, *lits), kind)

    def _bind_concat(self, ast: A.Bin, scope) -> E.Expr:
        """x || y (textcat): flatten the chain; at most one non-literal
        part, folded into a ("concat", prefix, suffix) step around it."""
        parts: list[E.Expr] = []

        def flat(n):
            if isinstance(n, A.Bin) and n.op == "||":
                flat(n.left)
                flat(n.right)
            else:
                parts.append(self._expr(n, scope))

        flat(ast)
        rendered: list[str | None] = []
        subject_i = None
        for i, p in enumerate(parts):
            if isinstance(p, E.Literal):
                rendered.append(None if p.value is None
                                else _render_text(p))
            else:
                if subject_i is not None:
                    raise SqlError(
                        "|| supports at most one column operand (combine "
                        "literals around a single column)")
                subject_i = i
                rendered.append(None)
        if any(r is None and (subject_i != i)
               for i, r in enumerate(rendered)):
            # a NULL literal operand: || propagates NULL (textcat semantics)
            return E.Literal(None, T.TEXT)
        if subject_i is None:
            return E.Literal("".join(rendered), T.TEXT)
        subject = parts[subject_i]
        if subject.type.kind is not T.Kind.TEXT:
            raise SqlError("|| column operand must be text (use cast)")
        prefix = "".join(rendered[:subject_i])
        suffix = "".join(rendered[subject_i + 1:])
        if not prefix and not suffix:
            return subject
        return self._lower_str_step(subject, ("concat", prefix, suffix), "str")

    def _lower_str_step(self, subject: E.Expr, step: tuple, kind: str) -> E.Expr:
        """Apply one string-function step to a bound TEXT expression."""
        from greengage_tpu.utils import strfuncs

        if isinstance(subject, E.Literal):
            if subject.value is None:
                return E.Literal(None, T.TEXT if kind == "str" else T.INT32)
            try:
                v = strfuncs.apply(step[0], subject.value, *step[1:])
            except (ValueError, TypeError) as ex:
                raise SqlError(f"{step[0]}(): {ex}")
            return (E.Literal(v, T.TEXT) if kind == "str"
                    else E.Literal(int(v), T.INT32))
        if isinstance(subject, E.RawChain) or _raw_ref_of(subject) is not None:
            base = subject.arg if isinstance(subject, E.RawChain) else subject
            prev = _raw_chain_of(subject) or ()
            if kind == "int":
                # length(chain) over raw TEXT: the byte-window view's
                # length is a plain device int32 — usable in projections,
                # predicates, and aggregates with no host decode
                dev = self._raw_strop(subject, prev + (tuple(step),),
                                      "length")
                if dev is not None:
                    return dev
            t = T.TEXT if kind == "str" else T.INT32
            rc = E.RawChain(base, prev + (tuple(step),), t)
            object.__setattr__(rc, "_raw_ref", _raw_ref_of(subject))
            return rc
        d = _dict_ref_of(subject)
        if d is None:
            raise SqlError(
                f"{step[0]}() requires a text column or string literal")
        dic = self.store.dictionary(*d)
        try:
            outs = [strfuncs.apply(step[0], v, *step[1:])
                    for v in dic.values]
        except (ValueError, TypeError) as ex:
            raise SqlError(f"{step[0]}(): {ex}")
        self._count_scalar(device=True)   # dict LUT rides the fused program
        if kind == "int":
            lut = np.array(list(outs) + [0], dtype=np.int32)
            return E.Lut(subject, self._const(lut), type=T.INT32)
        dedup = list(dict.fromkeys(outs))
        ref = self.store.derived_dictionary(dedup)
        dd = self.store.dictionary(*ref)
        lut = np.array([dd.lookup(o) for o in outs] + [-1], dtype=np.int32)
        e = E.Lut(subject, self._const(lut), type=T.TEXT)
        object.__setattr__(e, "_dict_ref", ref)
        return e

    def _bind_scalar_func(self, ast: A.FuncCall, scope) -> E.Expr:
        """Resolve against the extension registry (pg_proc analog,
        reference: src/backend/parser/parse_func.c func_get_detail);
        overload resolution is by arity, coercion by declared signature."""
        return self._typed_scalar_func(
            ast.name, len(ast.args),
            [self._expr(a, scope) for a in ast.args])

    def _typed_scalar_func(self, name: str, nargs: int,
                           bound: list) -> E.Expr:
        from greengage_tpu import extensions as X

        spec = X.lookup(name, nargs)
        if spec is not None and spec.extension and \
                spec.extension not in getattr(self.catalog, "extensions", ()):
            # visibility follows THIS database's catalog, not process
            # import history (pg_proc is per-database)
            raise SqlError(f"unknown function {name}")
        if spec is None:
            ar = X.arities(name)
            if ar:
                raise SqlError(
                    f"function {name} takes "
                    f"{' or '.join(map(str, ar))} argument(s), got {nargs}")
            raise SqlError(f"unknown function {name}")
        args = [self._coerce_func_arg(a, want, name)
                for a, want in zip(bound, spec.arg_types)]
        rt = args[0].type if spec.result_type == "first" else spec.result_type
        return E.Func(spec.name, tuple(args), rt)

    @staticmethod
    def _coerce_func_arg(a: E.Expr, want: str, fname: str) -> E.Expr:
        k = a.type.kind
        num = (T.Kind.INT32, T.Kind.INT64, T.Kind.FLOAT64, T.Kind.DECIMAL)
        if want == "any":
            return a
        if want == "float64":
            if k is T.Kind.FLOAT64:
                return a
            if k in num:
                return E.Cast(a, T.FLOAT64)
        elif want == "int64":
            if k is T.Kind.INT64:
                return a
            if k is T.Kind.INT32:
                return E.Cast(a, T.INT64)
        elif want == "numeric":
            if k in num:
                return a
        elif want == "bool" and k is T.Kind.BOOL:
            return a
        elif want == "date" and k is T.Kind.DATE:
            return a
        raise SqlError(f"function {fname} expects {want}, got {a.type}")

    # ---- raw-text host predicates --------------------------------------
    def _raw_aux_col(self, scan, name: str, sqltype, dict_ref=None) -> E.Expr:
        """Reuse-or-append a virtual staged column on a scan (the shared
        mechanics of host predicates, device raw-prefix columns, and
        transient raw-dictionary codes)."""
        for c in scan.cols:
            if c.name == name:
                return _colref(c)
        ci = ColInfo(self.new_id("rp"), sqltype, name, dict_ref=dict_ref)
        scan.cols.append(ci)
        self._scan_for[ci.id] = scan
        return _colref(ci)

    def _device_raw_eq_ok(self, arg: E.Expr, value) -> bool:
        """Pure feasibility check for _device_raw_pred's eq lowering —
        callers with SEVERAL values (IN lists) must check them ALL before
        staging any aux column, or a partially-lowerable list leaves
        orphan prefix columns that disable zone-map pruning for nothing."""
        if isinstance(arg, E.RawChain) or not isinstance(arg, E.ColRef):
            return False
        if value is None or not isinstance(value, str):
            return False
        if _raw_ref_of(arg) is None or arg.name not in self._scan_for:
            return False
        from greengage_tpu.storage.table_store import RAW_PREFIX_BYTES

        return len(value.encode("utf-8")) <= RAW_PREFIX_BYTES

    def _device_raw_pred(self, arg: E.Expr, kind: str, value) -> E.Expr | None:
        """DEVICE lowering for raw-TEXT predicates (VERDICT r3 #7): the
        scan stages the column's packed 32-byte prefix (int64 lanes) and
        exact length, and equality / LIKE-'prefix%' compile to integer
        compares — one vectorized pass on the mesh instead of O(heap)
        host python per statement. None -> caller falls back to the host
        path (chains, long literals, general patterns).

        Soundness: utf-8 packing is big-endian per word with zero padding,
        so equal strings <=> equal (length, words); a literal longer than
        the prefix cap can never fully compare on device. LIKE prefixes
        mask the straddling word. Reference role: the varlena texteq /
        text_like fast paths (varlena.c), vectorized."""
        if isinstance(arg, E.RawChain) or not isinstance(arg, E.ColRef):
            return None
        if value is None or not isinstance(value, str):
            return None
        rr = _raw_ref_of(arg)
        if rr is None or arg.name not in self._scan_for:
            return None
        from greengage_tpu.storage.table_store import (RAW_PREFIX_BYTES,
                                                       RAW_PREFIX_WORDS)

        bts = value.encode("utf-8")
        if len(bts) > RAW_PREFIX_BYTES:
            return None
        scan = self._scan_for[arg.name]
        col = rr[1]
        rl = self._raw_aux_col(scan, f"@rl:{col}", T.INT32)

        def word_lit(chunk: bytes) -> int:
            return int.from_bytes(chunk.ljust(8, b"\0"), "big", signed=True)

        conj: list = []
        if kind == "eq":
            conj.append(E.Cmp("=", rl, E.Literal(len(bts), T.INT32), T.BOOL))
            # rows passing the exact-length check have zero padding beyond
            # their bytes, identical to the literal's padding — compare
            # every word the literal touches (others are zero on both
            # sides only up to the row's length... which equals the
            # literal's, so untouched words are zero for both)
            for w in range(RAW_PREFIX_WORDS):
                lit = word_lit(bts[w * 8:(w + 1) * 8])
                if w * 8 >= len(bts) and lit == 0:
                    break   # all remaining words are zero on both sides
                wcol = self._raw_aux_col(scan, f"@rp:{col}:{w}", T.INT64)
                conj.append(E.Cmp("=", wcol, E.Literal(lit, T.INT64), T.BOOL))
        elif kind == "prefix":
            conj.append(E.Cmp(">=", rl, E.Literal(len(bts), T.INT32), T.BOOL))
            full, rem = divmod(len(bts), 8)
            for w in range(full):
                wcol = self._raw_aux_col(scan, f"@rp:{col}:{w}", T.INT64)
                conj.append(E.Cmp(
                    "=", wcol, E.Literal(word_lit(bts[w * 8:(w + 1) * 8]),
                                         T.INT64), T.BOOL))
            if rem:
                mask = int.from_bytes(
                    (b"\xff" * rem).ljust(8, b"\0"), "big", signed=True)
                wcol = self._raw_aux_col(scan, f"@rp:{col}:{full}", T.INT64)
                masked = E.BinOp("&", wcol, E.Literal(mask, T.INT64), T.INT64)
                conj.append(E.Cmp(
                    "=", masked, E.Literal(word_lit(bts[full * 8:]),
                                           T.INT64), T.BOOL))
            if not conj:
                return None
        else:
            return None
        return conj[0] if len(conj) == 1 else E.BoolOp("and", tuple(conj))

    def _device_raw_like(self, arg: E.Expr, pattern: str) -> E.Expr | None:
        """GENERAL device LIKE for raw TEXT (VERDICT r4 #7): any pattern
        of literal parts separated by % lowers to byte-matrix matching
        over the staged RAW_WIDE_BYTES window (E.RawLike). Sound only
        when EVERY committed row fits the window — a longer row could
        match past it — so the column's exact max length gates the
        lowering; None falls back to the host path."""
        if isinstance(arg, E.RawChain) or not isinstance(arg, E.ColRef):
            return None
        rr = _raw_ref_of(arg)
        if rr is None or arg.name not in self._scan_for:
            return None
        from greengage_tpu.storage.table_store import (RAW_WIDE_BYTES,
                                                       RAW_WIDE_WORDS)

        parts = [s.encode("utf-8") for s in pattern.split("%") if s]
        if any(len(b) > RAW_WIDE_BYTES for b in parts):
            return None
        table, col = rr
        max_len = self.store.raw_max_len(table, col)
        if max_len > RAW_WIDE_BYTES:
            return None
        scan = self._scan_for[arg.name]
        rl = self._raw_aux_col(scan, f"@rl:{col}", T.INT32)
        # stage only the lanes the column's rows can occupy — matches can
        # never extend past max_len (the evaluator sizes W from the lanes)
        nlanes = min(max(-(-max_len // 8), 1), RAW_WIDE_WORDS)
        words = tuple(
            self._raw_aux_col(scan, f"@rw:{col}:{w}", T.INT64)
            for w in range(nlanes))
        return E.RawLike(
            words=words, length=rl, parts=tuple(parts),
            anchored_start=not pattern.startswith("%"),
            anchored_end=not pattern.endswith("%"))

    def _raw_strop(self, arg: E.Expr, steps: tuple, out: str,
                   **kw) -> E.Expr | None:
        """DEVICE lowering for scalar string-function chains over raw TEXT
        (the byte-op half of ops/scalar.py; docs/PERF.md "Scalar data-path
        fusion"): stage the column's wide byte window (@rw lanes + @rl
        length) and evaluate the chain + terminal op as elementwise work
        inside the fused program. None -> caller falls back to the host
        chain (counted in scalar_host_fallback_total). Gates:

        * the GUC scalar_device_enabled is on;
        * every chain step is byte-window-expressible (scalar.RAW_STEPS);
        * every committed row fits the staged window (raw_max_len — a
          longer row could match/measure past it);
        * the column is pure ASCII where the chain counts characters
          (upper/lower/substr/length — bytes == characters only then)."""
        from greengage_tpu.ops import scalar as scalar_ops
        from greengage_tpu.storage.table_store import (RAW_WIDE_BYTES,
                                                       RAW_WIDE_WORDS)

        if not self.scalar_device:
            return None
        base = arg.arg if isinstance(arg, E.RawChain) else arg
        rr = _raw_ref_of(arg)
        if rr is None or not isinstance(base, E.ColRef) \
                or base.name not in self._scan_for:
            return None
        ok, needs_ascii = scalar_ops.raw_steps_ok(steps)
        if not ok:
            return None
        table, col = rr
        if self.store.raw_max_len(table, col) > RAW_WIDE_BYTES:
            return None
        if needs_ascii and not self.store.raw_is_ascii(table, col):
            return None
        scan = self._scan_for[base.name]
        rl = self._raw_aux_col(scan, f"@rl:{col}", T.INT32)
        nlanes = min(max(-(-self.store.raw_max_len(table, col) // 8), 1),
                     RAW_WIDE_WORDS)
        words = tuple(
            self._raw_aux_col(scan, f"@rw:{col}:{w}", T.INT64)
            for w in range(nlanes))
        self._count_scalar(device=True)
        return E.RawStrOp(
            words=words, length=rl, steps=tuple(tuple(s) for s in steps),
            out=out, type=T.INT32 if out == "length" else T.BOOL, **kw)

    def _host_pred(self, arg: E.Expr, payload: dict) -> E.Expr:
        """Lower a predicate over a raw TEXT column into a host-evaluated
        boolean staged with the scan (the dictionary-LUT strategy at
        O(rows) host cost, cached per manifest version)."""
        rr = _raw_ref_of(arg)
        base = arg.arg if isinstance(arg, E.RawChain) else arg
        if not isinstance(base, E.ColRef) or base.name not in self._scan_for:
            raise SqlError(
                "predicates on raw-encoded text are only supported directly "
                "on base-table columns")
        if payload.get("op") == "chain":
            # a scalar function chain the device paths couldn't express:
            # the retained per-row host fallback, counted so the fused
            # coverage claim stays measurable
            self._count_scalar(device=False)
        scan = self._scan_for[base.name]
        name = self.store.host_pred_name(rr[1], payload)
        return self._raw_aux_col(scan, name, T.BOOL)

    # ---- comparisons with literal coercion ----------------------------
    def _bind_cmp(self, ast: A.Bin, scope) -> E.Expr:
        le = self._expr(ast.left, scope)
        re_ = self._expr(ast.right, scope)
        if (isinstance(le, E.Literal) and isinstance(re_, E.Literal)
                and le.type.kind is T.Kind.TEXT
                and re_.type.kind is T.Kind.TEXT):
            if le.value is None or re_.value is None:
                return E.Literal(None, T.BOOL)

            fn = {"=": operator.eq, "<>": operator.ne, "<": operator.lt,
                  "<=": operator.le, ">": operator.gt, ">=": operator.ge}
            return E.Literal(fn[ast.op](le.value, re_.value), T.BOOL)
        # raw TEXT comparisons evaluate on host (storage carries surrogates)
        for a, b, flipped in ((le, re_, False), (re_, le, True)):
            if _raw_ref_of(a) is None:
                continue
            if isinstance(a, E.RawChain):
                if not isinstance(b, E.Literal):
                    raise SqlError(
                        "raw-text function results compare only against "
                        "literals")
                op = ast.op
                if flipped:
                    op = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}.get(op, op)
                if a.type.kind is T.Kind.TEXT:
                    if b.type.kind is not T.Kind.TEXT:
                        raise SqlError(
                            "raw-text function result compared to non-string")
                    val = b.value
                    if op in ("=", "<>") and isinstance(val, str):
                        dev = self._raw_strop(a, a.chain, "cmp",
                                              literal=val.encode("utf-8"))
                        if dev is not None:
                            return E.Not(dev) if op == "<>" else dev
                else:
                    if not isinstance(b.value, (int, float)):
                        raise SqlError(
                            "numeric string function compared to non-number")
                    val = b.value
                    if b.type.kind is T.Kind.DECIMAL:
                        # literals carry the scaled-int representation
                        val = b.value / 10 ** b.type.scale
                return self._host_pred(a, {
                    "op": "chain", "chain": [list(s) for s in a.chain],
                    "cmp": op, "value": val})
            if not (isinstance(b, E.Literal) and b.type.kind is T.Kind.TEXT
                    and ast.op in ("=", "<>")):
                raise SqlError(
                    "raw-encoded text supports only =/<> against string "
                    "literals, LIKE, and IN")
            e = self._device_raw_pred(a, "eq", b.value)
            if e is None:
                e = self._host_pred(a, {"op": "eq", "value": b.value})
            return E.Not(e) if ast.op == "<>" else e
        le, re_ = self._coerce_pair(le, re_)
        return E.Cmp(ast.op, le, re_)

    def _coerce_pair(self, le: E.Expr, re_: E.Expr):
        lt, rt = le.type, re_.type
        # unknown string literal adopts the other side's type
        if isinstance(re_, E.Literal) and rt.kind is T.Kind.TEXT and lt.kind is not T.Kind.TEXT:
            re_ = self._coerce_literal(re_, lt)
            rt = re_.type
        if isinstance(le, E.Literal) and lt.kind is T.Kind.TEXT and rt.kind is not T.Kind.TEXT:
            le = self._coerce_literal(le, rt)
            lt = le.type
        if lt.kind is T.Kind.TEXT and rt.kind is T.Kind.TEXT:
            # literal vs column: dictionary code; col vs col: translate dicts
            if isinstance(re_, E.Literal):
                d = _dict_ref_of(le)
                code = self.store.dictionary(*d).lookup(re_.value) if d else -1
                return le, E.Literal(code, T.TEXT)
            if isinstance(le, E.Literal):
                d = _dict_ref_of(re_)
                code = self.store.dictionary(*d).lookup(le.value) if d else -1
                return E.Literal(code, T.TEXT), re_
            ld, rd = _dict_ref_of(le), _dict_ref_of(re_)
            if ld != rd and ld is not None and rd is not None:
                left_dict = self.store.dictionary(*ld)
                right_dict = self.store.dictionary(*rd)
                lut = np.array(
                    [left_dict.lookup(v) for v in right_dict.values] + [-1],
                    dtype=np.int32)
                re_ = E.Lut(re_, self._const(lut), type=T.TEXT)
            return le, re_
        if lt == rt:
            return le, re_
        common = T.promote(lt, rt)
        if isinstance(le, E.Literal):
            le = self._coerce_literal(le, common)
        elif lt != common:
            le = E.Cast(le, common)
        if isinstance(re_, E.Literal):
            re_ = self._coerce_literal(re_, common)
        elif rt != common:
            re_ = E.Cast(re_, common)
        return le, re_

    def _coerce_literal(self, lit: E.Literal, target: T.SqlType) -> E.Literal:
        if lit.value is None:
            return E.Literal(None, target)
        if lit.type == target:
            return lit
        v = lit.value
        k = target.kind
        if lit.type.kind is T.Kind.TEXT:
            if k is T.Kind.TEXT:
                return lit
            try:
                return E.Literal(T.from_string(v, target), target)
            except ValueError as ex:
                raise SqlError(f"cannot coerce string literal to {target}: {ex}")
        if k is T.Kind.DECIMAL:
            if lit.type.kind is T.Kind.DECIMAL:
                from greengage_tpu.ops.expr_eval import _rescale_host
                return E.Literal(_rescale_host(v, lit.type.scale, target.scale), target)
            return E.Literal(int(v) * 10 ** target.scale, target)
        if k is T.Kind.FLOAT64:
            if lit.type.kind is T.Kind.DECIMAL:
                return E.Literal(v / 10 ** lit.type.scale, target)
            return E.Literal(float(v), target)
        if k in (T.Kind.INT32, T.Kind.INT64):
            return E.Literal(int(v), target)
        raise SqlError(f"cannot coerce {lit.type} literal to {target}")

    # ---- date +/- interval constant folding ---------------------------
    def _bind_arith(self, ast: A.Bin, scope) -> E.Expr:
        # date +/- interval: literal bases fold at bind time (calendar math
        # on host); column bases lower to device civil math (ops/scalar.py
        # add_months; day units are plain day arithmetic)
        if isinstance(ast.right, A.IntervalLit) and ast.op in ("+", "-"):
            base = self._expr(ast.left, scope)
            if base.type.kind is not T.Kind.DATE:
                raise SqlError("interval arithmetic requires a date")
            if isinstance(base, E.Literal):
                days = _apply_interval(base.value, ast.right, ast.op)
                return E.Literal(days, T.DATE)
            iv = ast.right
            n = int(iv.value)
            if ast.op == "-":
                n = -n
            if iv.unit.startswith("day"):
                return E.BinOp("+", base, E.Literal(n, T.INT32), T.DATE)
            if iv.unit.startswith("week"):
                return E.BinOp("+", base, E.Literal(7 * n, T.INT32), T.DATE)
            if iv.unit.startswith("month") or iv.unit.startswith("year"):
                months = n * (12 if iv.unit.startswith("year") else 1)
                self._count_scalar(device=True)
                return E.Func("add_months", (base,), T.DATE,
                              params=(months,))
            raise SqlError(f"interval unit {iv.unit} unsupported")
        le = self._expr(ast.left, scope)
        re_ = self._expr(ast.right, scope)
        self._no_rawchain(le, "arithmetic")
        self._no_rawchain(re_, "arithmetic")
        # unknown literal coercion mirrors comparison
        if isinstance(re_, E.Literal) and re_.type.kind is T.Kind.TEXT:
            re_ = self._coerce_literal(re_, le.type)
        if isinstance(le, E.Literal) and le.type.kind is T.Kind.TEXT:
            le = self._coerce_literal(le, re_.type)
        rtype = T.arith_result(ast.op, le.type, re_.type)
        return E.BinOp(ast.op, le, re_, rtype)

    def _const(self, arr: np.ndarray) -> str:
        tid = f"lut{len(self.consts)}"
        self.consts[tid] = arr
        return tid


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _expr_col_ids(e) -> set:
    """Bound column ids a predicate references (generic expr walk)."""

    out: set = set()

    def walk(x):
        if isinstance(x, E.ColRef):
            out.add(x.name)
            return
        if isinstance(x, E.Expr):
            for f in dataclasses.fields(x):
                walk(getattr(x, f.name))
        elif isinstance(x, (tuple, list)):
            for y in x:
                walk(y)

    walk(e)
    return out


def _sink_pred(plan, pred, refs: set):
    """Push a bound conjunct below join nodes whose one side covers every
    referenced column: inner/cross sink either side, outer/semi/anti only
    the probe side (a WHERE pred on a left join's nullable side must stay
    above the join to reject null-extended rows). -> (plan, sunk?)."""
    if isinstance(plan, Filter):
        child, ok = _sink_pred(plan.child, pred, refs)
        if ok:
            plan.child = child
            return plan, True
        return plan, False
    if isinstance(plan, Join):
        lids = {c.id for c in plan.left.out_cols()}
        if refs <= lids:
            child, ok = _sink_pred(plan.left, pred, refs)
            plan.left = child if ok else _merge_filter(plan.left, pred)
            return plan, True
        if plan.kind in ("inner", "cross"):
            rids = {c.id for c in plan.right.out_cols()}
            if refs <= rids:
                child, ok = _sink_pred(plan.right, pred, refs)
                plan.right = child if ok else _merge_filter(plan.right, pred)
                return plan, True
    return plan, False


def _merge_filter(node, pred):
    """AND into an existing Filter rather than stacking a second one —
    the planner's scan-level pushdown (zone maps, direct dispatch) only
    inspects the Filter DIRECTLY above a Scan."""
    if isinstance(node, Filter):
        node.predicate = E.BoolOp("and", (node.predicate, pred))
        return node
    return Filter(node, pred)


_SUBST_FAIL = object()


def _subst_refs(e: E.Expr, mapping: dict):
    """Replace ColRefs (by id) with their mapped source expressions,
    rebuilding the tree; -> None when any part can't be rebuilt (caller
    keeps the original expression and its original constraints)."""
    def walk(v):
        if isinstance(v, E.ColRef):
            hit = mapping.get(v.name)
            return hit if hit is not None else v
        if isinstance(v, E.Expr):
            if not dataclasses.is_dataclass(v):
                return _SUBST_FAIL
            changes = {}
            for fld in dataclasses.fields(v):
                old = getattr(v, fld.name)
                new = walk(old)
                if new is _SUBST_FAIL:
                    return _SUBST_FAIL
                if new is not old:
                    changes[fld.name] = new
            if not changes:
                return v
            out = dataclasses.replace(v, **changes)
            for attr in ("_dict_ref", "_raw_ref", "_raw_chain",
                         "_rank_space", "_rank_bits"):
                if hasattr(v, attr):
                    object.__setattr__(out, attr, getattr(v, attr))
            return out
        if isinstance(v, tuple):
            outs = []
            for x in v:
                nx = walk(x)
                if nx is _SUBST_FAIL:
                    return _SUBST_FAIL
                outs.append(nx)
            return (tuple(outs) if any(a is not b for a, b in zip(outs, v))
                    else v)
        return v

    res = walk(e)
    return None if res is _SUBST_FAIL else res


def _colref(c: ColInfo) -> E.ColRef:
    e = E.ColRef(c.id, c.type)
    if c.dict_ref is not None:
        object.__setattr__(e, "_dict_ref", c.dict_ref)
    if c.raw_ref is not None:
        object.__setattr__(e, "_raw_ref", c.raw_ref)
    if getattr(c, "raw_chain", None):
        object.__setattr__(e, "_raw_chain", c.raw_chain)
    return e


def _raw_chain_of(e: E.Expr):
    if isinstance(e, E.RawChain):
        return e.chain
    return getattr(e, "_raw_chain", None)


def _render_text(lit: E.Literal) -> str:
    """Literal -> its SQL text form (|| operand rendering)."""
    t, v = lit.type, lit.value
    if t.kind is T.Kind.TEXT:
        return v
    if t.kind is T.Kind.DECIMAL:
        s = t.scale
        if not s:
            return str(v)
        sign = "-" if v < 0 else ""
        a = abs(v)
        return f"{sign}{a // 10**s}.{a % 10**s:0{s}d}"
    if t.kind is T.Kind.DATE:
        return (datetime.date(1970, 1, 1)
                + datetime.timedelta(days=v)).isoformat()
    if t.kind is T.Kind.BOOL:
        return "true" if v else "false"
    return str(v)


def _zero_lit(t: T.SqlType) -> E.Literal:
    if t.kind is T.Kind.TEXT:
        return E.Literal(-1, t)      # dictionary code space: -1 = absent
    if t.kind is T.Kind.FLOAT64:
        return E.Literal(0.0, t)
    if t.kind is T.Kind.BOOL:
        return E.Literal(False, t)
    return E.Literal(0, t)


def _dict_ref_of(e: E.Expr):
    return getattr(e, "_dict_ref", None)


def _raw_ref_of(e: E.Expr):
    return getattr(e, "_raw_ref", None)


_ORDERED_SET_AGGS = ("percentile_cont", "percentile_disc", "median")


def _contains_agg(ast) -> bool:
    if isinstance(ast, A.FuncCall) and ast.over is None and \
            ast.name in ("count", "sum", "avg", "min", "max",
                         *_ORDERED_SET_AGGS):
        return True
    return any(_contains_agg(c) for c in _ast_children(ast))


def _contains_count(ast) -> bool:
    if isinstance(ast, A.FuncCall) and ast.over is None and ast.name == "count":
        return True
    return any(_contains_count(c) for c in _ast_children(ast))


def _split_correlation(conjuncts, outer_scope, sub_scope):
    """Classify a subquery's WHERE conjuncts relative to the outer scope:
    -> (inner_only, corr_pairs [(outer_ast, inner_ast)], outer_only,
        residual, bad). ``residual`` = mixed-reference conjuncts that are
    NOT plain equality correlation (e.g. l2.suppkey <> l1.suppkey): they
    evaluate per candidate pair on the semi/anti join."""
    inner_only, corr_pairs, outer_only, residual, bad = [], [], [], [], []
    for c in conjuncts:
        refs = _name_refs(c)
        # innermost scope wins (SQL scoping): anything resolvable fully
        # inside the subquery is an inner predicate
        if not refs or all(_in_scope(p, sub_scope) for p in refs):
            inner_only.append(c)
            continue
        # equality with one side inner-resolvable and the other only
        # outer-resolvable = correlation (checked before outer_only so
        # tables appearing in both scopes classify as correlation)
        if isinstance(c, A.Bin) and c.op == "=":
            lrefs, rrefs = _name_refs(c.left), _name_refs(c.right)
            l_inner = lrefs and all(_in_scope(p, sub_scope) for p in lrefs)
            r_inner = rrefs and all(_in_scope(p, sub_scope) for p in rrefs)
            l_outer = lrefs and all(_in_scope(p, outer_scope) for p in lrefs)
            r_outer = rrefs and all(_in_scope(p, outer_scope) for p in rrefs)
            if l_inner and not r_inner and r_outer:
                corr_pairs.append((c.right, c.left))
                continue
            if r_inner and not l_inner and l_outer:
                corr_pairs.append((c.left, c.right))
                continue
        if refs and all(_in_scope(p, outer_scope) for p in refs):
            outer_only.append(c)
            continue
        if all(_in_scope(p, sub_scope) or _in_scope(p, outer_scope)
               for p in refs):
            residual.append(c)
            continue
        bad.append(c)
    return inner_only, corr_pairs, outer_only, residual, bad


def _contains_window(ast) -> bool:
    if isinstance(ast, A.FuncCall) and ast.over is not None:
        return True
    return any(_contains_window(c) for c in _ast_children(ast))


def _ast_children(ast):
    for f in ("left", "right", "arg", "lo", "hi", "else_", "query"):
        v = getattr(ast, f, None)
        if isinstance(v, A.ANode):
            yield v
    for v in getattr(ast, "args", []) or []:
        yield v
    for v in getattr(ast, "values", []) or []:
        if isinstance(v, A.ANode):
            yield v
    for c, v in getattr(ast, "whens", []) or []:
        yield c
        yield v


def _ast_key(ast) -> str:
    """Structural key for GROUP BY expression matching."""
    if isinstance(ast, A.Name):
        return "n:" + ".".join(ast.parts)
    if isinstance(ast, A.Num):
        return "#" + ast.text
    if isinstance(ast, A.Str):
        return "s:" + ast.value
    # every value-bearing attribute that changes semantics must enter the
    # key — a missed one silently MERGES distinct aggregates via dup_map
    # (e.g. sum(cast(x as bigint)) vs sum(cast(x as double precision)))
    parts = [type(ast).__name__, getattr(ast, "op", ""), getattr(ast, "name", ""),
             getattr(ast, "field", ""), getattr(ast, "type_name", ""),
             str(getattr(ast, "typmod", "")),
             str(getattr(ast, "negate", "")), str(getattr(ast, "distinct", "")),
             str(getattr(ast, "star", "")), str(getattr(ast, "desc", "")),
             str(getattr(ast, "value", "")), getattr(ast, "pattern", ""),
             getattr(ast, "unit", "")]
    for c in _ast_children(ast):
        parts.append(_ast_key(c))
    return "(" + " ".join(parts) + ")"


_PLAIN_AGGS = ("count", "sum", "avg", "min", "max")


def _has_grouping_call(n) -> bool:
    if isinstance(n, A.FuncCall) and n.name == "grouping" and n.over is None:
        return True
    return any(_has_grouping_call(c) for c in _ast_children(n))


def _contains_grouping(stmt) -> bool:
    return any(_has_grouping_call(it.expr) for it in stmt.items) or (
        stmt.having is not None and _has_grouping_call(stmt.having)) or any(
        _has_grouping_call(oi.expr) for oi in stmt.order_by)


def _gs_rewrite(node, present: set, universe: set):
    """Grouping-sets branch rewrite: keys absent from this set become
    TypedNullOf, grouping(...) folds to its per-branch bitmask constant
    (PG bit order: first argument = most significant). Aggregate arguments
    are left untouched — they see real rows, not key NULLs."""
    if not isinstance(node, A.ANode):
        if isinstance(node, list):
            return [_gs_rewrite(v, present, universe) for v in node]
        if isinstance(node, tuple):
            return tuple(_gs_rewrite(v, present, universe) for v in node)
        return node
    if isinstance(node, A.SelectStmt):
        return node
    if isinstance(node, A.FuncCall) and node.over is None:
        if node.name == "grouping":
            if not node.args:
                raise SqlError("grouping() requires arguments")
            mask = 0
            n = len(node.args)
            for i, a in enumerate(node.args):
                k = _ast_key(a)
                if k not in universe:
                    raise SqlError(
                        "grouping() arguments must be grouping keys")
                if k not in present:
                    mask |= 1 << (n - 1 - i)
            return A.Num(str(mask))
        if node.name in _PLAIN_AGGS or node.name in _ORDERED_SET_AGGS:
            # aggregate args (incl. WITHIN GROUP order exprs) see real
            # rows, never key NULLs
            return node
    k = _ast_key(node)
    if k in universe:
        return node if k in present else A.TypedNullOf(node)
    for f in dataclasses.fields(node):
        setattr(node, f.name,
                _gs_rewrite(getattr(node, f.name), present, universe))
    return node


def _ast_rebind(ast, rec):
    """Rebuild scalar AST nodes whose children may contain agg/key refs."""
    def cmp(op, l, r):
        lt, rt = l.type, r.type
        if lt != rt:
            common = T.promote(lt, rt)
            if lt != common:
                l = E.Cast(l, common)
            if rt != common:
                r = E.Cast(r, common)
        return E.Cmp(op, l, r)

    if isinstance(ast, A.Between):
        # HAVING-over-aggregate ratios (TPC-DS Q21): BETWEEN desugars to
        # the two comparisons here, the same as plain-expression binding
        arg, lo, hi = rec(ast.arg), rec(ast.lo), rec(ast.hi)
        e = E.BoolOp("and", (cmp(">=", arg, lo), cmp("<=", arg, hi)))
        return E.Not(e) if ast.negate else e
    if isinstance(ast, A.Bin):
        l = rec(ast.left)
        r = rec(ast.right)
        if ast.op in ("and", "or"):
            return E.BoolOp(ast.op, (l, r))
        if ast.op in ("=", "<>", "<", "<=", ">", ">="):
            return cmp(ast.op, l, r)
        return E.BinOp(ast.op, l, r, T.arith_result(ast.op, l.type, r.type))
    if isinstance(ast, A.Unary) and ast.op == "-":
        a = rec(ast.arg)
        return E.BinOp("-", E.Literal(0, a.type), a, a.type)
    if isinstance(ast, A.IsNullTest):
        return E.IsNull(rec(ast.arg), ast.negate)
    if isinstance(ast, A.CaseExpr):
        # CASE over aggregate results (the stat-agg expansion emits these:
        # negative-residue clamps, pairwise NULL restriction)
        whens = [(rec(c), rec(v)) for c, v in ast.whens]
        else_e = rec(ast.else_) if ast.else_ is not None else None
        out_t = whens[0][1].type
        for _, v in whens[1:]:
            out_t = T.promote(out_t, v.type)
        if else_e is not None and else_e.type != out_t:
            out_t = T.promote(out_t, else_e.type)
        return E.Case(tuple(whens), else_e, out_t)
    if isinstance(ast, A.CastExpr):
        return E.Cast(rec(ast.arg), type_from_name(ast.type_name, ast.typmod))
    return None


def _name_refs(ast) -> list[tuple[str, ...]]:
    out = []
    if isinstance(ast, A.Name):
        out.append(ast.parts)
    for c in _ast_children(ast):
        out.extend(_name_refs(c))
    return out


def _split_and(ast) -> list:
    if ast is None:
        return []
    if isinstance(ast, A.Bin) and ast.op == "and":
        return _split_and(ast.left) + _split_and(ast.right)
    return [ast]


def _join_and(conjuncts: list):
    if not conjuncts:
        return None
    e = conjuncts[0]
    for c in conjuncts[1:]:
        e = A.Bin("and", e, c)
    return e


def _extract_equi(conjuncts, lscope, rscope):
    """Partition conjuncts into equi-join pairs (lhs from lscope, rhs from
    rscope) and the rest."""
    eq, rest = [], []

    def side(parts):
        inl = _in_scope(parts, lscope)
        inr = _in_scope(parts, rscope)
        if inl and not inr:
            return "l"
        if inr and not inl:
            return "r"
        return None

    for c in conjuncts:
        if isinstance(c, A.Bin) and c.op == "=":
            lrefs = _name_refs(c.left)
            rrefs = _name_refs(c.right)
            if lrefs and rrefs:
                lsides = {side(p) for p in lrefs}
                rsides = {side(p) for p in rrefs}
                if lsides == {"l"} and rsides == {"r"}:
                    eq.append((c.left, c.right))
                    continue
                if lsides == {"r"} and rsides == {"l"}:
                    eq.append((c.right, c.left))
                    continue
        rest.append(c)
    return eq, rest


def _in_scope(parts, scope) -> bool:
    try:
        scope.resolve(parts)
        return True
    except SqlError:
        return False


def _ast_name(ast) -> str:
    if isinstance(ast, A.Name):
        return ast.parts[-1]
    if isinstance(ast, A.FuncCall):
        return ast.name
    if isinstance(ast, A.ExtractExpr):
        return ast.field
    return "?column?"


def _like_to_regex(pattern: str) -> "re.Pattern":
    return T.like_to_regex(pattern)


def _apply_interval(days: int, iv: A.IntervalLit, op: str) -> int:
    n = int(iv.value)
    if op == "-":
        n = -n
    d = np.datetime64("1970-01-01", "D") + np.timedelta64(days, "D")
    if iv.unit.startswith("day"):
        d = d + np.timedelta64(n, "D")
    elif iv.unit.startswith("week"):
        d = d + np.timedelta64(7 * n, "D")
    elif iv.unit.startswith("month"):
        m = d.astype("datetime64[M]") + np.timedelta64(n, "M")
        dom = (d - d.astype("datetime64[M]")).astype(int)
        d = m + np.timedelta64(dom, "D")
    elif iv.unit.startswith("year"):
        m = d.astype("datetime64[M]") + np.timedelta64(12 * n, "M")
        dom = (d - d.astype("datetime64[M]")).astype(int)
        d = m + np.timedelta64(dom, "D")
    else:
        raise SqlError(f"interval unit {iv.unit} unsupported")
    return int((d - np.datetime64("1970-01-01", "D")).astype(int))


# --------------------------------------------------------------------------
# scan pruning (projection pushdown to storage)
# --------------------------------------------------------------------------

def _collect_needed(plan: Plan, needed: set):
    from greengage_tpu.planner.logical import Motion, Window

    if isinstance(plan, Window):
        for e in plan.partition_keys:
            needed.update(E.columns_used(e))
        for e, _, _ in plan.order_keys:
            needed.update(E.columns_used(e))
        for _, _, arg, *_ in plan.wfuncs:
            if arg is not None:
                needed.update(E.columns_used(arg))
    if isinstance(plan, Project):
        for _, e in plan.exprs:
            needed.update(E.columns_used(e))
    elif isinstance(plan, Filter):
        needed.update(E.columns_used(plan.predicate))
    elif isinstance(plan, Join):
        for e in plan.left_keys + plan.right_keys:
            needed.update(E.columns_used(e))
        if plan.residual is not None:
            needed.update(E.columns_used(plan.residual))
        if plan.kind in ("inner", "left", "cross"):
            pass
    elif isinstance(plan, Aggregate):
        for _, e in plan.group_keys:
            needed.update(E.columns_used(e))
        for _, a in plan.aggs:
            if a.arg is not None:
                needed.update(E.columns_used(a.arg))
    elif isinstance(plan, Sort):
        for e, _, _ in plan.keys:
            needed.update(E.columns_used(e))
    elif isinstance(plan, Motion):
        for e in plan.hash_exprs:
            needed.update(E.columns_used(e))
    for c in plan.children:
        _collect_needed(c, needed)


def _prune_scans(plan: Plan, needed: set):
    for c in plan.children:
        _prune_scans(c, needed)
    if isinstance(plan, Scan):
        kept = [c for c in plan.cols if c.id in needed]
        if not kept:
            kept = plan.cols[:1]   # keep one column for row counting
        plan.cols = kept
