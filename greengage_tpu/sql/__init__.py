from greengage_tpu.sql.parser import parse  # noqa: F401
