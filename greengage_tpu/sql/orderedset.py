"""Ordered-set aggregates — percentile_cont / percentile_disc / median.

Reference parity: the WITHIN GROUP ordered-set aggregates of
pg_aggregate.h:246 (percentile_* executed by sorting each group,
nodeAgg.c ordered-set path). The TPU-first translation avoids a new
executor mode entirely: the statement rewrites pre-bind into

    inner:  select *, row_number() over (partition by <group keys>
                                         order by <e>)   as __osrn_i,
                      count(<e>)  over (partition by ...) as __oscnt_i
    outer:  the original select over (inner), each percentile replaced
            by MAX(CASE WHEN __osrn = <order statistic position> ...)

so the heavy work is the engine's existing distributed window sort, and
the order statistic itself is an ordinary grouped aggregate — dense /
sort paths, spill, and multihost lockstep all apply unchanged.

Position math (PG semantics): cont: pos = 1 + q*(n-1), linear
interpolation between floor/ceil rows, result double precision; disc:
the first value at cumulative fraction >= q (position max(ceil(q*n), 1)),
original type. NULL order keys sort last with row numbers past count(e),
so they never select — PG's NULL-ignoring behavior for free. median(x)
is percentile_cont(0.5). DESC within-group order is rejected at parse."""

from __future__ import annotations

import copy
import dataclasses

from greengage_tpu.sql import ast as A
from greengage_tpu.sql.parser import SqlError

# authoritative name list lives in the binder (grouping-sets rewrite and
# aggregate detection consult it too)
from greengage_tpu.sql import binder as _b  # noqa: E402  (cycle-safe: names only)

ORDERED_SET = set(_b._ORDERED_SET_AGGS)


def _collect(stmt) -> list:
    calls: list = []

    def walk(n):
        if isinstance(n, A.SelectStmt):
            return
        if isinstance(n, A.FuncCall):
            if n.within_order is not None and n.name not in ORDERED_SET:
                raise SqlError(
                    f"WITHIN GROUP is not supported for {n.name}()")
            if n.name in ORDERED_SET and n.over is None:
                calls.append(n)
                return

        if isinstance(n, A.ANode):
            for f in dataclasses.fields(n):
                walk(getattr(n, f.name))
        elif isinstance(n, (list, tuple)):
            for v in n:
                walk(v)

    for it in stmt.items:
        walk(it.expr)
    if stmt.having is not None:
        walk(stmt.having)
    for oi in stmt.order_by:
        walk(oi.expr)
    return calls


def _resolved_group_keys(stmt) -> list:
    """GROUP BY entries with ordinals resolved to their select-item
    expressions (the binder's own ordinal rule) — a verbatim A.Num copied
    into a window PARTITION BY would bind as a constant instead."""
    out = []
    for g in stmt.group_by:
        if isinstance(g, A.Num) and "." not in g.text:
            idx = int(g.text) - 1
            if not 0 <= idx < len(stmt.items):
                raise SqlError(f"GROUP BY position {g.text} out of range")
            out.append(stmt.items[idx].expr)
        else:
            out.append(g)
    return out


def _strip_qualifiers(n):
    """Rewrite table-qualified Names to bare columns: the outer statement
    reads the flattened __os subquery, where the original table aliases
    no longer exist (PG would keep them; the Star-flattening loses them
    by construction)."""

    if isinstance(n, A.SelectStmt):
        return n
    if isinstance(n, A.Name) and len(n.parts) > 1:
        return A.Name((n.parts[-1],))
    if isinstance(n, A.ANode):
        for f in dataclasses.fields(n):
            setattr(n, f.name, _strip_qualifiers(getattr(n, f.name)))
        return n
    if isinstance(n, list):
        return [_strip_qualifiers(v) for v in n]
    if isinstance(n, tuple):
        return tuple(_strip_qualifiers(v) for v in n)
    return n


def _qfrac(call: A.FuncCall) -> float:
    if call.name == "median":
        if len(call.args) != 1:
            raise SqlError("median() takes exactly one argument")
        return 0.5
    if len(call.args) != 1 or not isinstance(call.args[0], A.Num):
        raise SqlError(f"{call.name}() needs a literal fraction argument")
    q = float(call.args[0].text)
    if not 0.0 <= q <= 1.0:
        raise SqlError(f"{call.name}() fraction must be in [0, 1]")
    return q


def _order_expr(call: A.FuncCall):
    if call.name == "median":
        return call.args[0]
    if call.within_order is None:
        raise SqlError(
            f"{call.name}() requires WITHIN GROUP (ORDER BY ...)")
    return call.within_order


def _num(v) -> A.ANode:
    return A.Num(repr(float(v)) if isinstance(v, float) else str(v))


def expand_ordered_set(stmt: A.SelectStmt):
    """-> replacement SelectStmt, or None when no ordered-set aggregates
    appear."""
    from greengage_tpu.sql.binder import _ast_key

    if stmt.grouping_sets is not None:
        # defer: the grouping-sets desugar re-enters _bind_select per
        # branch with that branch's concrete group_by, and THIS expansion
        # then applies with the right window partition keys (the
        # grouping() validation in _collect still runs per branch)
        return None
    calls = _collect(stmt)
    if not calls:
        return None
    if not stmt.from_:
        raise SqlError("percentile aggregates need a FROM clause")

    group_keys = _resolved_group_keys(stmt)
    # one window pair per DISTINCT order expression
    order_of: dict[str, tuple[int, A.ANode]] = {}
    for c in calls:
        e = _order_expr(c)
        k = _ast_key(e)
        if k not in order_of:
            order_of[k] = (len(order_of), e)

    inner = A.SelectStmt()
    inner.from_ = stmt.from_
    inner.where = stmt.where
    inner.items = [A.SelectItem(A.Star())]
    for k, (i, e) in order_of.items():
        over_rank = A.WindowSpec(
            partition_by=[copy.deepcopy(g) for g in group_keys],
            order_by=[A.OrderItem(copy.deepcopy(e))])
        # the count window must NOT carry the order key: an ordered count
        # is a RUNNING count up to peers, not the group size
        over_cnt = A.WindowSpec(
            partition_by=[copy.deepcopy(g) for g in group_keys])
        inner.items.append(A.SelectItem(
            A.FuncCall("row_number", [], over=over_rank),
            alias=f"__osrn{i}"))
        inner.items.append(A.SelectItem(
            A.FuncCall("count", [copy.deepcopy(e)], over=over_cnt),
            alias=f"__oscnt{i}"))

    def replacement(call: A.FuncCall) -> A.ANode:
        q = _qfrac(call)
        e = _order_expr(call)
        i = order_of[_ast_key(e)][0]
        rn = A.Name((f"__osrn{i}",))
        cnt = A.Name((f"__oscnt{i}",))

        def mx(arg):
            return A.FuncCall("max", [arg])

        def when(cond, val):
            return A.CaseExpr(whens=[(cond, val)], else_=None)

        if call.name == "percentile_disc":
            posd = A.FuncCall("ceiling", [
                A.Bin("*", _num(q), copy.deepcopy(cnt))])
            posd = A.CaseExpr(
                whens=[(A.Bin("<", posd, _num(1)), _num(1))],
                else_=copy.deepcopy(posd))
            return mx(when(A.Bin("=", rn, posd), copy.deepcopy(e)))
        # cont / median: interpolate between the floor/ceil positions
        xf = A.CastExpr(copy.deepcopy(e), "double precision")

        def pos_over(cnt_node):
            return A.Bin("+", _num(1), A.Bin(
                "*", _num(q), A.Bin("-", cnt_node, _num(1))))

        vlo = mx(when(A.Bin("=", copy.deepcopy(rn), A.FuncCall(
            "floor", [pos_over(copy.deepcopy(cnt))])), xf))
        vhi = mx(when(A.Bin("=", copy.deepcopy(rn), A.FuncCall(
            "ceiling", [pos_over(copy.deepcopy(cnt))])),
            copy.deepcopy(xf)))
        pos_g = pos_over(mx(copy.deepcopy(cnt)))
        frac = A.Bin("-", pos_g, A.FuncCall(
            "floor", [copy.deepcopy(pos_g)]))
        return A.Bin("+", vlo, A.Bin("*", frac, A.Bin("-", vhi,
                                                      copy.deepcopy(vlo))))

    def rewrite(n):
        if isinstance(n, A.SelectStmt):
            return n
        if isinstance(n, A.FuncCall) and n.name in ORDERED_SET \
                and n.over is None:
            return replacement(n)
        if isinstance(n, A.ANode):
            for f in dataclasses.fields(n):
                setattr(n, f.name, rewrite(getattr(n, f.name)))
            return n
        if isinstance(n, list):
            return [rewrite(v) for v in n]
        if isinstance(n, tuple):
            return tuple(rewrite(v) for v in n)
        return n

    outer = A.SelectStmt(
        items=stmt.items, from_=[A.SubqueryRef(inner, "__os")],
        where=None, group_by=stmt.group_by, having=stmt.having,
        order_by=stmt.order_by, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct)
    for it in outer.items:
        it.expr = _strip_qualifiers(rewrite(it.expr))
    outer.group_by = [_strip_qualifiers(g) for g in outer.group_by]
    if outer.having is not None:
        outer.having = _strip_qualifiers(rewrite(outer.having))
    for oi in outer.order_by:
        oi.expr = _strip_qualifiers(rewrite(oi.expr))
    return outer
