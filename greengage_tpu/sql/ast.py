"""SQL abstract syntax — output of the parser, input to the binder.

Covers the analytical core of the reference's PostgreSQL 9.4 grammar
(src/backend/parser/gram.y): SELECT with joins/grouping/ordering, DDL with
Greenplum DISTRIBUTED clauses (exttablecmds/gram.y GP extensions), INSERT,
COPY, EXPLAIN.
"""

from __future__ import annotations

from dataclasses import dataclass, field


# ---- expressions ----------------------------------------------------------

@dataclass
class ANode:
    pass


@dataclass
class Name(ANode):
    parts: tuple[str, ...]        # possibly qualified: (alias, col) or (col,)


@dataclass
class Num(ANode):
    text: str


@dataclass
class Str(ANode):
    value: str


@dataclass
class DateLit(ANode):
    value: str


@dataclass
class IntervalLit(ANode):
    value: str
    unit: str                     # day | month | year


@dataclass
class Null(ANode):
    pass


@dataclass
class Bool(ANode):
    value: bool


@dataclass
class ParamRef(ANode):
    """A literal hoisted out of the statement by sql/paramize.py: the
    binder lowers it to a typed expr.Param read from the statement's
    parameter vector at execution. ``ptype`` is the exact SqlType the
    original literal would have bound to — it stays in the cache key, so
    only same-typed shapes share a plan. ``est_value`` carries the
    hoisted value for ESTIMATION only (selectivity/capacity sizing —
    the custom-plan seeding of a generic plan); it is excluded from repr
    so the cache signature stays value-free."""

    idx: int
    ptype: object                 # types.SqlType
    est_value: object = field(default=None, repr=False, compare=False)


@dataclass
class Star(ANode):
    table: str | None = None      # t.* or *


@dataclass
class Bin(ANode):
    op: str
    left: ANode
    right: ANode


@dataclass
class Unary(ANode):
    op: str                       # - | not
    arg: ANode


@dataclass
class IsNullTest(ANode):
    arg: ANode
    negate: bool


@dataclass
class Between(ANode):
    arg: ANode
    lo: ANode
    hi: ANode
    negate: bool = False


@dataclass
class InExpr(ANode):
    arg: ANode
    values: list[ANode]
    negate: bool = False


@dataclass
class LikeExpr(ANode):
    arg: ANode
    pattern: str
    negate: bool = False


@dataclass
class InSubquery(ANode):
    arg: ANode
    query: "SelectStmt"
    negate: bool = False


@dataclass
class ExistsExpr(ANode):
    query: "SelectStmt"
    negate: bool = False


@dataclass
class ScalarSubquery(ANode):
    query: "SelectStmt"


@dataclass
class CaseExpr(ANode):
    whens: list[tuple[ANode, ANode]]
    else_: ANode | None


@dataclass
class CastExpr(ANode):
    arg: ANode
    type_name: str
    typmod: tuple[int, ...] = ()


@dataclass
class WindowSpec(ANode):
    partition_by: list = field(default_factory=list)
    order_by: list = field(default_factory=list)   # OrderItem
    # ("rows"|"range", (bound kind, n), (bound kind, n)) or None (default
    # frame: RANGE UNBOUNDED PRECEDING .. CURRENT ROW)
    frame: tuple | None = None


@dataclass
class FuncCall(ANode):
    name: str
    args: list[ANode]
    star: bool = False            # count(*)
    distinct: bool = False
    over: "WindowSpec | None" = None
    # ordered-set aggregates: percentile_cont(q) WITHIN GROUP (ORDER BY e)
    within_order: "ANode | None" = None


@dataclass
class ExtractExpr(ANode):
    field: str                    # year | month | day
    arg: ANode


# ---- query structure ------------------------------------------------------

@dataclass
class TableRef(ANode):
    pass


@dataclass
class BaseTable(TableRef):
    name: str
    alias: str | None = None


@dataclass
class SubqueryRef(TableRef):
    query: "SelectStmt"
    alias: str = ""


@dataclass
class JoinRef(TableRef):
    kind: str                     # inner | left | cross
    left: TableRef
    right: TableRef
    on: ANode | None = None


@dataclass
class SelectItem(ANode):
    expr: ANode
    alias: str | None = None


@dataclass
class OrderItem(ANode):
    expr: ANode
    desc: bool = False
    nulls_first: bool | None = None


@dataclass
class UnionStmt(ANode):
    selects: list = field(default_factory=list)   # SelectStmt branches
    all: bool = True
    order_by: list = field(default_factory=list)  # OrderItem over branch-1 names
    limit: int | None = None
    offset: int = 0


@dataclass
class SelectStmt(ANode):
    items: list[SelectItem] = field(default_factory=list)
    from_: list[TableRef] = field(default_factory=list)
    where: ANode | None = None
    group_by: list[ANode] = field(default_factory=list)
    having: ANode | None = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: int | None = None
    offset: int = 0
    distinct: bool = False
    # GROUP BY ROLLUP/CUBE/GROUPING SETS, normalized by the parser into an
    # explicit list of grouping sets (each a list of key exprs); group_by
    # stays empty when set (gram.y:12457 group_clause extensions)
    grouping_sets: "list[list[ANode]] | None" = None
    # set on desugared grouping-set branches: bind as a grouped select
    # even when this branch's key set is empty (GROUP BY () -> one row)
    forced_group: bool = False


@dataclass
class RecursiveCTE(ANode):
    """WITH RECURSIVE r AS (base UNION [ALL] recursive): split at parse
    time; the session iterates the recursive term against a worktable
    until fixpoint (nodeRecursiveunion.c / WorkTableScan role,
    gram.y:12190)."""

    name: str
    base: ANode                  # branches not referencing ``name``
    rec: ANode                   # branches referencing ``name``
    union_all: bool              # False -> dedupe rows across iterations


@dataclass
class TypedNullOf(ANode):
    """NULL carrying the type (and TEXT dictionary) of another expression —
    the grouping-sets desugar emits these for keys absent from a set so
    UNION branch schemas line up without guessing types."""

    arg: ANode


# ---- DDL / DML / utility --------------------------------------------------

@dataclass
class ColumnDef(ANode):
    name: str
    type_name: str
    typmod: tuple[int, ...] = ()
    not_null: bool = False


@dataclass
class PartitionDef(ANode):
    name: str
    lo: ANode | None = None       # RANGE START literal (inclusive)
    hi: ANode | None = None       # RANGE END literal (exclusive)
    every: ANode | None = None    # RANGE EVERY step (expands to a series)
    values: list = field(default_factory=list)   # LIST literals
    default: bool = False


@dataclass
class CreateTableStmt(ANode):
    name: str
    columns: list[ColumnDef]
    dist_kind: str = "hash"       # hash | random | replicated
    dist_keys: list[str] = field(default_factory=list)
    options: dict = field(default_factory=dict)
    if_not_exists: bool = False
    partition_kind: str | None = None    # range | list
    partition_col: str | None = None
    partition_defs: list[PartitionDef] = field(default_factory=list)


@dataclass
class ResourceGroupStmt(ANode):
    action: str                   # create | drop | alter
    name: str
    options: dict = field(default_factory=dict)


@dataclass
class AlterTableStmt(ANode):
    table: str
    action: str                   # add_partition | drop_partition
    partition: PartitionDef | None = None
    partition_name: str | None = None


@dataclass
class DropTableStmt(ANode):
    name: str
    if_exists: bool = False


@dataclass
class CreateExternalTableStmt(ANode):
    name: str
    columns: list[ColumnDef]
    writable: bool = False
    urls: list[str] = field(default_factory=list)   # LOCATION clause
    exec_cmd: str | None = None                     # EXECUTE clause
    format_opts: dict = field(default_factory=dict)
    reject_limit: int | None = None
    if_not_exists: bool = False


@dataclass
class InsertStmt(ANode):
    table: str
    columns: list[str]
    rows: list[list[ANode]]
    query: ANode | None = None    # INSERT INTO ... SELECT


@dataclass
class DeleteStmt(ANode):
    table: str
    where: ANode | None = None


@dataclass
class UpdateStmt(ANode):
    table: str
    sets: list = field(default_factory=list)   # [(colname, expr)]
    where: ANode | None = None


@dataclass
class CopyStmt(ANode):
    table: str
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class ExplainStmt(ANode):
    query: ANode
    analyze: bool = False


@dataclass
class ShowStmt(ANode):
    what: str


@dataclass
class SetStmt(ANode):
    name: str
    value: object


@dataclass
class TxStmt(ANode):
    action: str        # begin | commit | abort


@dataclass
class CreateIndexStmt(ANode):
    name: str
    table: str
    column: str
    using: str = "btree"
    if_not_exists: bool = False


@dataclass
class DropIndexStmt(ANode):
    name: str
    if_exists: bool = False


@dataclass
class AnalyzeStmt(ANode):
    table: str | None = None   # None = every table


@dataclass
class CreateExtensionStmt(ANode):
    name: str
    if_not_exists: bool = False


@dataclass
class DeclareCursorStmt(ANode):
    name: str
    query: ANode          # SelectStmt/UnionStmt


@dataclass
class RetrieveStmt(ANode):
    endpoint: int
    cursor: str


@dataclass
class CloseCursorStmt(ANode):
    cursor: str
