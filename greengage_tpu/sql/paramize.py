"""Literal parameterization — the prepared-statement / generic-plan pass.

A serving workload is dominated by repeated query *shapes* with varying
literals ("dashboard queries"). Today's plan cache keys on ``repr(stmt)``
— which embeds literal values — and the evaluator bakes each literal into
the traced program, so ``WHERE x > 5`` vs ``WHERE x > 6`` each pay a full
re-plan plus a multi-second XLA compile. This pass is the
plancache.c/prepared-statement analog: it walks a SELECT-shaped AST,
hoists plan-safe literals into an ordered parameter vector, and replaces
them with typed ``A.ParamRef`` nodes. The literal-stripped statement repr
(plus the hoisted literals' exact types) becomes the plan-cache key; the
values travel separately and feed the compiled program as traced scalar
inputs (ops/expr_eval.Evaluator._eval_param).

Safe/unsafe classification (docs/PERF.md "Plan cache"):

- **Hoistable**: numeric and date literals in comparisons, arithmetic,
  BETWEEN bounds, CASE branches, and extract() arguments. Zone-map prune
  predicates built over hoisted literals keep working: the planner records
  the Param in the pushed predicate and the executor substitutes the
  current value at staging time (the value affects which blocks are READ,
  never the compiled program).
- **Pinned** (stay literal, values in the cache key): everything whose
  value feeds a *plan-time* decision or a bind-time rewrite —
  - string literals (dictionary-code lookup, LIKE lowering, raw-text
    word-compare rewrites are all bind-time value rewrites);
  - any comparison against a partition key (static partition pruning
    changes the staged input spec and capacities);
  - any comparison against ``extract(year from col)`` (the planner
    derives zone-map day bounds from the year value at plan time);
  - equality against a hash-distribution key (direct dispatch pins the
    scan to one segment in the input spec);
  - IN lists, string-function arguments, CAST operands, interval
    arithmetic (the binder folds/validates these as literals);
  - LIMIT/OFFSET counts (plain AST ints — naturally part of the repr);
  - anything inside GROUP BY / ORDER BY / window specs (positional
    references, group-key matching by AST shape) or nested subqueries
    (bound by a separate pass).

A shape the binder still cannot parameterize (e.g. raw-text predicates)
raises at bind time; the session falls back to the classic value-pinned
plan under the full-repr key — correctness never depends on this pass.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass

from greengage_tpu import types as T
from greengage_tpu.sql import ast as A


@dataclass(frozen=True)
class ParamVector:
    """One statement execution's hoisted literal values, slot-ordered.
    ``types`` are the exact SqlTypes the literals would have bound to —
    they are part of the plan-cache key, the values are not. Travels in
    the plan's consts dict under the reserved "@params@" key."""

    values: tuple
    types: tuple


def coerce_storage_value(v, ft, tt):
    """Numeric storage-representation coercion of a hoisted value from
    type ``ft`` to ``tt`` — the host mirror of Binder._coerce_literal's
    numeric branches, so runtime-resolved values match exactly what a
    pinned literal would have bound to."""
    if ft == tt:
        return v
    if tt.kind is T.Kind.DECIMAL:
        if ft.kind is T.Kind.DECIMAL:
            from greengage_tpu.ops.expr_eval import _rescale_host

            return _rescale_host(v, ft.scale, tt.scale)
        return int(v) * 10 ** tt.scale
    if tt.kind is T.Kind.FLOAT64:
        if ft.kind is T.Kind.DECIMAL:
            return v / 10 ** ft.scale
        return float(v)
    if tt.kind in (T.Kind.INT32, T.Kind.INT64):
        return int(v)
    return v


def resolve_param_value(expr, vec: ParamVector):
    """Concrete storage value of a prune-predicate operand built over a
    hoisted parameter — a bare expr.Param or the binder's numeric
    coercion Cast around one (planner._param_value) — so staging-time
    zone-map / block-index probes see exactly the value a pinned literal
    would have bound to."""
    from greengage_tpu import expr as E

    if isinstance(expr, E.Param):
        return vec.values[expr.slot]
    assert isinstance(expr, E.Cast) and isinstance(expr.arg, E.Param)
    return coerce_storage_value(vec.values[expr.arg.slot],
                                expr.arg.type, expr.type)


def _literal_of(node):
    """Mirror of Binder._expr literal construction: the (value, type) the
    binder would produce for this AST literal, in storage representation.
    None when the node is not a hoistable literal."""
    if isinstance(node, A.Num):
        if "." in node.text:
            frac = len(node.text.split(".")[1])
            return T.decimal_to_int(node.text, frac), T.decimal(frac)
        v = int(node.text)
        return v, T.literal_type(v)
    if isinstance(node, A.DateLit):
        return T.date_to_days(node.value), T.DATE
    if isinstance(node, A.Unary) and node.op == "-":
        inner = _literal_of(node.arg)
        if inner is None or isinstance(node.arg, A.Unary):
            return None
        v, t = inner
        # the binder folds unary minus keeping the POSITIVE literal's type
        return -v, t
    return None


class _Paramizer:
    def __init__(self, catalog):
        self.params: list[tuple] = []   # (value, SqlType)
        # column names whose comparisons stay pinned: partition keys for
        # every op (static partition pruning is a plan-time decision),
        # hash-distribution keys for equality (direct dispatch). Matching
        # is by unqualified column name across the statement's base
        # tables — over-pinning is a perf loss, never a correctness one.
        self.pin_all: set[str] = set()
        self.pin_eq: set[str] = set()
        self.catalog = catalog

    def collect_tables(self, stmt) -> None:
        for ref in getattr(stmt, "from_", ()) or ():
            self._collect_ref(ref)

    def _collect_ref(self, ref) -> None:
        if isinstance(ref, A.JoinRef):
            self._collect_ref(ref.left)
            self._collect_ref(ref.right)
            return
        if not isinstance(ref, A.BaseTable):
            return
        try:
            schema = self.catalog.get(ref.name)
        except Exception:
            return
        if getattr(schema, "partition_by", None) is not None:
            self.pin_all.add(schema.partition_by[1])
        for k in getattr(schema.policy, "keys", ()) or ():
            self.pin_eq.add(k)

    # ------------------------------------------------------------------
    def _hoist(self, node):
        lit = _literal_of(node)
        if lit is None:
            return node
        v, t = lit
        if t.kind is T.Kind.TEXT or isinstance(v, bool):
            return node
        idx = len(self.params)
        self.params.append((v, t))
        return A.ParamRef(idx, t, est_value=v)

    def _pinned_name(self, node, op: str) -> bool:
        """Is ``node`` an operand whose comparisons must stay literal?"""
        if isinstance(node, A.ExtractExpr) and node.field.lower() == "year" \
                and isinstance(node.arg, A.Name):
            # extract(year from col) <op> literal: the planner derives
            # zone-map day bounds on the base column from the literal at
            # plan time (planner._year_prune) — hoisting the year would
            # make the TPC-DS date-filter pruning inert, so it stays in
            # the cache key like partition-key comparisons do
            return True
        if not isinstance(node, A.Name):
            return False
        name = node.parts[-1]
        if name in self.pin_all:
            return True
        return op == "=" and name in self.pin_eq

    def expr(self, node):
        """Rewrite one scalar expression tree in place; returns the
        (possibly replaced) node."""
        if node is None or not isinstance(node, A.ANode):
            return node
        if isinstance(node, A.Bin):
            if node.op in ("=", "<>", "<", "<=", ">", ">="):
                # a literal facing a pinned column stays pinned; the
                # opposite operand still rewrites normally
                if not self._pinned_name(node.left, node.op):
                    node.right = self._rw_operand(node.right)
                if not self._pinned_name(node.right, node.op):
                    node.left = self._rw_operand(node.left)
                return node
            if node.op in ("and", "or"):
                node.left = self.expr(node.left)
                node.right = self.expr(node.right)
                return node
            if node.op == "||" or isinstance(node.right, A.IntervalLit):
                # concat needs literals; date +/- interval folds at bind
                return node
            if node.op in ("+", "-", "*", "/", "%"):
                node.left = self._rw_operand(node.left)
                node.right = self._rw_operand(node.right)
                return node
            return node
        if isinstance(node, A.Unary):
            if node.op == "not":
                node.arg = self.expr(node.arg)
            # unary minus over a literal is handled by _rw_operand at the
            # parent; a bare `-x` recurses
            elif _literal_of(node) is None:
                node.arg = self.expr(node.arg)
            return node
        if isinstance(node, A.Between):
            node.arg = self.expr(node.arg)
            if not self._pinned_name(node.arg, "<"):
                node.lo = self._rw_operand(node.lo)
                node.hi = self._rw_operand(node.hi)
            return node
        if isinstance(node, A.IsNullTest):
            node.arg = self.expr(node.arg)
            return node
        if isinstance(node, A.InExpr):
            node.arg = self.expr(node.arg)   # values must stay literal
            return node
        if isinstance(node, A.LikeExpr):
            node.arg = self.expr(node.arg)   # pattern is a str field
            return node
        if isinstance(node, A.CaseExpr):
            node.whens = [(self.expr(c), self._rw_operand(v))
                          for c, v in node.whens]
            if node.else_ is not None:
                node.else_ = self._rw_operand(node.else_)
            return node
        if isinstance(node, A.ExtractExpr):
            node.arg = self.expr(node.arg)
            return node
        # pinned wholesale: FuncCall args (string funcs demand literals,
        # aggregates key group matching on AST shape), CastExpr (the
        # binder folds literal casts), subqueries (bound separately),
        # window specs, IntervalLit, Str/Null/Bool and bare literals in
        # non-expression positions
        return node

    def _rw_operand(self, node):
        """An operand position where a literal is hoistable."""
        rep = self._hoist(node)
        if rep is not node:
            return rep
        return self.expr(node)

    # ------------------------------------------------------------------
    def select(self, stmt: A.SelectStmt) -> None:
        self.collect_tables(stmt)
        if stmt.where is not None:
            stmt.where = self.expr(stmt.where)
        # grouped statements: the binder matches GROUP BY keys to select
        # items by AST shape — hoisting on one side only would break the
        # match, so grouped targetlists/HAVING stay pinned
        if not stmt.group_by and not stmt.grouping_sets \
                and not stmt.forced_group:
            for it in stmt.items:
                if not isinstance(it.expr, A.Star):
                    it.expr = self._rw_operand(it.expr)
            if stmt.having is not None:
                stmt.having = self.expr(stmt.having)
        for ref in stmt.from_:
            self._join_on(ref)

    def _join_on(self, ref) -> None:
        if isinstance(ref, A.JoinRef):
            if ref.on is not None:
                ref.on = self.expr(ref.on)
            self._join_on(ref.left)
            self._join_on(ref.right)


def paramize(stmt, catalog):
    """-> (normalized stmt, ParamVector, signature) for SELECT-shaped
    statements, or (stmt, None, None) when nothing was hoisted. The
    normalized statement is a deep copy with hoistable literals replaced
    by A.ParamRef nodes; the signature is its value-free repr (ParamRef
    reprs carry the literal TYPES, so only same-typed shapes share it)."""
    if not isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
        return stmt, None, None
    if getattr(stmt, "_recursive_ctes", None):
        return stmt, None, None   # fixpoint terms re-execute via session
    norm = copy.deepcopy(stmt)
    p = _Paramizer(catalog)
    try:
        if isinstance(norm, A.UnionStmt):
            for s in norm.selects:
                if isinstance(s, A.SelectStmt):
                    p.select(s)
        else:
            p.select(norm)
    except Exception:
        return stmt, None, None   # malformed AST: bind the original
    if not p.params:
        return stmt, None, None
    vec = ParamVector(tuple(v for v, _ in p.params),
                      tuple(t for _, t in p.params))
    return norm, vec, "P:" + repr(norm)
