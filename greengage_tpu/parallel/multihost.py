"""Multi-host runtime — the interconnect/dispatch fabric across hosts.

Reference parity: the reference spans hosts with libpq dispatch (control
plane) + UDPIFC/ic-proxy (data plane, src/backend/cdb/motion/ic_udpifc.c,
README.ic-proxy.md). The TPU-native translation:

  data plane   = XLA collectives over the GLOBAL device mesh
                 (jax.distributed: every process contributes its local
                 chips; all_to_all/all_gather ride ICI/DCN)
  control plane = a slim TCP statement channel (the libpq 'M'-message
                 role): the coordinator broadcasts each SQL statement,
                 every process plans/compiles the SAME program from the
                 shared catalog (multi-controller SPMD), workers stage
                 only their LOCAL segments' storage, and the jitted
                 program's collectives synchronize execution.

Lockstep invariants (why this is deterministic):
  * all processes see the same cluster directory (shared/replicated fs);
    workers refresh catalog+manifest before each statement,
  * binder/planner are deterministic, so every process compiles an
    identical HLO and the collectives rendezvous,
  * overflow flags and metrics are device-reduced (pmax/psum over the
    mesh) and replicated, so every process takes the same capacity-retry
    decision without any extra control traffic,
  * only the coordinator performs writes (manifest/catalog/dictionaries);
    workers run the device part of DML's internal scans and skip the
    publish.

Failure model (docs/ROBUSTNESS.md): every control-channel read — the
startup accept, readiness/completion acks, the worker's statement wait —
is bounded by a deadline from config.py (mh_connect_deadline,
mh_ready_deadline, mh_ack_deadline), so silence classifies as WorkerDied
instead of hanging the cluster; idle-time ping/pong heartbeats
(mh_heartbeat_interval) catch partitions between statements; and a
quiesced coordinator keeps its listener open so a recovered worker can
rejoin (hello/sync handshake) and mesh dispatch resumes — the ftsprobe
timeout + cdbgang re-formation roles.
"""

from __future__ import annotations

import json
import socket
import sys
import threading
from contextlib import contextmanager
from dataclasses import dataclass
import subprocess

from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.retry import (Deadline, RetryPolicy,
                                         TRANSIENT_ERRORS)


@dataclass
class MultihostRuntime:
    process_id: int
    num_processes: int
    channel: object = None            # CoordinatorChannel | WorkerChannel
    local_segments: tuple = ()        # mesh positions of this process's devices

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def init_multihost(coordinator: str, num_processes: int, process_id: int,
                   control_port: int,
                   connect_deadline: float | None = None,
                   distributed: bool = True) -> MultihostRuntime:
    """Join the distributed JAX runtime and the control channel. Must run
    BEFORE any devices are used.

    distributed=False joins ONLY the control channel (no jax.distributed
    global mesh): every process compiles and executes the lockstep program
    over its own full local mesh. That is the mode for replicated-device
    deployments and for CPU demo clusters — XLA's CPU backend has no
    cross-process collectives, and the coordination service force-kills
    surviving processes when a peer dies, which would defeat the gang
    recovery the control plane provides (docs/ROBUSTNESS.md)."""
    if distributed:
        import jax

        jax.distributed.initialize(coordinator, num_processes=num_processes,
                                   process_id=process_id)
    host = coordinator.rsplit(":", 1)[0]
    if process_id == 0:
        ch = CoordinatorChannel(control_port, num_processes - 1,
                                connect_deadline=connect_deadline)
    else:
        ch = WorkerChannel(host, control_port, process_id=process_id,
                           connect_deadline=connect_deadline)
    return MultihostRuntime(process_id, num_processes, ch)


def local_segment_positions() -> tuple:
    """Mesh positions (= segment ids) of this process's devices, assuming
    the mesh enumerates jax.devices() in order (parallel/mesh.py does)."""
    import jax

    all_devs = {id(d): i for i, d in enumerate(jax.devices())}
    return tuple(sorted(all_devs[id(d)] for d in jax.local_devices()))


# ---------------------------------------------------------------------------
# control channel: line-JSON over TCP
# ---------------------------------------------------------------------------

class WorkerDied(ConnectionError):
    """A worker's control connection is gone OR silent past its deadline
    (process death / network partition / wedged process): the statement
    channel cannot reach the full gang. ``process_id`` carries the peer
    the failure was observed on (None when unattributable) so mesh
    re-formation can name the lost worker."""

    def __init__(self, msg: str, process_id: int | None = None):
        super().__init__(msg)
        self.process_id = process_id


class CoordinatorLost(ConnectionError):
    """The worker's control connection to the coordinator dropped WITHOUT
    a clean 'stop' frame — coordinator death or a gang re-formation, never
    a normal shutdown."""


def _limit(settings, name_or_value) -> float:
    """Resolve a deadline: a literal number, or a config.py setting name —
    falling back to the Settings dataclass DEFAULT (its class attribute)
    when no Settings object is attached yet (the channel exists before the
    Database that owns the live values)."""
    if isinstance(name_or_value, (int, float)):
        return float(name_or_value)
    v = getattr(settings, name_or_value, None) if settings is not None else None
    if v is None:
        from greengage_tpu.config import Settings

        v = getattr(Settings, name_or_value)
    return float(v)


class _Peer:
    """One accepted worker connection (socket kept for per-read timeouts)."""

    __slots__ = ("sock", "f", "process_id")

    def __init__(self, sock, f, process_id):
        self.sock = sock
        self.f = f
        self.process_id = process_id

    def close(self):
        for obj in (self.f, self.sock):
            try:
                obj.close()
            except Exception:
                pass


class CoordinatorChannel:
    """Accepts every worker once, then broadcasts statements and collects
    acks (the CdbDispatchCommand/checkDispatchResult roles).

    Locking: one re-entrant lock serializes whole EXCHANGES (send .. acks)
    against the heartbeat thread; hold it via the ``exchange()`` context
    manager. send/collect also take it internally (re-entrant), so a
    failed send can never leave the lock held across methods — close()
    always completes.
    """

    def __init__(self, port: int, expected_workers: int, settings=None,
                 connect_deadline: float | None = None):
        self.settings = settings
        self.hb_failure: str | None = None   # set by the heartbeat thread
        self._lock = threading.RLock()
        self._workers: list[_Peer] = []
        self._pending: dict[int, _Peer] = {}  # rejoin handshakes by process id
        self._expected = expected_workers
        self._quiesced = False
        self._closed = False
        self._hb_thread: threading.Thread | None = None
        self._hb_stop = threading.Event()
        self._rejoin_thread: threading.Thread | None = None
        self._rejoin_stop = threading.Event()
        self._rejoin_ready = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(max(expected_workers, 1))
        # bounded gang assembly (gp_segment_connect_timeout): a worker that
        # never launches must fail startup with a count, not hang accept()
        dl = Deadline(_limit(settings, connect_deadline
                             if connect_deadline is not None
                             else "mh_connect_deadline"))
        try:
            for _ in range(expected_workers):
                try:
                    self._srv.settimeout(dl.remaining(minimum=0.001))
                    # gang assembly at Database init: no statement exists
                    # yet; bounded by mh_connect_deadline
                    conn, _ = self._srv.accept()   # gg:ok(interrupts)
                    peer = self._handshake(conn, dl)
                except (socket.timeout, TimeoutError):
                    raise WorkerDied(
                        f"only {len(self._workers)} of {expected_workers} "
                        f"workers joined within the "
                        f"{dl.seconds:.0f}s mh_connect_deadline")
                self._workers.append(peer)
        except BaseException:
            for p in self._workers:
                p.close()
            self._srv.close()
            raise
        self._srv.settimeout(None)

    def _handshake(self, conn, dl: Deadline) -> _Peer:
        """Read the worker's hello frame (identifies its process id; a
        connection that never says hello counts against the deadline)."""
        conn.settimeout(dl.remaining(minimum=0.001))
        f = conn.makefile("rwb")
        line = f.readline()
        if not line:
            raise WorkerDied("worker connection closed during handshake")
        try:
            msg = json.loads(line)
        except ValueError:
            raise WorkerDied(f"bad hello frame: {line[:80]!r}")
        conn.settimeout(None)
        return _Peer(conn, f, msg.get("process_id"))

    # ---- exchange discipline -------------------------------------------
    @contextmanager
    def exchange(self):
        """Scope one whole protocol exchange (send .. collect) so the
        heartbeat thread can never interleave frames with a statement."""
        with self._lock:
            yield self

    def send(self, msg: dict) -> None:
        with self._lock:
            if self._closed:
                raise WorkerDied("control channel is closed")
            if self.hb_failure:
                # a stale late ack from the failed heartbeat round could
                # otherwise be mis-read as this exchange's ack
                raise WorkerDied(
                    f"control channel marked dead by heartbeat: "
                    f"{self.hb_failure}")
            try:
                if faults.check("dispatch_send"):
                    return     # 'skip' drops the frame (partition analog)
            except FaultError as e:
                raise WorkerDied(str(e))
            line = (json.dumps(msg) + "\n").encode()
            pid = None
            try:
                for p in self._workers:
                    pid = p.process_id
                    p.sock.settimeout(
                        _limit(self.settings, "mh_ready_deadline"))
                    p.f.write(line)
                    p.f.flush()
            except (socket.timeout, TimeoutError) as e:
                raise WorkerDied(f"worker send timed out: {e}",
                                 process_id=pid)
            except OSError as e:
                raise WorkerDied(f"worker connection lost on send: {e}",
                                 process_id=pid)

    def collect_acks(self, deadline="mh_ack_deadline",
                     phase: str = "ack") -> list[dict]:
        acks = self.collect_raw(deadline, phase)
        errs = [a for a in acks if not a.get("ok")]
        if errs:
            raise RuntimeError(f"worker error: {errs[0].get('error')}")
        return acks

    def collect_raw(self, deadline="mh_ack_deadline",
                    phase: str = "ack") -> list[dict]:
        """Collect one ack per worker WITHOUT raising on not-ok — for
        ops whose ack 'error' slot carries payload (exec/gpssh output).
        One deadline bounds the WHOLE round: a silent worker classifies
        as dead, never as an unbounded block."""
        with self._lock:
            limit = _limit(self.settings, deadline)
            dl = Deadline(limit)
            acks = []
            cancelled = None
            for p in self._workers:
                # per-worker read boundary = cancellation point, ONLY for
                # the statement phases whose callers handle the unwind —
                # a raise during set/sync/fault exchanges would strand
                # buffered acks for the next exchange to misread. A no-op
                # for the heartbeat thread (no registered statement).
                # Completion raises EARLY (the wait IS workers running
                # their program; the session degrades the gang, and the
                # quiesce clears any already-buffered acks). Readiness
                # DRAINS the round first — workers ack readiness
                # promptly, so finishing the reads is cheap and leaves
                # the ack stream clean for the session's 'skip' release.
                if phase == "completion":
                    interrupt.check_interrupts()
                elif phase == "readiness" and cancelled is None:
                    try:
                        interrupt.check_interrupts()
                    except Exception as e:
                        cancelled = e
                try:
                    p.sock.settimeout(dl.remaining(minimum=0.001))
                    line = p.f.readline()
                except (socket.timeout, TimeoutError):
                    raise WorkerDied(
                        f"{phase} ack from worker {p.process_id} timed out "
                        f"after {limit:.1f}s — hung or partitioned",
                        process_id=p.process_id)
                except OSError as e:
                    raise WorkerDied(f"worker connection lost: {e}",
                                     process_id=p.process_id)
                if not line:
                    raise WorkerDied(
                        f"worker {p.process_id} connection closed (EOF) — "
                        "the process died mid-statement",
                        process_id=p.process_id)
                try:
                    acks.append(json.loads(line))
                except ValueError as e:
                    raise WorkerDied(f"garbled ack frame: {e}",
                                     process_id=p.process_id)
            if cancelled is not None:
                raise cancelled   # after the drain: no stale acks remain
            return acks

    def broadcast(self, msg: dict, deadline="mh_ack_deadline",
                  phase: str = "ack") -> list[dict]:
        """Send to all workers and wait for every ack, as one exchange."""
        with self.exchange():
            self.send(msg)
            return self.collect_acks(deadline, phase)

    # ---- heartbeats (idle-time liveness, FTS-probe cadence) ------------
    def start_heartbeat(self) -> None:
        """Ping/pong between statements. A beat is skipped while an
        exchange holds the lock (an in-flight statement IS liveness
        traffic). On failure the channel marks itself dead — the next
        statement degrades instead of dispatching into a black hole."""
        if _limit(self.settings, "mh_heartbeat_interval") <= 0:
            return
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._hb_stop = threading.Event()

        def loop():
            while True:
                interval = _limit(self.settings, "mh_heartbeat_interval")
                if interval <= 0:
                    return     # '0 disables' applies to a LIVE SET too —
                               # wait(0) would turn this into a busy loop
                # heartbeat daemon thread: never a statement thread
                if self._hb_stop.wait(interval):   # gg:ok(interrupts)
                    return
                if self._quiesced or self._closed or self.hb_failure:
                    return
                if not self._lock.acquire(blocking=False):
                    continue       # statement in flight = alive
                try:
                    if self._quiesced or self._closed:
                        return
                    try:
                        self.send({"op": "ping"})
                        self.collect_acks(
                            deadline=max(_limit(self.settings,
                                                "mh_heartbeat_interval"),
                                         1.0),
                            phase="heartbeat")
                    except (WorkerDied, RuntimeError, OSError) as e:
                        if not self._closed:
                            self.hb_failure = str(e)
                        return
                finally:
                    self._lock.release()

        self._hb_thread = threading.Thread(target=loop, name="mh-heartbeat",
                                           daemon=True)
        self._hb_thread.start()

    def _stop_heartbeat(self) -> None:
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None and t.is_alive() and t is not threading.current_thread():
            # control-plane teardown: the loop exits on _hb_stop within
            # one heartbeat tick and the join is hard-bounded
            t.join(timeout=5)   # gg:ok(interrupts)
        self._hb_thread = None

    # ---- quiesce + rejoin (gang re-formation, cdbgang recreation) ------
    def quiesce(self) -> None:
        """Tear down worker connections but KEEP the listener: a worker
        that wakes from a hang (or is restarted) can reconnect, and the
        session can re-form the gang (docs/ROBUSTNESS.md)."""
        if self._quiesced or self._closed:
            return
        self._quiesced = True
        self._stop_heartbeat()
        self._stop_accept_loop()   # a partial gang keeps one running
        with self._lock:
            for p in self._workers:
                p.close()
            self._workers = []
        self._rejoin_stop = threading.Event()
        self._rejoin_ready.clear()

        def accept_loop():
            while not self._rejoin_stop.is_set():
                try:
                    self._srv.settimeout(0.2)
                    # rejoin accept thread (quiesce keeps the listener
                    # open for redialing workers): not a statement thread
                    conn, _ = self._srv.accept()   # gg:ok(interrupts)
                except (socket.timeout, TimeoutError):
                    continue
                except OSError:
                    return           # listener closed: channel shut down
                try:
                    dl = Deadline(5.0)
                    peer = self._handshake(conn, dl)
                except Exception:
                    try:
                        conn.close()
                    except Exception:
                        pass
                    continue
                with self._lock:
                    old = self._pending.pop(peer.process_id, None)
                    if old is not None:
                        old.close()   # a worker re-dialing replaces itself
                    self._pending[peer.process_id] = peer
                    # ready = the missing complement has reconnected (the
                    # whole gang when quiesced; the dead worker when a
                    # partial N-1 gang is serving)
                    if len(self._pending) >= self._expected - len(self._workers):
                        self._rejoin_ready.set()

        self._rejoin_thread = threading.Thread(
            target=accept_loop, name="mh-rejoin-accept", daemon=True)
        self._rejoin_thread.start()

    def rejoin_ready(self) -> bool:
        """True once every MISSING worker has reconnected and said hello
        (the full gang after a quiesce; the dead member while an N-1
        partial gang serves)."""
        return self._rejoin_ready.is_set()

    # ---- partial gangs (N-1 mesh re-formation) -------------------------
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def active_ids(self) -> list:
        with self._lock:
            return sorted((p.process_id for p in self._workers),
                          key=lambda x: (x is None, x))

    def is_partial(self) -> bool:
        with self._lock:
            return len(self._workers) < self._expected

    @property
    def expected_workers(self) -> int:
        return self._expected

    def adopt_pending(self) -> int:
        """Fold every reconnected worker into the serving gang — the
        re-bind step of mesh re-formation. Works from quiesced (adopt the
        survivors into an N-1 gang) and from a partial gang (the dead
        member rejoined: restore full strength). The rejoin accept loop
        stays up while the gang is still short so a late rejoiner is never
        locked out; it stops once the gang is whole. Returns the number of
        workers adopted."""
        with self._lock:
            adopted = 0
            for pid in sorted(self._pending, key=lambda x: (x is None, x)):
                peer = self._pending[pid]
                stale = [p for p in self._workers if p.process_id == pid]
                for p in stale:
                    p.close()
                    self._workers.remove(p)
                self._workers.append(peer)
                adopted += 1
            self._pending = {}
            self._workers.sort(key=lambda p: (p.process_id is None,
                                              p.process_id))
            self._quiesced = False
            self.hb_failure = None
            self._rejoin_ready.clear()
            full = len(self._workers) >= self._expected
        if full:
            self._stop_accept_loop()
        return adopted

    def _stop_accept_loop(self) -> None:
        self._rejoin_stop.set()
        t = self._rejoin_thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            # gang-reformation teardown: the acceptor exits on
            # _rejoin_stop within one accept timeout, join hard-bounded
            t.join(timeout=2)   # gg:ok(interrupts)
        self._rejoin_thread = None

    def adopt_rejoined(self) -> None:
        """Swap the reconnected gang in; the caller then replays the
        sync handshake before clearing degraded mode."""
        self._stop_accept_loop()
        with self._lock:
            for p in self._workers:
                p.close()   # a full swap replaces any partial remnants
            self._workers = [self._pending[k]
                             for k in sorted(self._pending,
                                             key=lambda x: (x is None, x))]
            self._pending = {}
            self._quiesced = False
            self.hb_failure = None
            self._rejoin_ready.clear()

    def close(self):
        if self._closed:
            return
        self._stop_heartbeat()
        self._rejoin_stop.set()
        try:
            # best-effort clean stop so workers exit instead of rejoining
            with self._lock:
                if not self.hb_failure:
                    self.send({"op": "stop"})
        except Exception:
            pass
        self._closed = True
        with self._lock:
            for p in self._workers:
                p.close()
            self._workers = []
            for p in self._pending.values():
                p.close()
            self._pending = {}
        self._srv.close()


class WorkerChannel:
    def __init__(self, host: str, port: int, process_id: int | None = None,
                 settings=None, connect_deadline: float | None = None):
        self.host = host
        self.port = port
        self.process_id = process_id
        self.settings = settings
        self._connect_deadline = connect_deadline
        self._dial(rejoin=False)

    @staticmethod
    def parse_addrs(spec: str) -> list:
        """'host:port,host:port' -> [(host, port)], order preserved;
        malformed entries are dropped (a worker must never crash on a
        broadcast GUC value)."""
        out = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            host, _, port_s = part.rpartition(":")
            try:
                hp = (host or "127.0.0.1", int(port_s))
            except ValueError:
                continue
            if hp not in out:
                out.append(hp)
        return out

    def candidate_addrs(self) -> list:
        """Ordered redial candidates: the CURRENT coordinator address
        first (gang re-formation rejoins the same kept listener), then
        every mh_coordinator_addrs entry in its declared order — the
        standby listener(s) a promoted coordinator answers on."""
        cands = [(self.host, self.port)]
        spec = (getattr(self.settings, "mh_coordinator_addrs", "")
                if self.settings is not None else "")
        for hp in self.parse_addrs(spec):
            if hp not in cands:
                cands.append(hp)
        return cands

    def _dial(self, rejoin: bool, host: str | None = None,
              port: int | None = None, limit: float | None = None) -> None:
        host = self.host if host is None else host
        port = self.port if port is None else port
        if limit is None:
            limit = _limit(self.settings,
                           self._connect_deadline
                           if self._connect_deadline is not None
                           else "mh_connect_deadline")
        # at STARTUP a refused connect means the coordinator's listener is
        # not up yet — retry. At REJOIN the listener predates us (quiesce
        # keeps it open), so refused means the coordinator process itself
        # is gone: give up immediately instead of burning the deadline.
        retryable = ((TimeoutError, socket.timeout, InterruptedError,
                      ConnectionResetError, ConnectionAbortedError)
                     if rejoin else TRANSIENT_ERRORS)
        pol = RetryPolicy(deadline_s=limit, base_s=0.1, cap_s=2.0,
                          retryable=retryable)
        try:
            self._sock = pol.call(lambda: socket.create_connection(
                (host, port), timeout=min(10.0, limit)))
        except OSError as e:
            raise ConnectionError(
                f"cannot reach coordinator at {host}:{port} within "
                f"{limit:.0f}s mh_connect_deadline: {e}")
        self._sock.settimeout(None)
        self._f = self._sock.makefile("rwb")
        self._f.write((json.dumps(
            {"op": "hello", "process_id": self.process_id,
             "rejoin": rejoin}) + "\n").encode())
        self._f.flush()

    def recv(self, idle_timeout: float | None = None) -> dict:
        """Next control frame. EOF and silence are NOT a clean stop: they
        raise CoordinatorLost so the worker can log the loss and attempt a
        rejoin, instead of exiting as if shut down."""
        try:
            self._sock.settimeout(idle_timeout)
            line = self._f.readline()
        except (socket.timeout, TimeoutError):
            raise CoordinatorLost(
                f"no control traffic for {idle_timeout:.0f}s "
                "(heartbeats stopped — coordinator hung or partitioned)")
        except OSError as e:
            raise CoordinatorLost(f"control connection error: {e}")
        if not line:
            raise CoordinatorLost(
                "control connection closed without a stop frame — the "
                "coordinator died or re-formed the gang")
        try:
            return json.loads(line)
        except ValueError as e:
            raise CoordinatorLost(f"garbled control frame: {e}")

    def ack(self, ok: bool = True, error: str | None = None, **extra):
        payload = {"ok": ok, "error": error}
        payload.update(extra)
        self._f.write((json.dumps(payload) + "\n").encode())
        self._f.flush()

    def reconnect(self) -> bool:
        """Bounded re-dial + hello after a lost coordinator connection
        (the gang-rejoin dial), walking the ordered candidate list: the
        current address first (gang re-formation), then each
        mh_coordinator_addrs entry — landing on a DIFFERENT address is a
        re-home to a promoted standby (mh_rehome_total). False once
        every candidate has burned its share of mh_connect_deadline:
        all addresses dead."""
        self.close()
        cands = self.candidate_addrs()
        limit = _limit(self.settings,
                       self._connect_deadline
                       if self._connect_deadline is not None
                       else "mh_connect_deadline")
        per = max(0.5, limit / max(1, len(cands)))
        for host, port in cands:
            try:
                self._dial(rejoin=True, host=host, port=port, limit=per)
            except (ConnectionError, OSError):
                continue
            if (host, port) != (self.host, self.port):
                counters.inc("mh_rehome_total")
                print(f"worker {self.process_id}: re-homed to promoted "
                      f"coordinator {host}:{port}",
                      file=sys.stderr, flush=True)
                self.host, self.port = host, port
            return True
        return False

    def close(self):
        for obj in (getattr(self, "_f", None), getattr(self, "_sock", None)):
            try:
                obj.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# worker loop
# ---------------------------------------------------------------------------

def worker_loop(db) -> None:
    """Follow the coordinator: execute each statement's DEVICE work in
    lockstep (the exec_mpp_query role, postgres.c:1057). Writes are the
    coordinator's job; the shared-directory refresh picks them up.

    Mesh statements arrive as a TWO-PHASE exchange: the worker first
    refreshes, re-plans, and acks readiness — verifying the coordinator's
    plan hash when one is attached, so a nondeterminism bug fails the
    statement on the channel instead of desyncing the collectives — and
    only enters the mesh program after an explicit 'go'. The readiness
    ack doubles as the liveness probe that keeps a dead worker from
    hanging the coordinator inside a collective.

    A lost coordinator connection (EOF without a stop frame, or silence
    past mh_ack_deadline while heartbeats are on) is LOGGED and answered
    with one bounded reconnect attempt — the worker half of gang
    recovery; only a clean 'stop' frame is a silent exit."""
    ch = db.multihost.channel
    ch.settings = db.settings
    while True:
        try:
            if not _serve_one(db, ch):
                return
        except (CoordinatorLost, OSError) as e:
            # a crashed coordinator must be VISIBLE, not a silent exit
            print(f"worker {db.multihost.process_id}: coordinator "
                  f"connection lost: {e}; attempting rejoin",
                  file=sys.stderr, flush=True)
            if not ch.reconnect():
                addrs = ", ".join(f"{h}:{p}"
                                  for h, p in ch.candidate_addrs())
                print(f"worker {db.multihost.process_id}: no coordinator "
                      f"reachable at [{addrs}] within "
                      "mh_connect_deadline — exiting",
                      file=sys.stderr, flush=True)
                return
            print(f"worker {db.multihost.process_id}: reconnected; "
                  "awaiting gang re-sync", file=sys.stderr, flush=True)


def _worker_idle_timeout(db) -> float | None:
    """With heartbeats on, total silence past the completion-ack bound
    means the coordinator is gone (pings would have arrived); without
    heartbeats the worker waits indefinitely for work."""
    if db.settings.mh_heartbeat_interval <= 0:
        return None
    return max(float(db.settings.mh_ack_deadline),
               10.0 * float(db.settings.mh_heartbeat_interval))


def _hbm_watermark(db) -> int:
    """Peak device bytes this process has observed, shipped in completion
    acks so the coordinator can drive ONE cluster-wide runaway verdict
    from the gang's aggregated watermarks. The mh_hbm_watermark fault
    point ('skip' type) substitutes a synthetic over-limit value so the
    gang test forces a verdict without a real multi-GB allocation."""
    if faults.check("mh_hbm_watermark"):
        return 1 << 40
    from greengage_tpu.runtime import memaccount

    st = memaccount.device_memory_stats()
    if st is None:
        return 0
    return int(st.get("peak_bytes_in_use", 0) or 0)


def _serve_one(db, ch) -> bool:
    """Handle one control frame; False = clean stop."""
    # worker process main loop: no statement registry on this side (the
    # coordinator cancels by quiescing/stopping the exchange)
    msg = ch.recv(_worker_idle_timeout(db))   # gg:ok(interrupts)
    op = msg.get("op")
    if op == "stop":
        return False
    if op == "ping":
        faults.check("heartbeat")   # sleep/suspend = hung worker analog
        ch.ack(True)
        return True
    if op == "fault":
        # gp_inject_fault dispatched to segments: arm/reset a named fault
        # point in THIS process so tests can force hangs deterministically
        try:
            if msg.get("reset"):
                faults.reset(msg.get("name"))
            else:
                faults.inject(msg["name"], msg.get("type", "error"),
                              segment=msg.get("segment"),
                              occurrences=int(msg.get("occurrences", 1)),
                              sleep_s=float(msg.get("sleep_s", 0.1)),
                              start_after=int(msg.get("start_after", 0)))
            ch.ack(True)
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    if op == "sync":
        # gang-rejoin replay: adopt the coordinator's committed catalog
        # and live settings, then report the topology version we see —
        # the coordinator verifies it against its own (FTS promotions
        # during the degraded window must be visible here)
        try:
            db.refresh()
            for k, v in (msg.get("settings") or {}).items():
                if not k.startswith("_"):
                    db.settings.set(k, v)
            ch.ack(True, topology_version=db.catalog.segments.version)
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    if op == "set":
        try:
            # mesh-steering settings stay in lockstep (spill passes,
            # retry tiers) — applied singly, never as batch re-parse
            db.settings.set(msg["name"], msg["value"])
            ch.ack(True)
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    if op == "exec":
        # gpssh role: run a shell command on every worker host over
        # the control plane; the ack's error slot carries the output

        try:
            out = subprocess.run(
                msg["cmd"], shell=True, capture_output=True,
                timeout=float(msg.get("timeout", 60)))
            ch.ack(out.returncode == 0,
                   (out.stdout + out.stderr).decode(
                       errors="replace")[-2000:])
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    if op == "runaway":
        # cluster-wide runaway verdict: the coordinator aggregated the
        # gang's HBM watermarks past the red zone and broadcast the kill.
        # Cancel whatever runs here through the interrupt registry (same
        # flag the single-host cleaner trips) and count it.
        counters.inc("statements_cancelled_runaway")
        interrupt.REGISTRY.cancel_all(
            "runaway", msg.get("reason")
            or "canceled by the runaway cleaner (cluster verdict)")
        ch.ack(True)
        return True
    if op == "sql_batch":
        # one batched serving window (exec/batchserve.py): same two-phase
        # contract as a classic statement — verify the window's plan hash
        # (every member shares the shape; the first member's hash stands
        # for the window), ack readiness, park for 'go', then run the
        # batched program CONCURRENTLY with the coordinator's dispatch
        faults.check("worker_ack")
        sqls = msg.get("sqls") or []
        try:
            db.refresh()
            # adopt the coordinator's applied calibration BEFORE planning:
            # plan hashes must match, and est_rows feed the plan text
            db.feedback.adopt(msg.get("fb"))
            want = msg.get("plan_hash")
            if want and sqls:
                got = db.plan_hash(sqls[0])
                if got != want:
                    raise RuntimeError(
                        f"plan-hash mismatch: coordinator {want} vs "
                        f"worker {got} — nondeterministic planning would "
                        "desync the batched collectives")
        except FaultError:
            raise
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
            return True
        ch.ack(True)
        nxt = ch.recv(_worker_idle_timeout(db))   # gg:ok(interrupts)
        if nxt.get("op") == "stop":
            return False
        if nxt.get("op") != "go":
            return True        # coordinator skipped the window
        from greengage_tpu.runtime.trace import TRACES

        tr, _ = TRACES.enter(
            None, sqls[0] if sqls else "batch",
            enabled=bool(getattr(db.settings, "trace_enabled", True)))
        try:
            db.worker_sql_batch(sqls)
        except Exception as e:
            # incl. BatchFallback: the coordinator maps a not-ok
            # completion ack to its own fallback, and the members'
            # serial re-runs arrive as classic sql ops
            TRACES.exit(tr)
            ch.ack(False, f"{type(e).__name__}: {e}")
            return True
        spans = tr.export(limit=512) if tr is not None else None
        TRACES.exit(tr)
        faults.check("worker_ack")
        ch.ack(True, spans=spans, process_id=db.multihost.process_id,
               hbm=_hbm_watermark(db))
        return True
    if op != "sql":
        return True
    # phase 1: refresh + plan + verify, ack readiness. A FaultError from
    # the worker_ack point propagates (= injected worker death at the ack
    # site); its sleep/suspend types model the hung-not-dead worker.
    faults.check("worker_ack")
    try:
        db.refresh()
        # adopt the coordinator's applied calibration BEFORE the plan-hash
        # check: corrected est_rows appear in describe(), so both sides
        # must plan from identical scales (JSON floats round-trip exactly)
        db.feedback.adopt(msg.get("fb"))
        want = msg.get("plan_hash")
        if want:
            # plan_hash raises if this worker cannot re-plan — that
            # too must fail the readiness ack, not surface later
            # inside a half-entered collective
            got = db.plan_hash(msg["sql"])
            if got != want:
                raise RuntimeError(
                    f"plan-hash mismatch: coordinator {want} vs "
                    f"worker {got} — nondeterministic planning would "
                    "desync the mesh collectives")
        ch.ack(True)
    except FaultError:
        raise
    except Exception as e:
        ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    nxt = ch.recv(_worker_idle_timeout(db))   # gg:ok(interrupts)
    if nxt.get("op") == "stop":
        return False
    if nxt.get("op") != "go":
        return True            # coordinator skipped the statement
    # phase 2: the mesh program (collectives rendezvous with the
    # coordinator's concurrent execution). The worker traces its side
    # (runtime/trace.py) and ships the span list in the completion ack so
    # the coordinator can graft it under its dispatch span — one trace
    # for the whole cluster's statement.
    from greengage_tpu.runtime.trace import TRACES

    tr, _ = TRACES.enter(
        None, msg["sql"],
        enabled=bool(getattr(db.settings, "trace_enabled", True)))
    # record the spill pass/bucket schedule this side actually runs: it
    # ships in the completion ack and the coordinator asserts it matches
    # its own (exec/session._mh_spill_parity — lockstep verification)
    db.executor.begin_spill_schedule()
    try:
        db.worker_sql(msg["sql"])
    except Exception as e:
        TRACES.exit(tr)
        ch.ack(False, f"{type(e).__name__}: {e}")
        return True
    # bounded export: one control-channel line carries the ack, and a
    # pathological pass count must not balloon it
    spans = tr.export(limit=512) if tr is not None else None
    TRACES.exit(tr)
    faults.check("worker_ack")
    ch.ack(True, spans=spans, process_id=db.multihost.process_id,
           spill_schedule=db.executor.collect_spill_schedule(),
           hbm=_hbm_watermark(db))
    return True
