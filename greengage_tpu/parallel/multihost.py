"""Multi-host runtime — the interconnect/dispatch fabric across hosts.

Reference parity: the reference spans hosts with libpq dispatch (control
plane) + UDPIFC/ic-proxy (data plane, src/backend/cdb/motion/ic_udpifc.c,
README.ic-proxy.md). The TPU-native translation:

  data plane   = XLA collectives over the GLOBAL device mesh
                 (jax.distributed: every process contributes its local
                 chips; all_to_all/all_gather ride ICI/DCN)
  control plane = a slim TCP statement channel (the libpq 'M'-message
                 role): the coordinator broadcasts each SQL statement,
                 every process plans/compiles the SAME program from the
                 shared catalog (multi-controller SPMD), workers stage
                 only their LOCAL segments' storage, and the jitted
                 program's collectives synchronize execution.

Lockstep invariants (why this is deterministic):
  * all processes see the same cluster directory (shared/replicated fs);
    workers refresh catalog+manifest before each statement,
  * binder/planner are deterministic, so every process compiles an
    identical HLO and the collectives rendezvous,
  * overflow flags and metrics are device-reduced (pmax/psum over the
    mesh) and replicated, so every process takes the same capacity-retry
    decision without any extra control traffic,
  * only the coordinator performs writes (manifest/catalog/dictionaries);
    workers run the device part of DML's internal scans and skip the
    publish.
"""

from __future__ import annotations

import json
import socket
import threading
from dataclasses import dataclass


@dataclass
class MultihostRuntime:
    process_id: int
    num_processes: int
    channel: object = None            # CoordinatorChannel | WorkerChannel
    local_segments: tuple = ()        # mesh positions of this process's devices

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def init_multihost(coordinator: str, num_processes: int, process_id: int,
                   control_port: int) -> MultihostRuntime:
    """Join the distributed JAX runtime and the control channel. Must run
    BEFORE any devices are used."""
    import jax

    jax.distributed.initialize(coordinator, num_processes=num_processes,
                               process_id=process_id)
    host = coordinator.rsplit(":", 1)[0]
    if process_id == 0:
        ch = CoordinatorChannel(control_port, num_processes - 1)
    else:
        ch = WorkerChannel(host, control_port)
    return MultihostRuntime(process_id, num_processes, ch)


def local_segment_positions() -> tuple:
    """Mesh positions (= segment ids) of this process's devices, assuming
    the mesh enumerates jax.devices() in order (parallel/mesh.py does)."""
    import jax

    all_devs = {id(d): i for i, d in enumerate(jax.devices())}
    return tuple(sorted(all_devs[id(d)] for d in jax.local_devices()))


# ---------------------------------------------------------------------------
# control channel: line-JSON over TCP
# ---------------------------------------------------------------------------

class WorkerDied(ConnectionError):
    """A worker's control connection is gone (process death / network
    partition): the statement channel cannot reach the full gang."""


class CoordinatorChannel:
    """Accepts every worker once, then broadcasts statements and collects
    acks (the CdbDispatchCommand/checkDispatchResult roles)."""

    def __init__(self, port: int, expected_workers: int):
        self._lock = threading.Lock()
        self._workers: list = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(expected_workers)
        for _ in range(expected_workers):
            conn, _ = self._srv.accept()
            self._workers.append(conn.makefile("rwb"))

    def send(self, msg: dict) -> None:
        line = (json.dumps(msg) + "\n").encode()
        self._lock.acquire()
        try:
            for w in self._workers:
                w.write(line)
                w.flush()
        except OSError as e:
            self._lock.release()
            raise WorkerDied(f"worker connection lost on send: {e}")
        except BaseException:
            self._lock.release()
            raise

    def post(self, msg: dict) -> None:
        """Send a message that expects NO ack (go/skip control frames)."""
        self.send(msg)
        self._lock.release()

    def collect_acks(self) -> list[dict]:
        try:
            acks = []
            for w in self._workers:
                line = w.readline()
                if not line:
                    raise WorkerDied("worker connection closed (EOF) — "
                                     "the process died mid-statement")
                acks.append(json.loads(line))
        except (OSError, ValueError) as e:
            raise WorkerDied(f"worker connection lost: {e}")
        finally:
            self._lock.release()
        errs = [a for a in acks if not a.get("ok")]
        if errs:
            raise RuntimeError(f"worker error: {errs[0].get('error')}")
        return acks

    def collect_raw(self) -> list[dict]:
        """Collect one ack per worker WITHOUT raising on not-ok — for
        ops whose ack 'error' slot carries payload (exec/gpssh output)."""
        try:
            acks = []
            for w in self._workers:
                line = w.readline()
                if not line:
                    raise WorkerDied("worker connection closed (EOF)")
                acks.append(json.loads(line))
            return acks
        except (OSError, ValueError) as e:
            raise WorkerDied(f"worker connection lost: {e}")
        finally:
            self._lock.release()

    def broadcast(self, msg: dict) -> list[dict]:
        """Send to all workers and wait for every ack."""
        self.send(msg)
        return self.collect_acks()

    def close(self):
        try:
            self.send({"op": "stop"})
            self._lock.release()
        except Exception:
            pass
        for w in self._workers:
            try:
                w.close()
            except Exception:
                pass
        self._srv.close()


class WorkerChannel:
    def __init__(self, host: str, port: int, retries: int = 100):
        import time

        last = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection((host, port), timeout=30)
                break
            except OSError as e:
                last = e
                time.sleep(0.1)
        else:
            raise ConnectionError(f"cannot reach coordinator: {last}")
        self._f = self._sock.makefile("rwb")

    def recv(self) -> dict:
        line = self._f.readline()
        if not line:
            return {"op": "stop"}
        return json.loads(line)

    def ack(self, ok: bool = True, error: str | None = None):
        self._f.write((json.dumps({"ok": ok, "error": error}) + "\n").encode())
        self._f.flush()


# ---------------------------------------------------------------------------
# worker loop
# ---------------------------------------------------------------------------

def worker_loop(db) -> None:
    """Follow the coordinator: execute each statement's DEVICE work in
    lockstep (the exec_mpp_query role, postgres.c:1057). Writes are the
    coordinator's job; the shared-directory refresh picks them up.

    Mesh statements arrive as a TWO-PHASE exchange: the worker first
    refreshes, re-plans, and acks readiness — verifying the coordinator's
    plan hash when one is attached, so a nondeterminism bug fails the
    statement on the channel instead of desyncing the collectives — and
    only enters the mesh program after an explicit 'go'. The readiness
    ack doubles as the liveness probe that keeps a dead worker from
    hanging the coordinator inside a collective."""
    ch = db.multihost.channel
    while True:
        msg = ch.recv()
        if msg.get("op") == "stop":
            break
        if msg.get("op") == "set":
            try:
                # mesh-steering settings stay in lockstep (spill passes,
                # retry tiers) — applied singly, never as batch re-parse
                db.settings.set(msg["name"], msg["value"])
                ch.ack(True)
            except Exception as e:
                ch.ack(False, f"{type(e).__name__}: {e}")
            continue
        if msg.get("op") == "exec":
            # gpssh role: run a shell command on every worker host over
            # the control plane; the ack's error slot carries the output
            import subprocess

            try:
                out = subprocess.run(
                    msg["cmd"], shell=True, capture_output=True,
                    timeout=float(msg.get("timeout", 60)))
                ch.ack(out.returncode == 0,
                       (out.stdout + out.stderr).decode(
                           errors="replace")[-2000:])
            except Exception as e:
                ch.ack(False, f"{type(e).__name__}: {e}")
            continue
        if msg.get("op") != "sql":
            continue
        # phase 1: refresh + plan + verify, ack readiness
        try:
            db.refresh()
            want = msg.get("plan_hash")
            if want:
                # plan_hash raises if this worker cannot re-plan — that
                # too must fail the readiness ack, not surface later
                # inside a half-entered collective
                got = db.plan_hash(msg["sql"])
                if got != want:
                    raise RuntimeError(
                        f"plan-hash mismatch: coordinator {want} vs "
                        f"worker {got} — nondeterministic planning would "
                        "desync the mesh collectives")
            ch.ack(True)
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
            continue
        nxt = ch.recv()
        if nxt.get("op") == "stop":
            break
        if nxt.get("op") != "go":
            continue               # coordinator skipped the statement
        # phase 2: the mesh program (collectives rendezvous with the
        # coordinator's concurrent execution)
        try:
            db.worker_sql(msg["sql"])
            ch.ack(True)
        except Exception as e:
            ch.ack(False, f"{type(e).__name__}: {e}")
