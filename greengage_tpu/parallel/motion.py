"""Motion — exchange operators as XLA collectives (the data plane).

Reference parity (src/backend/cdb/motion/, nodeMotion.c, cdbmutate.c:396):

  Redistribute Motion  -> lax.all_to_all over the "seg" axis
  Broadcast Motion     -> lax.all_gather (tiled)
  Gather Motion        -> device->host gather outside the compiled program

Where the reference streams tuples over reliable-UDP with its own flow
control (ic_udpifc.c), we exchange fixed-capacity row buckets over ICI and
let XLA schedule/overlap the collective. Static shapes demand a per-
destination capacity; skew beyond it sets an ``overflow`` flag and the
executor re-runs at a bigger capacity tier (the flow-control analog).

These functions run INSIDE shard_map: every array argument is the local
segment's shard.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from greengage_tpu.parallel.mesh import SEG_AXIS


def _bucketize(arrs: dict, present, dest, nseg: int, capacity: int):
    """Pack rows into per-destination buckets [nseg * capacity].

    Rows are ranked within their destination via a stable sort by dest;
    bucket index = dest * capacity + rank. Returns (buckets dict,
    present_buckets, overflow flag).
    """
    n = present.shape[0]
    dest = jnp.where(present, dest, nseg)  # dead rows -> overflow bucket
    counts = jnp.zeros((nseg + 1,), dtype=jnp.int32).at[dest].add(1)
    overflow = jnp.any(counts[:nseg] > capacity)
    start = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    dsorted = dest[order]
    rank = jnp.arange(n, dtype=jnp.int32) - start[dsorted]
    # clamp ranks so skewed rows drop instead of corrupting other buckets
    pos = jnp.where(
        (dsorted < nseg) & (rank < capacity),
        dsorted * capacity + rank,
        nseg * capacity,
    )
    size = nseg * capacity
    out = {}
    for name, a in arrs.items():
        buf = jnp.zeros((size + 1,) + a.shape[1:], dtype=a.dtype)
        out[name] = buf.at[pos].set(a[order])[:size]
    pbuf = jnp.zeros((size + 1,), dtype=bool).at[pos].set(dsorted < nseg)[:size]
    return out, pbuf, overflow


def redistribute(arrs: dict, present, dest, nseg: int, capacity: int):
    """All-to-all exchange by per-row destination segment.

    -> (received arrs [nseg*capacity], received present, overflow scalar).
    The received layout: chunk j holds rows sent by segment j.
    """
    buckets, pbuf, overflow = _bucketize(arrs, present, dest, nseg, capacity)
    recv = {
        name: lax.all_to_all(a, SEG_AXIS, split_axis=0, concat_axis=0, tiled=True)
        for name, a in buckets.items()
    }
    precv = lax.all_to_all(pbuf, SEG_AXIS, split_axis=0, concat_axis=0, tiled=True)
    # surface every segment's overflow everywhere (dispatcher error check)
    overflow = lax.pmax(overflow.astype(jnp.int32), SEG_AXIS) > 0
    return recv, precv, overflow


def broadcast(arrs: dict, present):
    """Broadcast Motion: every segment receives every row (tiled all_gather)."""
    recv = {n: lax.all_gather(a, SEG_AXIS, tiled=True) for n, a in arrs.items()}
    precv = lax.all_gather(present, SEG_AXIS, tiled=True)
    return recv, precv


def my_segment():
    return lax.axis_index(SEG_AXIS)
