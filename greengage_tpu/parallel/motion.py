"""Motion — exchange operators as XLA collectives (the data plane).

Reference parity (src/backend/cdb/motion/, nodeMotion.c, cdbmutate.c:396):

  Redistribute Motion  -> lax.all_to_all over the "seg" axis
  Broadcast Motion     -> lax.all_gather (tiled)
  Gather Motion        -> device->host gather outside the compiled program

Where the reference streams tuples over reliable-UDP with its own flow
control (ic_udpifc.c), we exchange fixed-capacity row buckets over ICI and
let XLA schedule/overlap the collective. Static shapes demand a per-
destination capacity; skew beyond it sets an ``overflow`` flag and the
executor re-runs at a bigger capacity tier (the flow-control analog).

These functions run INSIDE shard_map: every array argument is the local
segment's shard.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from greengage_tpu.parallel.mesh import SEG_AXIS


def _bucketize(arrs: dict, present, dest, nseg: int, capacity: int):
    """Pack rows into per-destination buckets [nseg * capacity].

    Rows are ranked within their destination via a stable sort by dest;
    bucket index = dest * capacity + rank. Returns (buckets dict,
    present_buckets, overflow flag).
    """
    n = present.shape[0]
    dest = jnp.where(present, dest, nseg)  # dead rows -> overflow bucket
    counts = jnp.zeros((nseg + 1,), dtype=jnp.int32).at[dest].add(1)
    overflow = jnp.any(counts[:nseg] > capacity)
    start = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    dsorted = dest[order]
    rank = jnp.arange(n, dtype=jnp.int32) - start[dsorted]
    # clamp ranks so skewed rows drop instead of corrupting other buckets
    pos = jnp.where(
        (dsorted < nseg) & (rank < capacity),
        dsorted * capacity + rank,
        nseg * capacity,
    )
    size = nseg * capacity
    out = {}
    for name, a in arrs.items():
        buf = jnp.zeros((size + 1,) + a.shape[1:], dtype=a.dtype)
        out[name] = buf.at[pos].set(a[order])[:size]
    pbuf = jnp.zeros((size + 1,), dtype=bool).at[pos].set(dsorted < nseg)[:size]
    return out, pbuf, overflow


def _exchange(a, capacity: int, nbuckets: int):
    """One array's all_to_all, optionally split into ``nbuckets``
    independent sub-exchanges over capacity/nbuckets-row slices.

    The split is row-order IDENTICAL to the monolithic exchange: bucket j
    carries rows [j*sub, (j+1)*sub) of every destination's slot range, and
    the stack/reshape below restores received position
    [src * capacity + j * sub + r]. Its point is the device timeline —
    XLA schedules the j+1 exchange's sends while the j exchange's receives
    are still draining into dependents, extending the host-side pipelined
    motion (exec/motionpipe.py) past the host/ICI boundary.
    """
    if nbuckets <= 1:
        return lax.all_to_all(a, SEG_AXIS, split_axis=0, concat_axis=0,
                              tiled=True)
    nseg = a.shape[0] // capacity
    sub = capacity // nbuckets
    rest = a.shape[1:]
    parts = a.reshape((nseg, nbuckets, sub) + rest)
    outs = []
    for j in range(nbuckets):
        r = lax.all_to_all(
            parts[:, j].reshape((nseg * sub,) + rest),
            SEG_AXIS, split_axis=0, concat_axis=0, tiled=True)
        outs.append(r.reshape((nseg, sub) + rest))
    return jnp.stack(outs, axis=1).reshape((nseg * capacity,) + rest)


def redistribute(arrs: dict, present, dest, nseg: int, capacity: int,
                 nbuckets: int = 1):
    """All-to-all exchange by per-row destination segment.

    -> (received arrs [nseg*capacity], received present, overflow scalar).
    The received layout: chunk j holds rows sent by segment j.
    ``nbuckets > 1`` (motion_pipeline_buckets) splits the exchange into
    that many sub-exchanges — identical rows, pipelined transfers.
    """
    if nbuckets > 1 and capacity % nbuckets:
        nbuckets = 1               # guard: only even splits preserve slots
    buckets, pbuf, overflow = _bucketize(arrs, present, dest, nseg, capacity)
    recv = {
        name: _exchange(a, capacity, nbuckets)
        for name, a in buckets.items()
    }
    precv = _exchange(pbuf, capacity, nbuckets)
    # surface every segment's overflow everywhere (dispatcher error check)
    overflow = lax.pmax(overflow.astype(jnp.int32), SEG_AXIS) > 0
    return recv, precv, overflow


def broadcast(arrs: dict, present):
    """Broadcast Motion: every segment receives every row (tiled all_gather)."""
    recv = {n: lax.all_gather(a, SEG_AXIS, tiled=True) for n, a in arrs.items()}
    precv = lax.all_gather(present, SEG_AXIS, tiled=True)
    return recv, precv


def my_segment():
    return lax.axis_index(SEG_AXIS)
