"""Device mesh management: segments == chips.

Reference parity: gp_segment_configuration maps content ids to host:port
processes; here content ids map to devices of a 1-D ``jax.sharding.Mesh``
over axis "seg". Multi-host scaling swaps the device list for a global one
(jax.distributed) without touching the motion layer — collectives ride ICI
within a pod and DCN across pods, replacing the reference's UDPIFC/TCP
interconnect choice (src/backend/cdb/motion/ic_udpifc.c).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SEG_AXIS = "seg"


def make_mesh(numsegments: int, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < numsegments:
        raise ValueError(
            f"cluster width {numsegments} exceeds {len(devs)} visible devices"
        )
    import numpy as np

    return Mesh(np.array(devs[:numsegments]), (SEG_AXIS,))


def seg_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over segments (leading axis)."""
    return NamedSharding(mesh, PartitionSpec(SEG_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
