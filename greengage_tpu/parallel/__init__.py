from greengage_tpu.parallel.mesh import SEG_AXIS, make_mesh  # noqa: F401
