"""gpmapreduce analog: YAML-defined MAP/REDUCE jobs compiled onto the
engine (reference: /root/reference/gpcontrib/gpmapreduce/ — YAML spec
with DEFINE INPUT/MAP/REDUCE + EXECUTE RUN, mappers in pl/python
yielding [key, value] rows, builtin reducers SUM/COUNT/MIN/MAX/AVG/
IDENTITY).

TPU-first translation: the REDUCE stage is where the data is big and it
compiles to a distributed GROUP BY through the ordinary planner (dense /
sort / fused-pallas aggregation, spill, multihost — everything applies).
MAP functions are arbitrary Python by spec, so they run on the host over
the source's columns (the reference likewise runs mappers in per-segment
interpreters, not in the scan kernel); mapped rows bulk-load into an
ephemeral table DISTRIBUTED BY (key), which is exactly the motion the
reference's redistribute-before-reduce performs.

Supported YAML (the reference's demo surface):
  DEFINE:
    - INPUT:  NAME + one of TABLE | QUERY | FILE (server-local paths)
    - MAP:    NAME, FUNCTION (python), PARAMETERS, RETURNS
  EXECUTE:
    - RUN:    SOURCE, MAP (optional), REDUCE (builtin), TARGET (optional
              output table; default prints rows)
Perl mappers and custom TRANSITION reducers are rejected explicitly.
"""

from __future__ import annotations

import numpy as np

BUILTIN_REDUCERS = {
    "SUM": "sum", "COUNT": "count", "MIN": "min", "MAX": "max",
    "AVG": "avg", "IDENTITY": None,
}


class MapReduceError(ValueError):
    pass


def _parse(yaml_text: str) -> dict:
    import yaml

    doc = yaml.safe_load(yaml_text)
    if not isinstance(doc, dict):
        raise MapReduceError("not a gpmapreduce YAML document")
    inputs: dict[str, dict] = {}
    maps: dict[str, dict] = {}
    for item in doc.get("DEFINE", []) or []:
        if "INPUT" in item:
            spec = item["INPUT"]
            inputs[spec["NAME"]] = spec
        elif "MAP" in item:
            spec = item["MAP"]
            lang = str(spec.get("LANGUAGE", "python")).lower()
            if lang not in ("python",):
                raise MapReduceError(
                    f"MAP language {lang!r} is not supported (python only)")
            maps[spec["NAME"]] = spec
        elif "REDUCE" in item:
            raise MapReduceError(
                "custom TRANSITION reducers are not supported; use the "
                "builtins SUM/COUNT/MIN/MAX/AVG/IDENTITY")
    runs = [r["RUN"] for r in doc.get("EXECUTE", []) or [] if "RUN" in r]
    if not runs:
        raise MapReduceError("EXECUTE contains no RUN")
    return {"inputs": inputs, "maps": maps, "runs": runs}


def _source_rows(db, spec: dict):
    """-> (column names, list of per-column numpy/object arrays)."""
    if "TABLE" in spec:
        r = db.sql(f"select * from {spec['TABLE']}")
        return list(r.columns), [_col(r, c) for c in r._order], r
    if "QUERY" in spec:
        r = db.sql(spec["QUERY"])
        return list(r.columns), [_col(r, c) for c in r._order], r
    if "FILE" in spec:
        lines: list[str] = []
        files = spec["FILE"]
        for path in ([files] if isinstance(files, str) else files):
            # reference format is host:/path; embedded engine reads local
            p = path.split(":", 1)[1] if ":" in path else path
            with open(p) as f:
                lines.extend(ln.rstrip("\n") for ln in f)
        return ["value"], [np.array(lines, dtype=object)], None
    raise MapReduceError("INPUT needs TABLE, QUERY, or FILE")


def _col(r, cid):
    v = r.valids.get(cid)
    a = np.asarray(r.cols[cid])
    if v is not None:
        a = a.astype(object)
        a[~np.asarray(v, bool)] = None
    return a


def _compile_mapper(spec: dict):
    """Reference mapper contract: the FUNCTION body sees its PARAMETERS as
    locals and yields [key, value] lists (a generator body, compiled here
    into a wrapper function)."""
    params = [p.split()[0] for p in
              str(spec.get("PARAMETERS", "value text")).split(",")]
    body = spec["FUNCTION"]
    indented = "\n".join("    " + ln for ln in body.splitlines())
    src = f"def __mapper__({', '.join(params)}):\n{indented}\n"
    ns: dict = {}
    exec(src, {"np": np}, ns)      # job YAML is operator-trusted, like the
    return ns["__mapper__"], params  # reference's pl/python execution


def _returns(spec: dict) -> list[tuple[str, str]]:
    out = []
    for r in spec.get("RETURNS", ["key text", "value bigint"]):
        name, typ = str(r).split(None, 1)
        out.append((name, typ))
    return out


def run_job(db, yaml_text: str, out=print) -> list:
    """Execute every RUN; returns the last run's result rows."""
    job = _parse(yaml_text)
    last = []
    for i, run in enumerate(job["runs"]):
        src = job["inputs"].get(run["SOURCE"])
        if src is None:
            raise MapReduceError(f"unknown SOURCE {run['SOURCE']!r}")
        cols, arrays, _ = _source_rows(db, src)

        if "MAP" in run:
            mspec = job["maps"].get(run["MAP"])
            if mspec is None:
                raise MapReduceError(f"unknown MAP {run['MAP']!r}")
            mapper, params = _compile_mapper(mspec)
            rets = _returns(mspec)
            by_name = dict(zip(cols, arrays))
            try:
                args = [by_name[p] for p in params]
            except KeyError as e:
                raise MapReduceError(
                    f"MAP parameter {e} not found in source columns {cols}")
            n = len(args[0]) if args else 0
            out_rows: list[list] = []
            for j in range(n):
                got = mapper(*[a[j] for a in args])
                if got is None:
                    continue
                out_rows.extend(list(row) for row in got)
        else:
            def _sql_type(a) -> str:
                k = np.asarray(a).dtype.kind
                if k in ("i", "u", "b"):
                    return "bigint"
                if k == "f":
                    return "double precision"
                return "text"

            rets = [(c, _sql_type(a)) for c, a in zip(cols, arrays)]
            out_rows = [list(t) for t in zip(*arrays)] if arrays else []

        reduce_name = str(run.get("REDUCE", "IDENTITY")).upper()
        if reduce_name not in BUILTIN_REDUCERS:
            raise MapReduceError(f"unknown REDUCE {reduce_name!r}")
        agg = BUILTIN_REDUCERS[reduce_name]

        tmp = f"__mr_{i}"
        db.sql(f"drop table if exists {tmp}")
        coldefs = ", ".join(f"{nm} {ty}" for nm, ty in rets)
        db.sql(f"create table {tmp} ({coldefs}) "
               f"distributed by ({rets[0][0]})")
        load_cols = {}
        for k, (nm, ty) in enumerate(rets):
            vals = [r_[k] for r_ in out_rows]
            ty_l = ty.lower()
            if "int" in ty_l:
                load_cols[nm] = np.array(vals, dtype=np.int64)
            elif any(x in ty_l for x in ("float", "double", "real")):
                load_cols[nm] = np.array(vals, dtype=np.float64)
            else:
                load_cols[nm] = [str(v) for v in vals]
        db.load_table(tmp, load_cols)

        key, val = rets[0][0], rets[-1][0]
        if agg is None:
            r = db.sql(f"select * from {tmp}")
        else:
            r = db.sql(f"select {key}, {agg}({val}) as {val} from {tmp} "
                       f"group by {key} order by {key}")
        target = run.get("TARGET")
        if target and agg is not None and len(rets) != 2:
            raise MapReduceError(
                "TARGET with an aggregate reducer needs exactly two "
                "RETURNS columns (key, value)")
        if target:
            tdefs = ", ".join(
                f"{nm} {'bigint' if agg in ('sum', 'count') and nm == val else ty}"
                for nm, ty in rets)
            db.sql(f"drop table if exists {target}")
            db.sql(f"create table {target} ({tdefs}) "
                   f"distributed by ({key})")
            tcols = [key, val] if agg else [nm for nm, _ in rets]
            got = {}
            for cid, nm in zip(r._order, tcols):
                a = np.asarray(r.cols[cid])
                got[nm] = a if a.dtype.kind != "O" else [str(x) for x in a]
            db.load_table(target, got)
        else:
            for row in r.rows():
                out("\t".join(str(x) for x in row))
        last = r.rows()
        db.sql(f"drop table if exists {tmp}")
    return last
