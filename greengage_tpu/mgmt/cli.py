"""gg — the cluster management CLI (gpMgmt/bin analog).

Subcommands mirror the reference's operator tools:

  gg init     -d DIR -n NSEG      gpinitsystem: create a cluster
  gg state    -d DIR [--probe]    gpstate: topology + table inventory
  gg sql      -d DIR "SELECT..."  psql: run statements, print results
  gg expand   -d DIR -n NEWN      gpexpand: widen + redistribute
  gg recover  -d DIR              gprecoverseg: roll back in-doubt 2PC,
                                  rebalance roles to preferred
  gg checkcat -d DIR              gpcheckcat: catalog/storage consistency

Run as: python -m greengage_tpu.mgmt.cli <cmd> ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _open(path, numsegments=None):
    import greengage_tpu

    return greengage_tpu.connect(path=path, numsegments=numsegments)


def cmd_init(args):
    if os.path.exists(os.path.join(args.dir, "catalog.json")):
        print(f"error: cluster already exists at {args.dir}", file=sys.stderr)
        return 1
    import greengage_tpu

    db = greengage_tpu.connect(path=args.dir, numsegments=args.numsegments,
                               mirrors=getattr(args, "mirrors", False))
    print(f"cluster initialized at {args.dir}: {db.numsegments} segments "
          f"on {len(list(db.mesh.devices.flat))} devices"
          + (" with mirrors" if getattr(args, "mirrors", False) else ""))
    return 0


def cmd_replicate(args):
    """gpaddmirrors/manual sync: bring every mirror to the current manifest
    version (normally automatic via the mirror_sync setting)."""
    db = _open(args.dir)
    if db.replicator is None:
        print("cluster has no mirrors (re-init with --mirrors)", file=sys.stderr)
        return 1
    out = db.replicator.sync()
    db.catalog._save()
    for content, v in sorted(out.items()):
        print(f"  content {content}: mirror at version {v}")
    print("replication complete")
    return 0


def cmd_vacuum(args):
    """Reclaim unreferenced segment files (rolled-back/stale writers)."""
    db = _open(args.dir)
    db.store.reap_gc()
    n = db.store.sweep_orphans(args.grace)
    print(f"vacuum: removed {n} orphaned files")
    return 0


def cmd_analyze(args):
    """analyzedb analog: refresh planner statistics."""
    db = _open(args.dir)
    db.sql(f"analyze {args.table}" if args.table else "analyze")
    names = [args.table] if args.table else sorted(db.catalog.tables)
    for n in names:
        ts = db.catalog.get(n).stats
        if ts is not None:
            print(f"  {n}: {ts.rows} rows, {len(ts.columns)} columns analyzed")
    return 0


def cmd_state(args):
    from greengage_tpu.runtime.fts import cluster_state, needs_rebalance

    db = _open(args.dir)
    if args.probe:
        results = db.fts.probe_once()
        print("probe:", json.dumps(results))
    print(f"cluster: {args.dir}  width: {db.numsegments}  "
          f"config version: {db.catalog.segments.version}")
    print(f"{'content':>8} {'role':>5} {'pref':>5} {'status':>7} {'device':>7} {'synced':>7}")
    for row in cluster_state(db.catalog.segments):
        print(f"{row['content']:>8} {row['role']:>5} {row['preferred_role']:>5} "
              f"{row['status']:>7} {str(row['device']):>7} {str(row['synced']):>7}")
    if needs_rebalance(db.catalog.segments):
        print("NOTE: segments are not on their preferred roles (run gg recover)")
    print("tables:")
    for name, schema in sorted(db.catalog.tables.items()):
        counts = db.store.segment_rowcounts(name)
        print(f"  {name}: {sum(counts)} rows over {schema.policy.numsegments} segments "
              f"({schema.policy.describe()})")
    return 0


def cmd_worker(args):
    """Multi-host worker process (the segment-host postmaster role): joins
    the distributed device runtime, then follows the coordinator's
    statement channel in lockstep. Requires the cluster directory on a
    shared filesystem. Start workers first, then the coordinator with
    greengage_tpu.connect(..., multihost=init_multihost(...))."""
    from greengage_tpu.parallel.multihost import init_multihost, worker_loop

    mh = init_multihost(args.coordinator, args.num_processes,
                        args.process_id, args.control_port)
    import greengage_tpu

    # multihost must flow through connect(): the worker guard skips the
    # startup writes (catalog save / manifest recovery) that would race
    # the coordinator's in-flight transactions
    db = greengage_tpu.connect(path=args.dir, multihost=mh)
    print(f"worker {args.process_id}/{args.num_processes} serving "
          f"{len(__import__('jax').local_devices())} local devices", flush=True)
    worker_loop(db)
    return 0


def cmd_server(args):
    """gpstart-style serving mode: listen on a unix socket until killed."""
    from greengage_tpu.runtime.server import SqlServer

    db = _open(args.dir)
    srv = SqlServer(db, args.socket)
    srv.start()
    print(f"serving {args.dir} on {args.socket} (ctrl-c to stop)")
    import signal

    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        srv.stop()
    return 0


def cmd_sql(args):
    if not getattr(args, "socket", None) and not args.dir:
        print("error: sql requires -d DIR (embedded) or -s SOCKET (server)",
              file=sys.stderr)
        return 1
    if getattr(args, "socket", None):
        from greengage_tpu.runtime.server import SqlClient

        c = SqlClient(args.socket)
        resp = c.sql(args.query)
        if resp.get("tag") is not None:
            print(resp["tag"])
        elif resp.get("columns") is not None:
            print("\t".join(resp["columns"]))
            for row in resp["rows"]:
                print("\t".join("" if v is None else str(v) for v in row))
            print(f"({len(resp['rows'])} rows)")
        c.close()
        return 0
    db = _open(args.dir)
    out = db.sql(args.query)
    if isinstance(out, str):
        print(out)
        return 0
    if hasattr(out, "columns"):
        print("\t".join(out.columns))
        for row in out.rows():
            print("\t".join("" if v is None else str(v) for v in row))
        print(f"({len(out)} rows)")
    return 0


def cmd_expand(args):
    db = _open(args.dir)
    moved = db.expand(args.numsegments)
    for t, n in moved.items():
        print(f"  {t}: {n} rows redistributed")
    print(f"cluster expanded to {args.numsegments} segments")
    return 0


def cmd_recover(args):
    from greengage_tpu.catalog.segments import SegmentRole

    db = _open(args.dir)
    rolled = db.store.manifest.recover()
    if rolled:
        print(f"rolled back in-doubt transactions: versions {rolled}")
    swept = db.store.sweep_orphans()
    if swept:
        print(f"reclaimed {swept} orphaned segment files")
    cfg = db.catalog.segments
    # full recovery (gprecoverseg -F / buildMirrorSegments full rebuild):
    # any content served by a promoted mirror gets its original primary
    # tree rebuilt from the mirror's files before roles swap back
    if db.replicator is not None:
        for content in range(cfg.numsegments):
            acting = cfg.acting_primary(content)
            if acting is not None and acting.preferred_role is SegmentRole.MIRROR:
                copied = db.replicator.rebuild(content)
                print(f"  content {content}: rebuilt primary from mirror "
                      f"({copied} files)")
    # rebalance: put segments back on preferred roles (gprecoverseg -r)
    changed = 0
    for e in cfg.entries:
        if e.role is not e.preferred_role:
            # restore the device binding along with the role
            e.role = e.preferred_role
            changed += 1
    if changed:
        for e in cfg.entries:
            if e.content >= 0:
                if e.role is SegmentRole.PRIMARY:
                    e.device_index = e.content
                    e.status = type(e.status)("u")
                else:
                    e.device_index = None
        cfg.version += 1
        print(f"rebalanced {changed} segments to preferred roles")
    db.catalog._save()
    print("recovery complete")
    return 0


def cmd_backup(args):
    """Full backup (gp_pitr/pg_basebackup analog). The manifest snapshot
    names one committed version's files; DELETE/UPDATE/expand may GC old
    files concurrently, so a vanished file triggers a re-snapshot retry
    until one version copies completely."""
    import shutil

    db = _open(args.dir)
    last_err = None
    for _ in range(5):
        snap = db.store.manifest.snapshot()
        try:
            os.makedirs(args.out, exist_ok=True)
            shutil.copy(os.path.join(args.dir, "catalog.json"),
                        os.path.join(args.out, "catalog.json"))
            copied = 0
            for tname, tmeta in snap["tables"].items():
                src_base = os.path.join(args.dir, "data", tname)
                dst_base = os.path.join(args.out, "data", tname)
                if os.path.isdir(src_base):
                    for fn in os.listdir(src_base):
                        if fn.startswith("dict_"):
                            os.makedirs(dst_base, exist_ok=True)
                            shutil.copy(os.path.join(src_base, fn),
                                        os.path.join(dst_base, fn))
                for files in tmeta["segfiles"].values():
                    for rel in files:
                        dst = os.path.join(dst_base, rel)
                        os.makedirs(os.path.dirname(dst), exist_ok=True)
                        shutil.copy(os.path.join(src_base, rel), dst)
                        copied += 1
            # manifest written LAST: its presence marks a complete image
            with open(os.path.join(args.out, "manifest.json"), "w") as f:
                json.dump(snap, f, indent=1)
            print(f"backup of version {snap['version']} written to {args.out} "
                  f"({copied} segment files)")
            return 0
        except FileNotFoundError as e:
            last_err = e   # concurrent writer GC'd a file: retry fresh
    print(f"error: backup could not converge ({last_err})", file=sys.stderr)
    return 1


def cmd_restore(args):
    import shutil

    if os.path.exists(os.path.join(args.dir, "catalog.json")):
        print(f"error: {args.dir} already contains a cluster", file=sys.stderr)
        return 1
    shutil.copytree(args.backup, args.dir, dirs_exist_ok=True)
    db = _open(args.dir)
    print(f"restored cluster at {args.dir}: width {db.numsegments}, "
          f"{len(db.catalog.tables)} tables, manifest version "
          f"{db.store.manifest.snapshot()['version']}")
    return 0


def cmd_checkcat(args):
    db = _open(args.dir)
    problems = []
    snap = db.store.manifest.snapshot()
    # orphaned manifest entries (table gone from catalog)
    for t in snap["tables"]:
        if t not in db.catalog:
            problems.append(f"manifest table {t} missing from catalog")
    for name, schema in db.catalog.tables.items():
        # partitioned parents audit through their child storage tables
        for sname in schema.storage_tables():
            tmeta = snap["tables"].get(sname)
            if tmeta is None:
                continue
            for seg, files in tmeta["segfiles"].items():
                if int(seg) >= schema.policy.numsegments:
                    problems.append(
                        f"{sname}: segfiles on seg {seg} beyond width")
                for rel in files:
                    # resolves through per-content roots (failover aware)
                    p = db.store.seg_file_path(sname, rel)
                    if not os.path.exists(p):
                        problems.append(f"{sname}: missing file {rel}")
            # row counts readable + placement verified per segment
            try:
                total = sum(db.store.segment_rowcounts(sname))
                declared = sum(int(v) for v in tmeta["nrows"].values())
                if total != declared:
                    problems.append(
                        f"{sname}: rowcount mismatch {total} != {declared}")
            except Exception as e:
                problems.append(f"{sname}: unreadable ({e})")
    if problems:
        for p in problems:
            print("PROBLEM:", p)
        return 1
    print("catalog and storage are consistent")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="gg")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("init")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-n", "--numsegments", type=int, default=None)
    p.add_argument("--mirrors", action="store_true")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("replicate")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_replicate)

    p = sub.add_parser("vacuum")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--grace", type=float, default=120.0)
    p.set_defaults(fn=cmd_vacuum)

    p = sub.add_parser("analyze")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-t", "--table", default=None)
    p.set_defaults(fn=cmd_analyze)

    p = sub.add_parser("state")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--probe", action="store_true")
    p.set_defaults(fn=cmd_state)

    p = sub.add_parser("sql")
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.add_argument("query")
    p.set_defaults(fn=cmd_sql)

    p = sub.add_parser("server")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-s", "--socket", required=True)
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("worker")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--coordinator", required=True)   # host:port (jax.distributed)
    p.add_argument("--control-port", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.set_defaults(fn=cmd_worker)

    p = sub.add_parser("expand")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-n", "--numsegments", type=int, required=True)
    p.set_defaults(fn=cmd_expand)

    p = sub.add_parser("recover")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_recover)

    p = sub.add_parser("checkcat")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_checkcat)

    p = sub.add_parser("backup")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-o", "--out", required=True)
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser("restore")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-b", "--backup", required=True)
    p.set_defaults(fn=cmd_restore)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
