"""gg — the cluster management CLI (gpMgmt/bin analog).

Subcommands mirror the reference's operator tools:

  gg init     -d DIR -n NSEG      gpinitsystem: create a cluster
  gg state    -d DIR [--probe]    gpstate: topology + table inventory
  gg sql      -d DIR "SELECT..."  psql: run statements, print results
  gg expand   -d DIR -n NEWN      gpexpand: widen + redistribute
  gg recover  -d DIR              gprecoverseg: roll back in-doubt 2PC,
                                  rebalance roles to preferred
  gg checkcat -d DIR              gpcheckcat: catalog/storage consistency
  gg check [--plans] [--json]     static-analysis gate (docs/ANALYSIS.md)

Run as: python -m greengage_tpu.mgmt.cli <cmd> ...
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import signal
import sys
import tarfile
import tempfile
import threading
import time


def _open(path, numsegments=None):
    import greengage_tpu

    return greengage_tpu.connect(path=path, numsegments=numsegments)


def cmd_init(args):
    if os.path.exists(os.path.join(args.dir, "catalog.json")):
        print(f"error: cluster already exists at {args.dir}", file=sys.stderr)
        return 1
    import greengage_tpu

    db = greengage_tpu.connect(path=args.dir, numsegments=args.numsegments,
                               mirrors=getattr(args, "mirrors", False))
    print(f"cluster initialized at {args.dir}: {db.numsegments} segments "
          f"on {len(list(db.mesh.devices.flat))} devices"
          + (" with mirrors" if getattr(args, "mirrors", False) else ""))
    return 0


def cmd_mirrorroots(args):
    """Cross-host mirror placement (gpaddmirrors spread analog): place
    content k's mirror tree under roots[(k+1) % n] — offset so a content
    never mirrors onto its own root when roots are per-host mounts — and
    move any already-replicated trees there."""

    from greengage_tpu.storage.table_store import mirror_root

    db = _open(args.dir)
    if db.replicator is None:
        print("cluster has no mirrors (re-init with --mirrors)",
              file=sys.stderr)
        db.close()
        return 1
    roots = [os.path.abspath(r) for r in args.roots.split(",") if r]
    if not roots:
        raise ValueError("--roots needs at least one directory")
    nseg = db.numsegments
    old = {k: mirror_root(db.path, k) for k in range(nseg)}
    mapping = {str(k): roots[(k + 1) % len(roots)] for k in range(nseg)}
    mp = os.path.join(db.path, "mirror_roots.json")
    with open(mp + ".tmp", "w") as f:
        json.dump(mapping, f, indent=1)
    os.replace(mp + ".tmp", mp)
    for k in range(nseg):
        new = os.path.join(mapping[str(k)], f"content{k}")
        if os.path.abspath(old[k]) != os.path.abspath(new) \
                and os.path.isdir(old[k]):
            os.makedirs(os.path.dirname(new), exist_ok=True)
            if os.path.exists(new):
                shutil.rmtree(new)
            shutil.move(old[k], new)
        print(f"  content {k}: mirror tree at {new}")
    db.replicator.sync()
    db.catalog._save()
    print("mirrors re-synced at the new roots")
    db.close()
    return 0


def cmd_mapreduce(args):
    """gpmapreduce analog: run a YAML MAP/REDUCE job (mgmt/mapreduce.py)."""
    from greengage_tpu.mgmt.mapreduce import run_job

    db = _open(args.dir)
    with open(args.file) as f:
        run_job(db, f.read())
    db.close()
    return 0


def cmd_config(args):
    """gpconfig analog: show or persist cluster-level settings
    (settings.json, adopted by every connect on every process)."""

    sp = os.path.join(args.dir, "settings.json")
    vals = {}
    if os.path.exists(sp):
        with open(sp) as f:
            vals = json.load(f)
    if args.change is None:
        from greengage_tpu.config import Settings

        base = Settings()
        for k, v in vals.items():
            try:
                base.set(k, v)
            except ValueError:
                pass
        for k in sorted(vars(base)):
            if k.startswith("_"):
                continue
            mark = " (persisted)" if k in vals else ""
            print(f"{k:<32} {getattr(base, k)}{mark}")
        return 0
    if args.value is None:   # --remove
        vals.pop(args.change, None)
        what = f"removed {args.change}"
    else:
        from greengage_tpu.config import Settings

        Settings().set(args.change, args.value)   # validate name + coercion
        vals[args.change] = args.value
        what = f"{args.change} = {args.value}"
    tmp = sp + ".tmp"
    with open(tmp, "w") as f:
        json.dump(vals, f, indent=1)
    os.replace(tmp, sp)
    print(f"config: {what} (takes effect at next connect/restart)")
    return 0


def cmd_initstandby(args):
    """gpinitstandby analog: seed a standby coordinator directory and
    register it for continuous post-commit sync."""
    from greengage_tpu.runtime import standby

    marker = standby.init_standby(args.dir, args.standby)
    print(f"standby initialized at {args.standby} "
          f"(synced to manifest v{marker['synced_version']})")
    return 0


def cmd_activatestandby(args):
    """gpactivatestandby analog: promote the standby's metadata copy to a
    servable cluster directory, linked to the surviving data trees."""
    from greengage_tpu.runtime import standby

    st = standby.activate(args.standby, args.data)
    print(f"standby activated (manifest v{st.get('synced_version', '?')}); "
          f"connect to {args.standby}")
    return 0


def cmd_standby(args):
    """Coordinator-failover control plane (docs/ROBUSTNESS.md "Coordinator
    failover"): default prints the standby's sync status and replication
    lag; --watch runs the heartbeat watcher that auto-promotes on primary
    silence; --promote fences the old primary and promotes immediately;
    --unfence clears a fence after a recovered primary has been verified
    (manual escape hatch — never automatic)."""
    from greengage_tpu.runtime import standby

    if args.unfence:
        owner = standby.fenced(args.unfence)
        if owner is None:
            print(f"no fence at {args.unfence}")
            return 0
        standby.clear_fence(args.unfence)
        print(f"fence cleared at {args.unfence} "
              f"(was held by {owner.get('standby', '?')})")
        return 0
    if not args.standby:
        print("error: -s/--standby is required (or --unfence CLUSTER)",
              file=sys.stderr)
        return 1
    if args.promote:
        st = standby.promote(args.standby, args.data, reason="operator")
        promoted = st.get("promoted") or {}
        print(f"standby promoted (manifest v{st.get('synced_version', '?')}, "
              f"topology v{promoted.get('topology_version', '?')}); "
              f"connect to {args.standby}")
        return 0
    if args.watch:
        from greengage_tpu.config import Settings

        s = Settings()
        # cadence GUCs ride the cluster's settings.json (standby copy
        # first, primary's as fallback — they are synced post-commit)
        st0 = standby.status(args.standby)
        for root in (args.standby, st0.get("primary")):
            sp = os.path.join(root, "settings.json") if root else None
            if sp and os.path.exists(sp):
                try:
                    with open(sp) as f:
                        for k, v in json.load(f).items():
                            try:
                                s.set(k, v)
                            except ValueError:
                                pass
                except (OSError, ValueError):
                    pass
                break
        interval = args.interval if args.interval is not None \
            else s.standby_watch_interval_s
        deadline = args.deadline if args.deadline is not None \
            else s.standby_promote_deadline_s
        done = threading.Event()
        w = standby.StandbyWatcher(
            args.standby, interval_s=interval, deadline_s=deadline,
            data_path=args.data, on_promote=lambda st: done.set())
        print(f"watching primary from {args.standby} "
              f"(interval {interval:g}s, promote deadline {deadline:g}s)")
        w.start()
        try:
            while not done.wait(timeout=0.5):
                pass
            print(f"primary silent past {deadline:g}s — standby promoted; "
                  f"connect to {args.standby}")
        except KeyboardInterrupt:
            print("watch stopped")
        finally:
            w.stop()
        return 0
    st = standby.status(args.standby)
    print(f"standby: {args.standby}")
    print(f"  role: {st.get('role', '?')}  synced to manifest "
          f"v{st.get('synced_version', '?')}")
    primary = st.get("primary")
    if primary and st.get("role") == "standby":
        lag = standby.lag(primary)
        age = standby.beat_age(primary)
        beat = "never" if age == float("inf") else f"{age:.1f}s ago"
        print(f"  primary: {primary}  lag: {lag} commit(s)  "
              f"last beat: {beat}")
        owner = standby.fenced(primary)
        if owner is not None:
            print(f"  FENCED by {owner.get('standby', '?')} "
                  f"({owner.get('reason', '?')})")
    return 0


def cmd_replicate(args):
    """gpaddmirrors/manual sync: bring every mirror to the current manifest
    version (normally automatic via the mirror_sync setting)."""
    db = _open(args.dir)
    if db.replicator is None:
        print("cluster has no mirrors (re-init with --mirrors)", file=sys.stderr)
        return 1
    out = db.replicator.sync()
    db.catalog._save()
    for content, v in sorted(out.items()):
        print(f"  content {content}: mirror at version {v}")
    print("replication complete")
    return 0


def cmd_vacuum(args):
    """Compact deletion bitmaps (visimap VACUUM) and reclaim
    unreferenced segment files (rolled-back/stale writers)."""
    db = _open(args.dir)
    compacted = db.vacuum(getattr(args, "table", None))   # reaps GC too
    n = db.store.sweep_orphans(args.grace)
    print(f"vacuum: compacted {len(compacted)} table(s) "
          f"({sum(compacted.values())} live rows), "
          f"removed {n} orphaned files")
    return 0


def cmd_analyze(args):
    """ANALYZE wrapper: refresh planner statistics."""
    db = _open(args.dir)
    db.sql(f"analyze {args.table}" if args.table else "analyze")
    names = [args.table] if args.table else sorted(db.catalog.tables)
    for n in names:
        ts = db.catalog.get(n).stats
        if ts is not None:
            print(f"  {n}: {ts.rows} rows, {len(ts.columns)} columns analyzed")
    return 0


def cmd_analyzedb(args):
    """analyzedb analog: incremental ANALYZE — only tables whose on-disk
    data changed since their last statistics pass (manifest-entry
    fingerprints stand in for analyzedb's mtime/state tracking)."""
    from greengage_tpu.planner.stats import table_fingerprint

    db = _open(args.dir)
    snap = db.store.manifest.snapshot()
    stale, fresh = [], []
    for name in sorted(db.catalog.tables):
        schema = db.catalog.get(name)
        if getattr(schema, "external", None) or \
                db._external_def(schema) is not None:
            continue
        ts = schema.stats
        if (ts is None or not ts.fingerprint
                or ts.fingerprint != table_fingerprint(snap, schema)
                or args.full):
            stale.append(name)
        else:
            fresh.append(name)
    for name in stale:
        db.sql(f"analyze {name}")
        print(f"  analyzed {name}: {db.catalog.get(name).stats.rows} rows")
    for name in fresh:
        print(f"  skipped {name}: statistics are current")
    db.log.info("mgmt", f"analyzedb: {len(stale)} analyzed, "
                f"{len(fresh)} current")
    return 0


def _print_feedback_report(rep: dict) -> None:
    print(f"self-tuning: calibration generation {rep['gen']}, "
          f"{rep['digests']} digest(s) tracked, {rep['pending']} pending")
    if rep.get("scales"):
        print(f"  applied row scales: {rep['scales']}")
    shapes = rep.get("shapes") or []
    if shapes:
        print(f"  {'shape':<18}{'runs':>5} {'rows err%':>10} "
              f"{'bytes err%':>11}  statement")
        for s in sorted(shapes, key=lambda x: -x.get("runs", 0)):
            rerr = s.get("rows_err_pct")
            berr = s.get("bytes_err_pct")
            print(f"  {s['shape']:<18}{s.get('runs', 0):>5} "
                  f"{('%.1f' % rerr) if rerr is not None else '-':>10} "
                  f"{('%.1f' % berr) if berr is not None else '-':>11}  "
                  f"{(s.get('sql') or '')[:60]}")


def cmd_checkperf_feedback(args) -> int:
    """The self-tuning half of `gg checkperf`: per-plan-digest
    est-vs-actual error (rows + bytes), `--apply` commits every pending
    calibration candidate, `--reset` clears the store."""
    db = _open(args.dir)
    try:
        fb = db.feedback
        if getattr(args, "reset", False):
            fb.reset()
            print("feedback store cleared")
            return 0
        if getattr(args, "apply", False) \
                and not getattr(args, "device", False):
            n = fb.apply_pending()
            print(f"applied {n} pending correction(s)")
        _print_feedback_report(fb.report())
        return 0
    finally:
        db.close()


def cmd_checkperf(args):
    """gpcheckperf analog: micro-benchmark the cluster's hardware paths —
    data-dir disk bandwidth, host memory bandwidth, device HBM bandwidth,
    and the mesh collective (ICI) path — plus the self-tuning loop's
    est-vs-actual report (`--feedback` for the report alone)."""

    if getattr(args, "feedback", False) or getattr(args, "reset", False):
        return cmd_checkperf_feedback(args)

    import numpy as np

    mb = args.size_mb
    buf = np.random.default_rng(0).bytes(mb << 20)
    results = {}

    # disk: write + fsync + read in the cluster's data dir
    with tempfile.NamedTemporaryFile(dir=args.dir, suffix=".perf") as f:
        t0 = time.monotonic()
        f.write(buf)
        f.flush()
        os.fsync(f.fileno())
        results["disk_write_MBps"] = mb / (time.monotonic() - t0)
        f.seek(0)
        t0 = time.monotonic()
        while f.read(1 << 22):
            pass
        results["disk_read_MBps"] = mb / (time.monotonic() - t0)

    # host memory bandwidth (memcpy)
    a = np.frombuffer(buf, np.uint8)
    t0 = time.monotonic()
    for _ in range(4):
        b = a.copy()
    results["host_mem_MBps"] = 4 * mb / (time.monotonic() - t0)
    del b

    # device HBM + collective over the mesh
    try:
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(np.frombuffer(buf, np.float32))
        jax.block_until_ready(x)
        t0 = time.monotonic()
        for _ in range(4):
            y = jax.block_until_ready(x * 2.0)
        # read + write per pass
        results["device_hbm_MBps"] = 8 * mb / (time.monotonic() - t0)
        del y
        db = _open(args.dir)
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = db.mesh
        n = mesh.devices.size
        shard = jax.device_put(
            jnp.ones((n, (mb << 18) // n), jnp.float32),
            NamedSharding(mesh, PartitionSpec("seg", None)))
        from jax.experimental.shard_map import shard_map

        f2 = jax.jit(shard_map(
            lambda v: jax.lax.psum(v, "seg"), mesh=mesh,
            in_specs=PartitionSpec("seg", None),
            out_specs=PartitionSpec("seg", None)))
        jax.block_until_ready(f2(shard))
        t0 = time.monotonic()
        for _ in range(4):
            jax.block_until_ready(f2(shard))
        results["collective_allreduce_MBps"] = 4 * mb / (time.monotonic() - t0)
    except Exception as e:   # no device available is a report, not a crash
        results["device_error"] = str(e)[:120]

    if getattr(args, "device", False):
        try:
            cal = _measure_device_primitives()
            results.update({f"cal_{k}": v for k, v in cal.items()})
            if getattr(args, "apply", False):
                p = os.path.join(args.dir, "calibration.json")
                with open(p, "w") as f:
                    json.dump(cal, f, indent=1)
                print(f"calibration written to {p}")
        except Exception as e:
            results["calibration_error"] = str(e)[:160]

    print(f"{'path':<28} {'bandwidth':>14}")
    for k, v in results.items():
        if isinstance(v, float):
            if k.startswith("cal_"):
                print(f"{k:<28} {v:>14.6g}")
            else:
                print(f"{k:<28} {v:>11.0f} MB/s")
        else:
            print(f"{k:<28} {v}")
    return 0


def _measure_device_primitives(n: int = 1 << 22) -> dict:
    """Measure the planner cost model's primitives (planner/cost.py
    CALIBRATION_DEFAULTS) on the live backend: random gather, scatter-add,
    two-operand sort, HBM streaming, and the device->host relay. The ICI
    constant needs >1 device; on a single chip it keeps its default."""

    import numpy as np

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    idx = jnp.asarray(rng.integers(0, n, n).astype(np.int32))
    val = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))
    key = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int64))

    def best_s(fn, *a, reps=3):
        fn_j = jax.jit(fn)
        jax.block_until_ready(fn_j(*a))   # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            jax.block_until_ready(fn_j(*a))
            best = min(best, time.monotonic() - t0)
        return best

    cal = {}
    cal["ns_gather_row"] = best_s(lambda v, i: v[i], val, idx) * 1e9 / n
    cal["ns_scatter_row"] = best_s(
        lambda v, i: jnp.zeros((n,), v.dtype).at[i].add(v), val, idx) \
        * 1e9 / n
    # two operands (key + payload) -> per-operand cost
    from jax import lax

    cal["ns_sort_row"] = best_s(
        lambda k, v: lax.sort((k, v), num_keys=1), key, val) * 1e9 / n / 2
    # one read + one write pass of 8B rows
    cal["ns_stream_byte"] = best_s(lambda k: k * 2, key) * 1e9 / (n * 16)
    # device->host relay: fixed call floor from a tiny transfer, per-byte
    # from a big one
    small = jnp.ones((8,), jnp.int64)
    t0 = time.monotonic()
    for _ in range(3):
        jax.device_get(small)
    cal["ns_host_call"] = (time.monotonic() - t0) / 3 * 1e9
    t0 = time.monotonic()
    jax.device_get(key)
    big_s = time.monotonic() - t0
    per_byte = (big_s * 1e9 - cal["ns_host_call"]) / (n * 8)
    cal["ns_host_byte"] = max(per_byte, 1e-4)
    return cal


def cmd_load(args):
    """gpload analog: YAML-driven bulk load. The control file maps onto
    an external table + INSERT SELECT (exactly gpload's own strategy:
    it generates gpfdist external tables under the covers).

    YAML shape (subset of gpload's):
        gpload:
          input:
            source:
              file: [/path/part*.csv]     # or a gpfdist:// URL
            format: csv
            delimiter: ','
            header: true
            error_limit: 50
          output:
            table: sales
            mode: insert | truncate
    """
    import yaml

    with open(args.config) as f:
        doc = yaml.safe_load(f)
    spec = doc.get("gpload", doc)
    inp = spec.get("input", {})
    out = spec.get("output", {})
    if isinstance(inp, list):   # gpload writes sections as 1-elem maps
        inp = {k: v for d in inp for k, v in d.items()}
    if isinstance(out, list):
        out = {k: v for d in out for k, v in d.items()}
    table = out.get("table")
    if not table:
        print("error: output.table is required", file=sys.stderr)
        return 1
    if not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", str(table)):
        print(f"error: output.table {table!r} is not a valid identifier",
              file=sys.stderr)
        return 1
    src = inp.get("source", {})
    if isinstance(src, list):
        src = {k: v for d in src for k, v in d.items()}
    files = src.get("file") or ([src["url"]] if "url" in src else None)
    if isinstance(files, str):
        files = [files]
    if not files:
        print("error: input.source.file (or url) is required", file=sys.stderr)
        return 1

    db = _open(args.dir)
    schema = db.catalog.get(table)
    from greengage_tpu import types as T

    def typ(c):
        k = c.type.kind
        return {T.Kind.INT32: "int", T.Kind.INT64: "bigint",
                T.Kind.FLOAT64: "double precision", T.Kind.BOOL: "bool",
                T.Kind.DATE: "date", T.Kind.TEXT: "text"}.get(
                    k, f"decimal(18,{c.type.scale})")

    def lit(v):
        # YAML-provided values (delimiters, paths) may contain quotes —
        # escape them the SQL way before splicing into a statement
        return "'" + str(v).replace("'", "''") + "'"

    cols = ", ".join(f"{c.name} {typ(c)}" for c in schema.columns)
    ext = f"gpload_ext_{table}"
    urls = ", ".join(
        lit(u if "://" in u else "file://" + os.path.abspath(u))
        for u in files)
    fmt_opts = []
    if inp.get("delimiter"):
        fmt_opts.append(f"delimiter {lit(inp['delimiter'])}")
    if str(inp.get("header", "")).lower() in ("true", "1", "yes"):
        fmt_opts.append("header")
    fmt_name = str(inp.get("format", "csv"))
    if fmt_name not in ("csv", "text"):
        print(f"error: unsupported format {fmt_name!r}", file=sys.stderr)
        return 1
    fmt = f"format '{fmt_name}'"
    if fmt_opts:
        fmt += " (" + " ".join(fmt_opts) + ")"
    reject = ""
    if inp.get("error_limit"):
        reject = f" segment reject limit {int(inp['error_limit'])}"
    db.sql(f"drop table if exists {ext}")
    db.sql(f"create external table {ext} ({cols}) location ({urls}) "
           f"{fmt}{reject}")
    try:
        if out.get("mode", "insert") == "truncate":
            db.sql(f"delete from {table}")
        db.sql(f"insert into {table} select * from {ext}")
        n = db.sql(f"select count(*) from {table}").rows()[0][0]
        print(f"loaded into {table}: now {n} rows")
        db.log.info("mgmt", f"gpload into {table}: {n} rows total")
    finally:
        db.sql(f"drop table if exists {ext}")
    return 0


def cmd_pkg(args):
    """gppkg analog: install/remove/list extension packages for a
    cluster. A package is a directory (or .tar.gz) holding
    ``<name>/__init__.py`` that registers scalar functions via
    greengage_tpu.extensions.register_scalar. Installing copies it under
    <cluster>/extensions/ and makes `CREATE EXTENSION <name>` resolve it
    for THIS cluster only (per-database pg_proc visibility)."""

    ext_root = os.path.join(args.dir, "extensions")
    if args.action in ("install", "remove") and not args.package:
        print(f"error: gg pkg {args.action} requires a package argument",
              file=sys.stderr)
        return 1
    if args.action == "list":
        names = (sorted(os.listdir(ext_root))
                 if os.path.isdir(ext_root) else [])
        db = _open(args.dir)
        created = set(getattr(db.catalog, "extensions", ()))
        for n in names:
            mark = " (created)" if n in created else ""
            print(f"  {n}{mark}")
        print(f"({len(names)} packages)")
        return 0
    if args.action == "remove":
        target = os.path.join(ext_root, args.package)
        if not os.path.isdir(target):
            print(f"error: package {args.package!r} is not installed",
                  file=sys.stderr)
            return 1
        db = _open(args.dir)
        if args.package in getattr(db.catalog, "extensions", ()):
            print(f"error: extension {args.package!r} is still created "
                  "(drop it first)", file=sys.stderr)
            return 1
        shutil.rmtree(target)
        print(f"removed {args.package}")
        return 0
    # install
    src = args.package
    os.makedirs(ext_root, exist_ok=True)
    if src.endswith((".tar.gz", ".tgz", ".tar")):
        with tarfile.open(src) as tf:
            names = [m.name.split("/")[0] for m in tf.getmembers()
                     if m.name and not m.name.startswith((".", "/"))]
            if not names:
                print("error: empty package", file=sys.stderr)
                return 1
            pkg = names[0]
            tf.extractall(ext_root, filter="data")
    else:
        pkg = os.path.basename(src.rstrip("/"))
        dst = os.path.join(ext_root, pkg)
        if os.path.exists(dst):
            shutil.rmtree(dst)
        shutil.copytree(src, dst)
    init = os.path.join(ext_root, pkg, "__init__.py")
    if not os.path.exists(init):
        print(f"error: {pkg}/__init__.py missing — not an extension "
              "package", file=sys.stderr)
        return 1
    print(f"installed {pkg} (enable with: gg sql -d {args.dir} "
          f"\"create extension {pkg}\")")
    return 0


def cmd_state(args):
    from greengage_tpu.runtime.fts import cluster_state, needs_rebalance

    db = _open(args.dir)
    if args.probe:
        results = db.fts.probe_once()
        print("probe:", json.dumps(results))
    print(f"cluster: {args.dir}  width: {db.numsegments}  "
          f"config version: {db.catalog.segments.version}")
    info = _read_pidfile(args.dir)
    if info and _pid_alive(info[0]):
        print(f"server: running (pid {info[0]}, socket {info[1]})")
    else:
        print("server: not running (embedded access only)")
    print(f"{'content':>8} {'role':>5} {'pref':>5} {'status':>7} {'device':>7} {'synced':>7}")
    for row in cluster_state(db.catalog.segments):
        print(f"{row['content']:>8} {row['role']:>5} {row['preferred_role']:>5} "
              f"{row['status']:>7} {str(row['device']):>7} {str(row['synced']):>7}")
    if needs_rebalance(db.catalog.segments):
        print("NOTE: segments are not on their preferred roles (run gg recover)")
    for w in db.settings_warnings:
        print(f"WARNING: {w}")
    print("tables:")
    for name, schema in sorted(db.catalog.tables.items()):
        counts = db.store.segment_rowcounts(name)
        print(f"  {name}: {sum(counts)} rows over {schema.policy.numsegments} segments "
              f"({schema.policy.describe()})")
    return 0


def cmd_worker(args):
    """Multi-host worker process (the segment-host postmaster role): joins
    the distributed device runtime, then follows the coordinator's
    statement channel in lockstep. Requires the cluster directory on a
    shared filesystem. Start workers first, then the coordinator with
    greengage_tpu.connect(..., multihost=init_multihost(...))."""
    from greengage_tpu.parallel.multihost import init_multihost, worker_loop

    mh = init_multihost(args.coordinator, args.num_processes,
                        args.process_id, args.control_port,
                        distributed=not getattr(args, "no_distributed", False))
    import greengage_tpu

    # multihost must flow through connect(): the worker guard skips the
    # startup writes (catalog save / manifest recovery) that would race
    # the coordinator's in-flight transactions
    db = greengage_tpu.connect(path=args.dir, multihost=mh)
    print(f"worker {args.process_id}/{args.num_processes} serving "
          f"{len(__import__('jax').local_devices())} local devices", flush=True)
    worker_loop(db)
    return 0


def cmd_useradd(args):
    """createuser analog: add/update a remote user in gg_hba.json (salted
    sha256 at rest, file mode 0600)."""
    from greengage_tpu.runtime import auth

    auth.add_user(args.dir, args.user, args.password)
    print(f"user {args.user!r} ready for TCP connections")
    return 0


def cmd_server(args):
    """gpstart-style serving mode: listen on a unix socket (and, with
    --host/--port, on TCP with gg_hba.json authentication) until
    killed."""
    from greengage_tpu.runtime.server import SqlServer

    host = getattr(args, "host", None)
    port = getattr(args, "port", None)
    if (host is None) != (port is None):
        print("error: --host and --port must be given together",
              file=sys.stderr)
        return 1
    db = _open(args.dir)
    srv = SqlServer(db, args.socket, host=host, port=port)
    srv.start()
    where = args.socket + (
        f" and {host}:{srv.port}" if srv._tcp_server is not None else "")
    print(f"serving {args.dir} on {where} (ctrl-c to stop)")

    try:
        if hasattr(signal, "pause"):
            signal.pause()
        else:
            # platforms without signal.pause: sleep-wait for the ctrl-c
            # (the old blanket AttributeError handler silently swallowed
            # REAL AttributeError bugs from anywhere in the wait path)
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        # flag every in-flight statement before tearing the listener
        # down, so blocked connections die with a typed cause instead of
        # a connection reset
        from greengage_tpu.runtime.interrupt import REGISTRY

        n = REGISTRY.cancel_all("shutdown")
        if n:
            print(f"cancelled {n} in-flight statement(s)")
    finally:
        srv.stop()
    return 0


def _pidfile(dirpath: str) -> str:
    return os.path.join(dirpath, "server.pid")


def _read_pidfile(dirpath: str):
    """-> (pid, socket_path) or None."""
    try:
        with open(_pidfile(dirpath)) as f:
            pid_s, sock = f.read().splitlines()[:2]
        return int(pid_s), sock
    except (OSError, ValueError, IndexError):
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def cmd_start(args):
    """gpstart analog: daemonize a serving postmaster for the cluster.

    Double-fork detach; the child writes <dir>/server.pid (pid + socket,
    the postmaster.pid analog) and serves until `gg stop`. stdout/stderr
    go to <dir>/log/server.out.
    """
    info = _read_pidfile(args.dir)
    if info and _pid_alive(info[0]):
        print(f"error: server already running (pid {info[0]})",
              file=sys.stderr)
        return 1
    sock = args.socket or os.path.join(args.dir, ".gg.sock")
    pid = os.fork()
    if pid:
        # parent: reap the intermediate child (it exits at once in the
        # double fork), then poll the pidfile until the daemon confirms

        os.waitpid(pid, 0)
        for _ in range(1200):   # jax import + device init can take ~30s
            info = _read_pidfile(args.dir)
            if info and _pid_alive(info[0]):
                print(f"server started (pid {info[0]}, socket {info[1]})")
                return 0
            time.sleep(0.05)
        print("error: server failed to start (see log/server.out)",
              file=sys.stderr)
        return 1
    # child: become the daemon
    os.setsid()
    if os.fork():
        os._exit(0)
    os.makedirs(os.path.join(args.dir, "log"), exist_ok=True)
    out = open(os.path.join(args.dir, "log", "server.out"), "a")
    os.dup2(out.fileno(), 1)
    os.dup2(out.fileno(), 2)
    from greengage_tpu.runtime.server import SqlServer

    db = _open(args.dir)
    srv = SqlServer(db, sock)
    srv.start()
    with open(_pidfile(args.dir), "w") as f:
        f.write(f"{os.getpid()}\n{sock}\n")
    db.log.info("lifecycle", f"server started on {sock}")

    # sigwait avoids the check-then-pause lost-wakeup race: the signal is
    # blocked until we are actually waiting for it
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGTERM, signal.SIGINT})
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    db.log.info("lifecycle", "server stopping (signal)")
    srv.stop()
    try:
        os.remove(_pidfile(args.dir))
    except OSError:
        pass
    os._exit(0)


def cmd_stop(args):
    """gpstop analog. -m smart/fast: SIGTERM + wait; -m immediate:
    SIGKILL."""

    info = _read_pidfile(args.dir)
    if not info or not _pid_alive(info[0]):
        print("server is not running")
        try:
            os.remove(_pidfile(args.dir))
        except OSError:
            pass
        return 0
    pid, _sock = info
    os.kill(pid, signal.SIGKILL if args.mode == "immediate"
            else signal.SIGTERM)
    for _ in range(int(args.timeout / 0.05)):
        if not _pid_alive(pid):
            print(f"server stopped (pid {pid})")
            try:
                os.remove(_pidfile(args.dir))
            except OSError:
                pass
            return 0
        time.sleep(0.05)
    print(f"error: server (pid {pid}) did not exit in {args.timeout}s "
          "(try -m immediate)", file=sys.stderr)
    return 1


def cmd_logfilter(args):
    """gplogfilter analog: mine the cluster's CSV logs."""
    from greengage_tpu.runtime.logger import filter_entries, read_entries

    entries = filter_entries(
        read_entries(args.dir), trouble=args.trouble, match=args.match,
        begin=args.begin, end=args.end,
        min_duration_ms=args.min_duration)
    if args.tail:
        entries = entries[-args.tail:]
    for e in entries:
        dur = f" ({e['duration_ms']}ms)" if e["duration_ms"] else ""
        rows = f" rows={e['rows']}" if e["rows"] else ""
        print(f"{e['ts']} {e['severity']:>7} [{e['kind']}]{dur}{rows} "
              f"{e['message']}")
    print(f"({len(entries)} entries)", file=sys.stderr)
    return 0


def cmd_sql(args):
    if not getattr(args, "socket", None) and not args.dir:
        print("error: sql requires -d DIR (embedded) or -s SOCKET (server)",
              file=sys.stderr)
        return 1
    if getattr(args, "socket", None):
        from greengage_tpu.runtime.server import SqlClient

        c = SqlClient(args.socket)
        resp = c.sql(args.query)
        if resp.get("tag") is not None:
            print(resp["tag"])
        elif resp.get("columns") is not None:
            print("\t".join(resp["columns"]))
            for row in resp["rows"]:
                print("\t".join("" if v is None else str(v) for v in row))
            print(f"({len(resp['rows'])} rows)")
        c.close()
        return 0
    db = _open(args.dir)
    out = db.sql(args.query)
    if isinstance(out, str):
        print(out)
        return 0
    if hasattr(out, "columns"):
        print("\t".join(out.columns))
        for row in out.rows():
            print("\t".join("" if v is None else str(v) for v in row))
        print(f"({len(out)} rows)")
    return 0


def _activity_socket(args):
    """Resolve the serving socket for ps/cancel: explicit -s, or the
    running daemon's server.pid in -d DIR (the postmaster.pid analog)."""
    if getattr(args, "socket", None):
        return args.socket
    if getattr(args, "dir", None):
        info = _read_pidfile(args.dir)
        if info and _pid_alive(info[0]):
            return info[1]
    return None


def cmd_ps(args):
    """pg_stat_activity analog: in-flight statements of a running server
    (id, elapsed, cancel state, sql) for `gg cancel` to target."""
    from greengage_tpu.runtime.server import SqlClient

    sock = _activity_socket(args)
    if sock is None:
        print("error: ps needs -s SOCKET or -d DIR with a running server",
              file=sys.stderr)
        return 1
    c = SqlClient(sock)
    try:
        resp = c.op({"op": "ps"})
    finally:
        c.close()
    rows = resp.get("rows") or []
    cl = resp.get("cluster") or {}
    pipe = resp.get("pipeline") or {}
    if cl:
        gang = ""
        if cl.get("expected_workers") is not None:
            gang = (f"  workers: {cl.get('active_workers')}/"
                    f"{cl.get('expected_workers')}")
        # serving-pipeline depths (vectorized serving + staging pool):
        # a persistent backlog here means the device or scan_threads is
        # the bottleneck, not planning
        pq = ""
        if pipe:
            pq = (f"  pipeline: batch-window "
                  f"{pipe.get('batch_admission_depth', 0)}"
                  f" in-flight {pipe.get('batch_inflight', 0)}"
                  f" stage-pool {pipe.get('staging_pool_queue_depth', 0)}")
        print(f"cluster: {cl.get('state', '?')}  "
              f"topology v{cl.get('topology_version', '?')}{gang}{pq}")
        # standby replication health (docs/ROBUSTNESS.md "Coordinator
        # failover"): a growing lag means promotion would lose commits
        sb = cl.get("standby") or {}
        if sb:
            print(f"standby: {sb.get('path', '?')}  "
                  f"lag {sb.get('lag_commits', '?')} commit(s)  "
                  f"sync failures {sb.get('sync_fail_total', 0)}")
    # overload state (docs/ROBUSTNESS.md "Overload protection"): a
    # browned-out engine is serving degraded on purpose — say so before
    # anyone reads the statement list as a performance bug
    ov = resp.get("overload") or {}
    if ov.get("brownout"):
        print(f"overload: BROWNOUT ({ov.get('since_s', 0):.0f}s) — "
              f"{ov.get('reason')}; block-cache x"
              f"{ov.get('cache_factor')}, batch serving disabled")
    # open ingest streams (streaming COPY plane): buffered rows are
    # volatile until the next micro-batch commit; committed_seq is the
    # durable resume watermark
    for s in resp.get("ingest") or []:
        state = "error" if s.get("error") else (
            "closed" if s.get("closed") else "open")
        print(f"stream: {s['stream']} -> {s['table']}  {state}  "
              f"buffered {s['buffered_rows']}  acked {s['acked_seq']}  "
              f"committed {s['committed_seq']}")
    print(f"{'ID':>6} {'ELAPSED_S':>10} {'STATE':>12} {'BATCH':>6} "
          f"{'SPAN':>22} SQL")
    for r in rows:
        state = f"cancel:{r['cancelled']}" if r.get("cancelled") else "active"
        # current execution phase (trace registry): span name + how long
        # the statement has been inside it — stage vs device vs queue at
        # a glance, the pg_stat_activity wait_event analog
        span = "-"
        if r.get("span"):
            span = f"{r['span']} {r.get('span_ms', 0):.0f}ms"
        # member-of-batch id (vectorized serving): statements riding one
        # admission window share a BATCH id — one device dispatch
        batch = str(r["batch"]) if r.get("batch") is not None else "-"
        print(f"{r['id']:>6} {r['elapsed_s']:>10.3f} {state:>12} "
              f"{batch:>6} {span:>22} {r['sql']}")
    print(f"({len(rows)} statements)", file=sys.stderr)
    return 0


def cmd_trace(args):
    """Chrome trace_event export of one statement's trace (the gpperfmon
    query-detail analog): `gg trace <id>` (or the newest trace with no
    id) from a running server's bounded trace ring; load the JSON in
    chrome://tracing or Perfetto."""
    from greengage_tpu.runtime.server import SqlClient

    sock = _activity_socket(args)
    if sock is None:
        print("error: trace needs -s SOCKET or -d DIR with a running "
              "server", file=sys.stderr)
        return 1
    c = SqlClient(sock)
    try:
        req = {"op": "trace"}
        if args.id is not None:
            req["id"] = args.id
        resp = c.op(req)
    finally:
        c.close()
    if not resp.get("ok"):
        print(f"error: {resp.get('error')}", file=sys.stderr)
        return 1
    out = json.dumps(resp["trace"], indent=1)
    if getattr(args, "out", None):
        with open(args.out, "w") as f:
            f.write(out)
        print(f"trace written to {args.out}", file=sys.stderr)
    else:
        print(out)
    return 0


def cmd_metrics(args):
    """Prometheus text exposition of the cluster's counters, gauges and
    latency histograms (the gpperfmon/pg_stat export surface): scrape
    with any Prometheus agent via `gg metrics`, or eyeball directly."""
    from greengage_tpu.runtime.server import SqlClient

    sock = _activity_socket(args)
    if sock is None:
        print("error: metrics needs -s SOCKET or -d DIR with a running "
              "server", file=sys.stderr)
        return 1
    c = SqlClient(sock)
    try:
        resp = c.op({"op": "metrics"})
    finally:
        c.close()
    if not resp.get("ok"):
        print(f"error: {resp.get('error')}", file=sys.stderr)
        return 1
    sys.stdout.write(resp["text"])
    return 0


def cmd_mem(args):
    """Measured memory accounting surface (`gg mem`, the gp_toolkit vmem
    views analog): live device allocator stats, per-statement owner
    trees (in-flight + recent), the runaway ledger, block-cache budget
    state, and each cached executable's measured footprint."""
    from greengage_tpu.runtime.server import SqlClient

    sock = _activity_socket(args)
    if sock is None:
        print("error: mem needs -s SOCKET or -d DIR with a running server",
              file=sys.stderr)
        return 1
    c = SqlClient(sock)
    try:
        resp = c.op({"op": "mem"})
    finally:
        c.close()
    if not resp.get("ok"):
        print(f"error: {resp.get('error')}", file=sys.stderr)
        return 1
    mem = resp.get("mem") or {}
    if getattr(args, "as_json", False):
        print(json.dumps(mem, indent=1))
        return 0
    dev = mem.get("device")
    if dev:
        print(f"device: {dev.get('bytes_in_use', 0) / 1e6:.1f} MB in use, "
              f"peak {dev.get('peak_bytes_in_use', 0) / 1e6:.1f} MB")
    else:
        print("device: no allocator stats (CPU backend)")
    proc = mem.get("process") or {}
    print(f"host: rss {proc.get('host_rss_bytes', 0) / 1e6:.1f} MB, "
          f"{proc.get('host_open_fds', '?')} fds, staging queue depth "
          f"{proc.get('staging_pool_queue_depth', 0)}")
    bc = mem.get("block_cache") or {}
    if bc:
        print(f"block cache: {bc.get('total_bytes', 0) / 1e6:.1f} / "
              f"{bc.get('limit_bytes', 0) / 1e6:.0f} MB")
    for snap in (mem.get("in_flight") or []):
        owners = ", ".join(
            f"{o}={v['bytes'] / 1e6:.1f}MB"
            for o, v in (snap.get("owners") or {}).items())
        print(f"stmt {snap.get('statement_id')}: "
              f"{snap.get('total_bytes', 0) / 1e6:.1f} MB in flight "
              f"[{owners}] {snap.get('sql', '')[:60]}")
    exes = mem.get("executables") or []
    meas = [x for x in exes if x.get("measured")]
    print(f"({len(mem.get('in_flight') or [])} in-flight statements, "
          f"{len(exes)} cached executables, {len(meas)} measured)",
          file=sys.stderr)
    return 0


def cmd_cancel(args):
    """pg_cancel_backend analog: flag one in-flight statement; it dies at
    its next cancellation point with cause 'user'."""
    from greengage_tpu.runtime.server import SqlClient

    sock = _activity_socket(args)
    if sock is None:
        print("error: cancel needs -s SOCKET or -d DIR with a running "
              "server", file=sys.stderr)
        return 1
    c = SqlClient(sock)
    try:
        resp = c.op({"op": "cancel", "id": args.id})
    finally:
        c.close()
    if resp.get("ok"):
        print(f"statement {args.id} cancelled")
        return 0
    print(f"error: {resp.get('error')}", file=sys.stderr)
    return 1


def cmd_expand(args):
    db = _open(args.dir)
    moved = db.expand(args.numsegments)
    for t, n in moved.items():
        print(f"  {t}: {n} rows redistributed")
    print(f"cluster expanded to {args.numsegments} segments")
    return 0


def cmd_recover(args):
    from greengage_tpu.catalog.segments import SegmentRole

    db = _open(args.dir)
    rolled = db.store.manifest.recover()
    if rolled:
        print(f"rolled back in-doubt transactions: versions {rolled}")
    swept = db.store.sweep_orphans()
    if swept:
        print(f"reclaimed {swept} orphaned segment files")
    cfg = db.catalog.segments
    # full recovery (gprecoverseg -F / buildMirrorSegments full rebuild):
    # any content served by a promoted mirror gets its original primary
    # tree rebuilt from the mirror's files before roles swap back
    if db.replicator is not None:
        for content in range(cfg.numsegments):
            acting = cfg.acting_primary(content)
            if acting is not None and acting.preferred_role is SegmentRole.MIRROR:
                copied = db.replicator.rebuild(content)
                print(f"  content {content}: rebuilt primary from mirror "
                      f"({copied} files)")
    # rebalance: put segments back on preferred roles (gprecoverseg -r)
    changed = 0
    for e in cfg.entries:
        if e.role is not e.preferred_role:
            # restore the device binding along with the role
            e.role = e.preferred_role
            changed += 1
    if changed:
        for e in cfg.entries:
            if e.content >= 0:
                if e.role is SegmentRole.PRIMARY:
                    e.device_index = e.content
                    e.status = type(e.status)("u")
                else:
                    e.device_index = None
        cfg.version += 1
        print(f"rebalanced {changed} segments to preferred roles")
    db.catalog._save()
    print("recovery complete")
    return 0


def cmd_archive(args):
    """Continuous-archiving catch-up (archive_command analog): ship the
    current committed version to the archive. Per-commit archiving is a
    session GUC (SET archive_mode TO on; SET archive_dir TO '...')."""
    from greengage_tpu.storage.archive import Archive

    db = _open(args.dir)
    a = Archive(args.archive)
    v = a.archive_now(args.dir, db.store)
    if v is None:
        print(f"version {db.store.manifest.snapshot().get('version', 0)} "
              "already archived")
    else:
        print(f"archived version {v} to {args.archive}")
        db.log.info("archive", f"manual archive of v{v} to {args.archive}")
    vs = a.versions()
    print(f"archive holds {len(vs)} versions "
          f"(v{vs[0][0]}..v{vs[-1][0]})" if vs else "archive is empty")
    return 0


def cmd_restore_pitr(args):
    """PITR: rebuild a cluster directory at an archived version or the
    newest version at/before a timestamp (recovery_target_time)."""
    from greengage_tpu.storage.archive import Archive

    a = Archive(args.archive)
    try:
        v = a.restore(args.dir, version=args.version, time=args.time)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"restored version {v} into {args.dir}")
    vs = dict(a.versions())
    print(f"recovery target: v{v} (archived {vs.get(v)})")
    return 0


def cmd_backup(args):
    """Full backup (gp_pitr/pg_basebackup analog). The manifest snapshot
    names one committed version's files; DELETE/UPDATE/expand may GC old
    files concurrently, so a vanished file triggers a re-snapshot retry
    until one version copies completely."""

    db = _open(args.dir)
    last_err = None
    for _ in range(5):
        snap = db.store.manifest.snapshot()
        try:
            os.makedirs(args.out, exist_ok=True)
            shutil.copy(os.path.join(args.dir, "catalog.json"),
                        os.path.join(args.out, "catalog.json"))
            copied = 0
            for tname, tmeta in snap["tables"].items():
                src_base = os.path.join(args.dir, "data", tname)
                dst_base = os.path.join(args.out, "data", tname)
                if os.path.isdir(src_base):
                    for fn in os.listdir(src_base):
                        if fn.startswith("dict_"):
                            os.makedirs(dst_base, exist_ok=True)
                            shutil.copy(os.path.join(src_base, fn),
                                        os.path.join(dst_base, fn))
                for files in tmeta["segfiles"].values():
                    for rel in files:
                        dst = os.path.join(dst_base, rel)
                        os.makedirs(os.path.dirname(dst), exist_ok=True)
                        shutil.copy(os.path.join(src_base, rel), dst)
                        copied += 1
            # manifest written LAST: its presence marks a complete image
            with open(os.path.join(args.out, "manifest.json"), "w") as f:
                json.dump(snap, f, indent=1)
            print(f"backup of version {snap['version']} written to {args.out} "
                  f"({copied} segment files)")
            return 0
        except FileNotFoundError as e:
            last_err = e   # concurrent writer GC'd a file: retry fresh
    print(f"error: backup could not converge ({last_err})", file=sys.stderr)
    return 1


def cmd_restore(args):
    if os.path.exists(os.path.join(args.dir, "catalog.json")):
        print(f"error: {args.dir} already contains a cluster", file=sys.stderr)
        return 1
    shutil.copytree(args.backup, args.dir, dirs_exist_ok=True)
    db = _open(args.dir)
    print(f"restored cluster at {args.dir}: width {db.numsegments}, "
          f"{len(db.catalog.tables)} tables, manifest version "
          f"{db.store.manifest.snapshot()['version']}")
    return 0


def cmd_scrub(args):
    """Storage scrub (AO verify_block_checksums + gprecoverseg repair
    analog): verify the footer and every frame checksum of every
    manifest-referenced block file; repair corrupt/missing files from the
    in-sync standby tree or quarantine them (storage/scrub.py)."""
    from greengage_tpu.storage.scrub import Scrubber

    db = _open(args.dir)
    try:
        # (Scrubber.scrub logs the summary through the cluster log)
        rep = Scrubber(db.store, repair=not args.no_repair).scrub(
            tables=[args.table] if args.table else None,
            mirrors=args.mirrors)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    # in-doubt write intents ride the scrub sweep (same grace-GC
    # discipline as stale delta claims). _open's startup recover()
    # already swept crash leftovers, so report the process-wide
    # manifest_intent_swept_total rather than just this late sweep.
    from greengage_tpu.runtime.logger import counters

    db.store.manifest.sweep_intents()
    rep["intents_swept"] = int(counters.get("manifest_intent_swept_total"))
    if args.json:
        print(json.dumps(rep, indent=1))
    else:
        print(f"scanned     {rep['files_scanned']} files "
              f"({rep['bytes_scanned']} bytes)")
        print(f"verified    {rep['files_verified']}")
        print(f"repaired    {rep['files_repaired']}")
        print(f"quarantined {rep['files_quarantined']}")
        if rep["files_corrupt"]:
            print(f"corrupt     {rep['files_corrupt']} (--no-repair)")
        if rep["files_missing"]:
            print(f"missing     {rep['files_missing']}")
        if rep["intents_swept"]:
            print(f"intents     {rep['intents_swept']} in-doubt write "
                  "intents swept")
        if args.mirrors:
            print(f"standby     {rep['standby_verified']} verified, "
                  f"{rep['standby_repaired']} repaired")
        for p in rep["problems"]:
            print(f"  {p.get('status', '?'):<12} {p.get('table')}/"
                  f"{p.get('relpath')} [{p.get('cause', '?')}]")
    bad = (rep["files_quarantined"] + rep["files_missing"]
           + rep["files_corrupt"]
           + sum(1 for p in rep["problems"]
                 if str(p.get("status", "")).startswith(
                     ("standby_corrupt", "standby_refresh"))))
    return 1 if bad else 0


def cmd_check(args):
    """gg check: the static-analysis gate (docs/ANALYSIS.md) — codebase
    lints always; the TPC-H/TPC-DS plan-corpus sweep under --plans;
    --list prints the check catalog with per-check finding counts (the
    tier-1 log's what-ran receipt)."""
    from greengage_tpu.analysis.runner import (CHECKS, DESCRIPTIONS,
                                               run_checks, run_plan_corpus)

    if args.list:
        from greengage_tpu.analysis import astutil
        from greengage_tpu.analysis.report import load_baseline

        names = args.checks or sorted(CHECKS)
        for name in names:
            if name not in CHECKS:
                raise ValueError(f"unknown check {name!r} "
                                 f"(have: {', '.join(sorted(CHECKS))})")
        # one shared parsed view of the package for every row (the
        # run_checks design), not a re-parse per check
        sources = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
        baseline = (None if args.no_baseline
                    else load_baseline(args.baseline))
        rows = []
        for name in names:
            rep = CHECKS[name](sources)
            if baseline is not None:
                rep = rep.suppressed(baseline)
            rows.append({"check": name,
                         "description": DESCRIPTIONS.get(name, ""),
                         "findings": len(rep.findings),
                         "notes": rep.notes})
        if args.json:
            print(json.dumps({"checks": rows}, indent=1, sort_keys=True))
        else:
            width = max(len(r["check"]) for r in rows)
            for r in rows:
                print(f"{r['check']:<{width}}  {r['findings']:>3} "
                      f"finding(s)  {r['description']}")
        return 1 if any(r["findings"] for r in rows) else 0

    report = run_checks(names=args.checks or None,
                        baseline_file=args.baseline,
                        use_baseline=not args.no_baseline)
    if args.plans:
        report.extend(run_plan_corpus(numsegments=args.nseg))
    if args.json:
        print(report.to_json())
    else:
        print(report.to_text())
    return 1 if report.findings else 0


def cmd_checkcat(args):
    db = _open(args.dir)
    problems = []
    snap = db.store.manifest.snapshot()
    # orphaned manifest entries (table gone from catalog)
    for t in snap["tables"]:
        if t not in db.catalog:
            problems.append(f"manifest table {t} missing from catalog")
    for name, schema in db.catalog.tables.items():
        # partitioned parents audit through their child storage tables
        for sname in schema.storage_tables():
            tmeta = snap["tables"].get(sname)
            if tmeta is None:
                continue
            for seg, files in tmeta["segfiles"].items():
                if int(seg) >= schema.policy.numsegments:
                    problems.append(
                        f"{sname}: segfiles on seg {seg} beyond width")
                for rel in files:
                    # resolves through per-content roots (failover aware)
                    p = db.store.seg_file_path(sname, rel)
                    if not os.path.exists(p):
                        problems.append(f"{sname}: missing file {rel}")
            # row counts readable + placement verified per segment
            try:
                total = sum(db.store.segment_rowcounts(sname))
                declared = sum(int(v) for v in tmeta["nrows"].values())
                if total != declared:
                    problems.append(
                        f"{sname}: rowcount mismatch {total} != {declared}")
            except Exception as e:
                problems.append(f"{sname}: unreadable ({e})")
    if problems:
        for p in problems:
            print("PROBLEM:", p)
        return 1
    print("catalog and storage are consistent")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="gg")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("init")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-n", "--numsegments", type=int, default=None)
    p.add_argument("--mirrors", action="store_true")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("config")   # gpconfig analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-c", "--change", default=None)
    p.add_argument("-v", "--value", default=None)
    p.set_defaults(fn=cmd_config)

    p = sub.add_parser("mirrorroots")   # gpaddmirrors spread placement
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--roots", required=True,
                   help="comma-separated per-host mirror root directories")
    p.set_defaults(fn=cmd_mirrorroots)

    p = sub.add_parser("mapreduce")   # gpmapreduce analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-f", "--file", required=True, help="YAML job spec")
    p.set_defaults(fn=cmd_mapreduce)

    p = sub.add_parser("initstandby")   # gpinitstandby analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-s", "--standby", required=True)
    p.set_defaults(fn=cmd_initstandby)

    p = sub.add_parser("activatestandby")   # gpactivatestandby analog
    p.add_argument("-s", "--standby", required=True)
    p.add_argument("--data", default=None,
                   help="surviving data directory to link (defaults to the "
                        "primary's if still reachable)")
    p.set_defaults(fn=cmd_activatestandby)

    p = sub.add_parser("standby")   # failover control plane
    p.add_argument("-s", "--standby", default=None,
                   help="standby coordinator directory")
    p.add_argument("--watch", action="store_true",
                   help="heartbeat the primary; auto-promote on silence")
    p.add_argument("--promote", action="store_true",
                   help="fence the primary and promote immediately")
    p.add_argument("--interval", type=float, default=None,
                   help="watch poll interval (default: standby_watch_interval_s)")
    p.add_argument("--deadline", type=float, default=None,
                   help="promote after this many seconds of primary "
                        "silence (default: standby_promote_deadline_s)")
    p.add_argument("--data", default=None,
                   help="surviving data directory to link on promotion")
    p.add_argument("--unfence", default=None, metavar="CLUSTER",
                   help="clear a promotion fence on CLUSTER (operator "
                        "escape hatch after verifying the old primary)")
    p.set_defaults(fn=cmd_standby)

    p = sub.add_parser("replicate")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_replicate)

    p = sub.add_parser("vacuum")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-t", "--table", default=None)
    p.add_argument("--grace", type=float, default=120.0)
    p.set_defaults(fn=cmd_vacuum)

    p = sub.add_parser("analyze")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-t", "--table", default=None)
    p.set_defaults(fn=cmd_analyze)

    p = sub.add_parser("analyzedb")   # incremental stats refresh
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--full", action="store_true")
    p.set_defaults(fn=cmd_analyzedb)

    p = sub.add_parser("checkperf")   # gpcheckperf analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--size-mb", type=int, default=64)
    p.add_argument("--device", action="store_true",
                   help="measure planner cost-model primitives on the "
                        "live backend")
    p.add_argument("--apply", action="store_true",
                   help="with --device: persist measurements to "
                        "<dir>/calibration.json; with --feedback: commit "
                        "every pending self-tuning correction")
    p.add_argument("--feedback", action="store_true",
                   help="print only the self-tuning est-vs-actual report "
                        "(planner/feedback.py store)")
    p.add_argument("--reset", action="store_true",
                   help="clear the self-tuning feedback store")
    p.set_defaults(fn=cmd_checkperf)

    p = sub.add_parser("load")        # gpload analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-f", "--config", required=True)
    p.set_defaults(fn=cmd_load)

    p = sub.add_parser("pkg")         # gppkg analog
    p.add_argument("action", choices=("install", "remove", "list"))
    p.add_argument("package", nargs="?", default=None)
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_pkg)

    p = sub.add_parser("state")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--probe", action="store_true")
    p.set_defaults(fn=cmd_state)

    p = sub.add_parser("sql")
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.add_argument("query")
    p.set_defaults(fn=cmd_sql)

    p = sub.add_parser("ps")      # pg_stat_activity analog
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.set_defaults(fn=cmd_ps)

    p = sub.add_parser("cancel")  # pg_cancel_backend analog
    p.add_argument("id", type=int)
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.set_defaults(fn=cmd_cancel)

    p = sub.add_parser("trace")   # Chrome trace_event export (gpperfmon)
    p.add_argument("id", nargs="?", type=int, default=None,
                   help="statement id (default: newest completed trace)")
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.add_argument("-o", "--out", default=None,
                   help="write the JSON here instead of stdout")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("metrics")  # Prometheus text exposition
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("mem")      # measured memory accounting surface
    p.add_argument("-d", "--dir", default=None)
    p.add_argument("-s", "--socket", default=None)
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="raw JSON report instead of the summary")
    p.set_defaults(fn=cmd_mem)

    p = sub.add_parser("server")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-s", "--socket", required=True)
    p.add_argument("--host", default=None,
                   help="also listen on TCP (requires gg_hba.json users)")
    p.add_argument("--port", type=int, default=None)
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("useradd")   # createuser + pg_hba analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-u", "--user", required=True)
    p.add_argument("-P", "--password", required=True)
    p.set_defaults(fn=cmd_useradd)

    p = sub.add_parser("start")   # gpstart analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-s", "--socket", default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop")    # gpstop analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-m", "--mode", choices=("smart", "fast", "immediate"),
                   default="smart")
    p.add_argument("--timeout", type=float, default=30.0)
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("logfilter")   # gplogfilter analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-t", "--trouble", action="store_true")
    p.add_argument("-m", "--match", default=None)
    p.add_argument("-b", "--begin", default=None)
    p.add_argument("-e", "--end", default=None)
    p.add_argument("--min-duration", type=float, default=None)
    p.add_argument("-n", "--tail", type=int, default=None)
    p.set_defaults(fn=cmd_logfilter)

    p = sub.add_parser("worker")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("--coordinator", required=True)   # host:port (jax.distributed)
    p.add_argument("--control-port", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    # control-plane-only gang: no jax.distributed global mesh; every
    # process runs the lockstep program on its own full local mesh
    # (replicated-device deployments, CPU demo clusters)
    p.add_argument("--no-distributed", action="store_true")
    p.set_defaults(fn=cmd_worker)

    p = sub.add_parser("expand")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-n", "--numsegments", type=int, required=True)
    p.set_defaults(fn=cmd_expand)

    p = sub.add_parser("recover")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_recover)

    p = sub.add_parser("check")   # static analysis gate (docs/ANALYSIS.md)
    p.add_argument("checks", nargs="*",
                   help="subset of checks (default: all static lints)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--plans", action="store_true",
                   help="also validate the TPC-H/TPC-DS plan corpus")
    p.add_argument("--nseg", type=int, default=4)
    p.add_argument("--baseline", default=None,
                   help="alternate baseline file (default: checked-in)")
    p.add_argument("--no-baseline", action="store_true",
                   help="show findings the baseline would suppress")
    p.add_argument("--list", action="store_true",
                   help="print the check catalog with per-check finding "
                        "counts instead of the findings themselves")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("checkcat")
    p.add_argument("-d", "--dir", required=True)
    p.set_defaults(fn=cmd_checkcat)

    p = sub.add_parser("scrub")     # storage verify + repair-or-quarantine
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-t", "--table", default=None)
    p.add_argument("--mirrors", action="store_true",
                   help="also verify (and refresh) standby-tree copies")
    p.add_argument("--no-repair", action="store_true",
                   help="report only; do not repair or quarantine")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.set_defaults(fn=cmd_scrub)

    p = sub.add_parser("archive")       # WAL-archive analog
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-a", "--archive", required=True)
    p.set_defaults(fn=cmd_archive)

    p = sub.add_parser("restore-pitr")  # point-in-time recovery
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-a", "--archive", required=True)
    p.add_argument("-v", "--version", type=int, default=None)
    p.add_argument("-t", "--time", default=None)
    p.set_defaults(fn=cmd_restore_pitr)

    p = sub.add_parser("backup")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-o", "--out", required=True)
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser("restore")
    p.add_argument("-d", "--dir", required=True)
    p.add_argument("-b", "--backup", required=True)
    p.set_defaults(fn=cmd_restore)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:      # e.g. `gg logfilter | head`
        return 0


if __name__ == "__main__":
    sys.exit(main())
