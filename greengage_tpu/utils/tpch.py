"""Deterministic TPC-H-style data generator (dbgen-lite) + schema DDL.

Structurally faithful to TPC-H (key relationships, value ranges, decimal
scales, date windows) with simplified text columns: free-text *_comment
fields use a small vocabulary so dictionary encoding stays cheap (the
reference's benchmark harness concern is bulk numbers, not prose —
src/test/performance loads synthetic rows similarly). Row counts follow the
spec: lineitem ≈ 6M x SF, orders = 1.5M x SF, customer = 150k x SF,
part = 200k x SF, supplier = 10k x SF.
"""

from __future__ import annotations
import os
import pickle

import numpy as np

from greengage_tpu import types as T
from greengage_tpu.types import Coded

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]

_D = T.date_to_days


def _dates(rng, n, lo="1992-01-01", hi="1998-08-02"):
    return rng.integers(_D(lo), _D(hi) + 1, n).astype(np.int32)


def _dec(rng, n, lo, hi, scale=2):
    """Random decimal in [lo, hi] as scaled int64."""
    return rng.integers(int(lo * 10**scale), int(hi * 10**scale) + 1, n).astype(np.int64)


def _vocab(rng, n, prefix, k) -> Coded:
    """Low-NDV text column in bulk-coded form (vocab + int32 codes): O(k)
    Python string work regardless of row count."""
    idx = rng.integers(0, k, n).astype(np.int32)
    return Coded([f"{prefix}{i}" for i in range(k)], idx)


def _choice(rng, n, values: list[str]) -> Coded:
    return Coded(list(values), rng.integers(0, len(values), n).astype(np.int32))


def generate(sf: float, seed: int = 19940801) -> dict[str, dict]:
    """-> {table: {col: np.ndarray | list[str]}} (decimals pre-scaled)."""
    rng = np.random.default_rng(seed)
    n_orders = max(int(1_500_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 5)
    n_supp = max(int(10_000 * sf), 3)
    n_part = max(int(200_000 * sf), 5)

    nation = {
        "n_nationkey": np.arange(25, dtype=np.int32),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int32),
        "n_comment": _vocab(rng, 25, "nation comment ", 10),
    }
    region = {
        "r_regionkey": np.arange(5, dtype=np.int32),
        "r_name": REGIONS,
        "r_comment": _vocab(rng, 5, "region comment ", 5),
    }
    supplier = {
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_address": _vocab(rng, n_supp, "addr ", 500),
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int32),
        "s_phone": _vocab(rng, n_supp, "phone ", 1000),
        "s_acctbal": _dec(rng, n_supp, -999.99, 9999.99),
        "s_comment": _vocab(rng, n_supp, "supp comment ", 200),
    }
    customer = {
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_address": _vocab(rng, n_cust, "addr ", 1000),
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int32),
        "c_phone": _vocab(rng, n_cust, "phone ", 1000),
        "c_acctbal": _dec(rng, n_cust, -999.99, 9999.99),
        "c_mktsegment": _choice(rng, n_cust, SEGMENTS),
        "c_comment": _vocab(rng, n_cust, "cust comment ", 300),
    }
    part = {
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": _vocab(rng, n_part, "part name ", 2000),
        "p_mfgr": Coded([f"Manufacturer#{i}" for i in range(1, 6)],
                        rng.integers(0, 5, n_part).astype(np.int32)),
        "p_brand": Coded([f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)],
                         rng.integers(0, 25, n_part).astype(np.int32)),
        "p_type": _vocab(rng, n_part, "type ", 150),
        "p_size": rng.integers(1, 51, n_part).astype(np.int32),
        "p_container": _vocab(rng, n_part, "container ", 40),
        "p_retailprice": _dec(rng, n_part, 900.0, 2000.0),
        "p_comment": _vocab(rng, n_part, "part comment ", 100),
    }
    odate = _dates(rng, n_orders, "1992-01-01", "1998-08-02")
    orders = {
        "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, n_cust + 1, n_orders).astype(np.int64),
        "o_orderstatus": _choice(rng, n_orders, ["F", "O", "P"]),
        "o_totalprice": _dec(rng, n_orders, 800.0, 500000.0),
        "o_orderdate": odate,
        "o_orderpriority": _choice(rng, n_orders, PRIORITIES),
        "o_clerk": Coded(
            [f"Clerk#{i:09d}" for i in range(1, max(n_orders // 1000, 2))],
            rng.integers(0, max(n_orders // 1000, 2) - 1, n_orders).astype(np.int32)),
        "o_shippriority": np.zeros(n_orders, dtype=np.int32),
        "o_comment": _vocab(rng, n_orders, "order comment ", 500),
    }
    # lineitem: 1-7 lines per order (avg 4)
    lines_per = rng.integers(1, 8, n_orders)
    n_line = int(lines_per.sum())
    l_orderkey = np.repeat(orders["o_orderkey"], lines_per)
    l_odate = np.repeat(odate, lines_per)
    ship_delay = rng.integers(1, 122, n_line)
    l_ship = (l_odate + ship_delay).astype(np.int32)
    # linenumber = position within order, vectorized: global index minus the
    # order's first global index, +1
    starts = np.repeat(np.cumsum(lines_per) - lines_per, lines_per)
    l_linenumber = (np.arange(n_line) - starts + 1).astype(np.int32)
    lineitem = {
        "l_orderkey": l_orderkey,
        "l_partkey": rng.integers(1, n_part + 1, n_line).astype(np.int64),
        "l_suppkey": rng.integers(1, n_supp + 1, n_line).astype(np.int64),
        "l_linenumber": l_linenumber,
        "l_quantity": _dec(rng, n_line, 1.0, 50.0),
        "l_extendedprice": _dec(rng, n_line, 900.0, 100000.0),
        "l_discount": _dec(rng, n_line, 0.0, 0.10),
        "l_tax": _dec(rng, n_line, 0.0, 0.08),
        "l_returnflag": _choice(rng, n_line, ["A", "N", "R"]),
        "l_linestatus": _choice(rng, n_line, ["F", "O"]),
        "l_shipdate": l_ship,
        "l_commitdate": (l_ship + rng.integers(-30, 31, n_line)).astype(np.int32),
        "l_receiptdate": (l_ship + rng.integers(1, 31, n_line)).astype(np.int32),
        "l_shipinstruct": _choice(rng, n_line, INSTRUCTS),
        "l_shipmode": _choice(rng, n_line, SHIPMODES),
        "l_comment": _vocab(rng, n_line, "li comment ", 1000),
    }
    # partsupp: each part stocked by 4 suppliers (dbgen's layout: supplier
    # chosen by a part/index formula so pairs are unique)
    ps_part = np.repeat(part["p_partkey"], 4)
    idx4 = np.tile(np.arange(4, dtype=np.int64), n_part)
    ps_supp = ((ps_part + idx4 * (n_supp // 4 + 1)) % n_supp) + 1
    n_ps = len(ps_part)
    partsupp = {
        "ps_partkey": ps_part.astype(np.int64),
        "ps_suppkey": ps_supp.astype(np.int64),
        "ps_availqty": rng.integers(1, 10_000, n_ps).astype(np.int32),
        "ps_supplycost": _dec(rng, n_ps, 1.0, 1000.0),
        "ps_comment": _vocab(rng, n_ps, "ps comment ", 200),
    }
    return {
        "nation": nation, "region": region, "supplier": supplier,
        "customer": customer, "part": part, "partsupp": partsupp,
        "orders": orders, "lineitem": lineitem,
    }


def generate_cached(sf: float, seed: int = 19940801,
                    cache_dir: str | None = None) -> dict[str, dict]:
    """generate() with a pickle disk cache: at SF10 generation costs minutes
    of the bench's measurement window while an unpickle costs seconds. The
    cache is keyed by (sf, seed) and validated by a version tag so a
    generator change invalidates stale files. Falls back to generate() on
    any cache error (corrupt file, disk full, ...)."""

    if cache_dir is None:
        # user-owned cache dir, not world-writable /tmp: the cache is
        # loaded with pickle, so the path must not be attacker-creatable
        cache_dir = os.environ.get(
            "GGTPU_TPCH_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "ggtpu"))
    os.makedirs(cache_dir, exist_ok=True)
    tag = f"v1:{sf:g}:{seed}"
    path = os.path.join(cache_dir, f"ggtpu_tpch_sf{sf:g}_{seed}.pkl")
    try:
        with open(path, "rb") as f:
            got_tag, data = pickle.load(f)
        if got_tag == tag:
            return data
    except Exception:
        pass
    data = generate(sf, seed)
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump((tag, data), f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except Exception:
        try:
            os.unlink(tmp)
        except Exception:
            pass
    return data


DDL = """
create table if not exists nation (
  n_nationkey int, n_name text, n_regionkey int, n_comment text
) distributed replicated;
create table if not exists region (
  r_regionkey int, r_name text, r_comment text
) distributed replicated;
create table if not exists supplier (
  s_suppkey bigint, s_name text, s_address text, s_nationkey int,
  s_phone text, s_acctbal decimal(15,2), s_comment text
) distributed by (s_suppkey);
create table if not exists customer (
  c_custkey bigint, c_name text, c_address text, c_nationkey int,
  c_phone text, c_acctbal decimal(15,2), c_mktsegment text, c_comment text
) distributed by (c_custkey);
create table if not exists part (
  p_partkey bigint, p_name text, p_mfgr text, p_brand text, p_type text,
  p_size int, p_container text, p_retailprice decimal(15,2), p_comment text
) distributed by (p_partkey);
create table if not exists partsupp (
  ps_partkey bigint, ps_suppkey bigint, ps_availqty int,
  ps_supplycost decimal(15,2), ps_comment text
) distributed by (ps_partkey);
create table if not exists orders (
  o_orderkey bigint, o_custkey bigint, o_orderstatus text,
  o_totalprice decimal(15,2), o_orderdate date, o_orderpriority text,
  o_clerk text, o_shippriority int, o_comment text
) distributed by (o_orderkey);
create table if not exists lineitem (
  l_orderkey bigint, l_partkey bigint, l_suppkey bigint, l_linenumber int,
  l_quantity decimal(15,2), l_extendedprice decimal(15,2),
  l_discount decimal(15,2), l_tax decimal(15,2),
  l_returnflag text, l_linestatus text,
  l_shipdate date, l_commitdate date, l_receiptdate date,
  l_shipinstruct text, l_shipmode text, l_comment text
) distributed by (l_orderkey);
"""


def load(db, sf: float, seed: int = 19940801, tables: list[str] | None = None):
    """Create schema + bulk load into a Database."""
    db.sql(DDL)
    data = generate(sf, seed)
    for name, cols in data.items():
        if tables is not None and name not in tables:
            continue
        db.load_table(name, cols)
    return {k: len(next(iter(v.values()))) for k, v in data.items()}


def to_pandas(data: dict[str, dict], decimals_as_float: bool = True):
    """Oracle-side view of generated data (decimals descaled to float)."""
    import pandas as pd

    scales = {
        "l_quantity": 2, "l_extendedprice": 2, "l_discount": 2, "l_tax": 2,
        "o_totalprice": 2, "c_acctbal": 2, "s_acctbal": 2, "p_retailprice": 2,
        "ps_supplycost": 2,
    }
    out = {}
    for t, cols in data.items():
        df = {}
        for c, v in cols.items():
            if isinstance(v, Coded):
                df[c] = v.decode()
            elif decimals_as_float and c in scales:
                df[c] = np.asarray(v, dtype=np.float64) / 100.0
            else:
                df[c] = v
        out[t] = pd.DataFrame(df)
    return out
