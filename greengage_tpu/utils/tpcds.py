"""Deterministic TPC-DS-style data generator (dsdgen-lite) + schema DDL.

Structurally faithful to the TPC-DS retail star schema (16 tables: three
sales channels + inventory over shared dimensions, surrogate-key
relationships, decimal scales, the 1998-2002 date_dim window, d_month_seq
months-since-1900 numbering) with simplified text columns: low-NDV
attributes use small vocabularies in bulk-coded form so dictionary
encoding stays cheap, like utils/tpch.py. Row counts scale linearly in
``scale`` from a test-scale base (store_sales = 60k rows at scale 1).

Tickets/orders group fact rows the way dsdgen does: every store ticket
(and catalog/web order) shares one customer, store, date, and demo set
across its line items — the Q68/Q73/Q79 per-ticket shapes depend on it.
"""

from __future__ import annotations

import numpy as np

from greengage_tpu import types as T
from greengage_tpu.types import Coded

_D = T.date_to_days

FIRST_DAY = "1998-01-01"
N_DATE = _D("2002-12-31") - _D(FIRST_DAY) + 1   # 1826 days

CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Men", "Music",
              "Shoes", "Sports", "Children", "Women"]
STATES = ["CA", "GA", "IL", "NY", "OH", "TN", "TX", "WA"]
COUNTIES = [f"{s} County {i}" for s in ("Ziebach", "Walker", "Daviess",
                                        "Barrow", "Fairfield") for i in (1, 2)]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
                 "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]
DAY_NAMES = ["Thursday", "Friday", "Saturday", "Sunday", "Monday",
             "Tuesday", "Wednesday"]   # 1998-01-01 was a Thursday
SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY",
            "LIBRARY"]


def _dec(rng, n, lo, hi, scale=2):
    return rng.integers(int(lo * 10**scale),
                        int(hi * 10**scale) + 1, n).astype(np.int64)


def _choice(rng, n, values) -> Coded:
    return Coded(list(values), rng.integers(0, len(values), n).astype(np.int32))


def _vocab(rng, n, prefix, k) -> Coded:
    idx = rng.integers(0, k, n).astype(np.int32)
    return Coded([f"{prefix}{i}" for i in range(k)], idx)


def generate(scale: float = 1.0, seed: int = 20020101) -> dict[str, dict]:
    """-> {table: {col: np.ndarray | Coded}} (decimals pre-scaled, scale 2;
    dates as days-since-epoch int32)."""
    rng = np.random.default_rng(seed)
    n_item = max(int(400 * scale), 40)
    n_store = max(int(12 * scale), 6)
    n_cust = max(int(2000 * scale), 100)
    n_addr = max(int(1000 * scale), 50)
    n_cd = 400
    n_hd = 144
    n_promo = 30
    n_wh = 5
    n_sm = len(SM_TYPES)
    n_web = 6
    n_ss_t = max(int(15_000 * scale), 200)     # store tickets (~4 lines each)
    n_cs_o = max(int(8_000 * scale), 100)      # catalog orders
    n_ws_o = max(int(8_000 * scale), 100)      # web orders

    # ---- date_dim: one row per day, 1998-01-01 .. 2002-12-31 ----------
    base = _D(FIRST_DAY)
    days = np.arange(N_DATE, dtype=np.int32)
    dates = (np.datetime64(FIRST_DAY) + days.astype("timedelta64[D]"))
    y = dates.astype("datetime64[Y]").astype(int) + 1970
    m = dates.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    date_dim = {
        "d_date_sk": days.astype(np.int64),
        "d_date": (base + days).astype(np.int32),
        "d_year": y.astype(np.int32),
        "d_moy": m.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": ((m + 2) // 3).astype(np.int32),
        "d_dow": (days % 7).astype(np.int32),
        "d_day_name": Coded(DAY_NAMES, (days % 7).astype(np.int32)),
        # months since 1900 (dsdgen numbering): 1998-01 -> 1176
        "d_month_seq": ((y - 1900) * 12 + m - 1).astype(np.int32),
        "d_week_seq": (days // 7 + 5114).astype(np.int32),
    }

    # ---- time_dim: one row per minute ---------------------------------
    mins = np.arange(1440, dtype=np.int32)
    time_dim = {
        "t_time_sk": mins.astype(np.int64),
        "t_hour": (mins // 60).astype(np.int32),
        "t_minute": (mins % 60).astype(np.int32),
    }

    # ---- item ---------------------------------------------------------
    cat_idx = rng.integers(0, len(CATEGORIES), n_item).astype(np.int32)
    class_id = rng.integers(1, 17, n_item).astype(np.int32)
    brand_id = (cat_idx + 1) * 1000000 + class_id * 1000 \
        + rng.integers(1, 10, n_item)
    item = {
        "i_item_sk": np.arange(n_item, dtype=np.int64),
        "i_item_id": Coded([f"AAAAAAAA{i:08d}" for i in range(n_item)],
                           np.arange(n_item, dtype=np.int32)),
        "i_item_desc": _vocab(rng, n_item, "item description ", 200),
        "i_current_price": _dec(rng, n_item, 0.09, 99.99),
        "i_wholesale_cost": _dec(rng, n_item, 0.05, 70.00),
        "i_brand_id": brand_id.astype(np.int32),
        "i_brand": _vocab(rng, n_item, "importobrand #", 60),
        "i_class_id": class_id,
        "i_class": _vocab(rng, n_item, "class ", 16),
        "i_category_id": (cat_idx + 1).astype(np.int32),
        "i_category": Coded(CATEGORIES, cat_idx),
        "i_manufact_id": rng.integers(1, 100, n_item).astype(np.int32),
        "i_manufact": _vocab(rng, n_item, "manufact ", 90),
        "i_manager_id": rng.integers(1, 40, n_item).astype(np.int32),
    }

    # ---- store --------------------------------------------------------
    store = {
        "s_store_sk": np.arange(n_store, dtype=np.int64),
        "s_store_id": Coded([f"AAAAAAAA{i:04d}BAAA" for i in range(n_store)],
                            np.arange(n_store, dtype=np.int32)),
        # dsdgen reuses a tiny name vocabulary ("ought", "able", "ese", ...)
        "s_store_name": _choice(rng, n_store,
                                ["ought", "able", "pri", "ese", "anti"]),
        "s_company_name": Coded(["Unknown"], np.zeros(n_store, np.int32)),
        "s_state": _choice(rng, n_store, STATES),
        "s_county": _choice(rng, n_store, COUNTIES),
        "s_city": _choice(rng, n_store, ["Midway", "Fairview", "Oakdale",
                                         "Glendale", "Centerville"]),
        "s_zip": _vocab(rng, n_store, "554", 30),
        "s_gmt_offset": rng.choice([-500, -600], n_store).astype(np.int64),
    }

    # ---- customer + dims ----------------------------------------------
    customer = {
        "c_customer_sk": np.arange(n_cust, dtype=np.int64),
        "c_customer_id": Coded([f"AAAAAAAA{i:08d}" for i in range(n_cust)],
                               np.arange(n_cust, dtype=np.int32)),
        "c_current_cdemo_sk": rng.integers(0, n_cd, n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(0, n_hd, n_cust).astype(np.int64),
        "c_current_addr_sk": rng.integers(0, n_addr, n_cust).astype(np.int64),
        "c_first_name": _vocab(rng, n_cust, "First", 300),
        "c_last_name": _vocab(rng, n_cust, "Last", 400),
        "c_salutation": _choice(rng, n_cust, ["Mr.", "Mrs.", "Ms.", "Dr.",
                                              "Miss", "Sir"]),
        "c_preferred_cust_flag": _choice(rng, n_cust, ["Y", "N"]),
        "c_birth_month": rng.integers(1, 13, n_cust).astype(np.int32),
        "c_birth_year": rng.integers(1924, 1993, n_cust).astype(np.int32),
        "c_birth_country": _choice(rng, n_cust, ["UNITED STATES", "CANADA",
                                                 "GERMANY", "JAPAN", "CHILE"]),
    }
    customer_address = {
        "ca_address_sk": np.arange(n_addr, dtype=np.int64),
        "ca_state": _choice(rng, n_addr, STATES),
        "ca_county": _choice(rng, n_addr, COUNTIES),
        "ca_city": _choice(rng, n_addr, ["Midway", "Fairview", "Oakdale",
                                         "Glendale", "Centerville",
                                         "Springdale", "Union Hill"]),
        "ca_zip": _vocab(rng, n_addr, "8", 400),
        "ca_country": Coded(["United States"], np.zeros(n_addr, np.int32)),
        "ca_gmt_offset": rng.choice([-500, -600, -700],
                                    n_addr).astype(np.int64),
        "ca_location_type": _choice(rng, n_addr, ["apartment", "condo",
                                                  "single family"]),
    }
    customer_demographics = {
        "cd_demo_sk": np.arange(n_cd, dtype=np.int64),
        "cd_gender": _choice(rng, n_cd, ["M", "F"]),
        "cd_marital_status": _choice(rng, n_cd, ["M", "S", "D", "W", "U"]),
        "cd_education_status": _choice(rng, n_cd, EDUCATION),
        "cd_purchase_estimate": (rng.integers(1, 20, n_cd) * 500).astype(
            np.int32),
        "cd_credit_rating": _choice(rng, n_cd, CREDIT),
        "cd_dep_count": rng.integers(0, 7, n_cd).astype(np.int32),
    }
    household_demographics = {
        "hd_demo_sk": np.arange(n_hd, dtype=np.int64),
        "hd_income_band_sk": rng.integers(0, 20, n_hd).astype(np.int64),
        "hd_buy_potential": _choice(rng, n_hd, BUY_POTENTIAL),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int32),
        "hd_vehicle_count": rng.integers(-1, 5, n_hd).astype(np.int32),
    }
    promotion = {
        "p_promo_sk": np.arange(n_promo, dtype=np.int64),
        "p_channel_dmail": _choice(rng, n_promo, ["Y", "N"]),
        "p_channel_email": _choice(rng, n_promo, ["Y", "N"]),
        "p_channel_tv": _choice(rng, n_promo, ["Y", "N"]),
        "p_channel_event": _choice(rng, n_promo, ["Y", "N"]),
    }
    warehouse = {
        "w_warehouse_sk": np.arange(n_wh, dtype=np.int64),
        "w_warehouse_name": Coded(
            [f"Warehouse number {i} with a long name" for i in range(n_wh)],
            np.arange(n_wh, dtype=np.int32)),
        "w_warehouse_sq_ft": rng.integers(50_000, 1_000_000, n_wh).astype(
            np.int32),
        "w_state": _choice(rng, n_wh, STATES),
    }
    ship_mode = {
        "sm_ship_mode_sk": np.arange(n_sm, dtype=np.int64),
        "sm_type": Coded(SM_TYPES, np.arange(n_sm, dtype=np.int32)),
        "sm_carrier": _choice(rng, n_sm, ["UPS", "FEDEX", "AIRBORNE", "USPS",
                                          "DHL", "TBS"]),
    }
    web_site = {
        "web_site_sk": np.arange(n_web, dtype=np.int64),
        "web_name": Coded([f"site_{i}" for i in range(n_web)],
                          np.arange(n_web, dtype=np.int32)),
    }

    # ---- store_sales: per-ticket grouping -----------------------------
    def _fact(n_orders, lo_lines, hi_lines):
        lines = rng.integers(lo_lines, hi_lines + 1, n_orders)
        n = int(lines.sum())
        rep = np.repeat(np.arange(n_orders), lines)
        return lines, n, rep

    t_lines, n_ss, t_rep = _fact(n_ss_t, 1, 7)
    t_date = rng.integers(0, N_DATE, n_ss_t)
    t_cust = rng.integers(0, n_cust, n_ss_t)
    t_store = rng.integers(0, n_store, n_ss_t)
    t_hdemo = rng.integers(0, n_hd, n_ss_t)
    t_cdemo = rng.integers(0, n_cd, n_ss_t)
    t_addr = rng.integers(0, n_addr, n_ss_t)
    qty = rng.integers(1, 101, n_ss).astype(np.int32)
    whole = _dec(rng, n_ss, 1.0, 100.0)
    lp = (whole * rng.integers(100, 201, n_ss) // 100).astype(np.int64)
    sp = (lp * rng.integers(20, 101, n_ss) // 100).astype(np.int64)
    coupon = np.where(rng.random(n_ss) < 0.2,
                      (sp * qty // 10).astype(np.int64), 0)
    store_sales = {
        "ss_sold_date_sk": t_date[t_rep].astype(np.int64),
        "ss_sold_time_sk": rng.integers(0, 1440, n_ss).astype(np.int64),
        "ss_item_sk": rng.integers(0, n_item, n_ss).astype(np.int64),
        "ss_customer_sk": t_cust[t_rep].astype(np.int64),
        "ss_cdemo_sk": t_cdemo[t_rep].astype(np.int64),
        "ss_hdemo_sk": t_hdemo[t_rep].astype(np.int64),
        "ss_addr_sk": t_addr[t_rep].astype(np.int64),
        "ss_store_sk": t_store[t_rep].astype(np.int64),
        "ss_promo_sk": rng.integers(0, n_promo, n_ss).astype(np.int64),
        "ss_ticket_number": t_rep.astype(np.int64),
        "ss_quantity": qty,
        "ss_wholesale_cost": whole,
        "ss_list_price": lp,
        "ss_sales_price": sp,
        "ss_ext_discount_amt": ((lp - sp) * qty).astype(np.int64),
        "ss_ext_sales_price": (sp * qty).astype(np.int64),
        "ss_ext_wholesale_cost": (whole * qty).astype(np.int64),
        "ss_ext_list_price": (lp * qty).astype(np.int64),
        "ss_ext_tax": (sp * qty // 20).astype(np.int64),
        "ss_coupon_amt": coupon,
        "ss_net_paid": (sp * qty - coupon).astype(np.int64),
        "ss_net_profit": (sp * qty - coupon - whole * qty).astype(np.int64),
    }

    # ---- catalog_sales ------------------------------------------------
    o_lines, n_cs, o_rep = _fact(n_cs_o, 1, 5)
    o_date = rng.integers(0, N_DATE - 125, n_cs_o)
    o_cust = rng.integers(0, n_cust, n_cs_o)
    o_cdemo = rng.integers(0, n_cd, n_cs_o)
    o_addr = rng.integers(0, n_addr, n_cs_o)
    qty = rng.integers(1, 101, n_cs).astype(np.int32)
    whole = _dec(rng, n_cs, 1.0, 100.0)
    lp = (whole * rng.integers(100, 201, n_cs) // 100).astype(np.int64)
    sp = (lp * rng.integers(20, 101, n_cs) // 100).astype(np.int64)
    disc = ((lp - sp) * qty).astype(np.int64)
    cs_coupon = np.where(rng.random(n_cs) < 0.2,
                         (sp * qty // 10).astype(np.int64), 0)
    catalog_sales = {
        "cs_sold_date_sk": o_date[o_rep].astype(np.int64),
        "cs_ship_date_sk": (o_date[o_rep]
                            + rng.integers(1, 121, n_cs)).astype(np.int64),
        "cs_bill_customer_sk": o_cust[o_rep].astype(np.int64),
        "cs_bill_cdemo_sk": o_cdemo[o_rep].astype(np.int64),
        "cs_bill_addr_sk": o_addr[o_rep].astype(np.int64),
        "cs_ship_mode_sk": rng.integers(0, n_sm, n_cs).astype(np.int64),
        "cs_warehouse_sk": rng.integers(0, n_wh, n_cs).astype(np.int64),
        "cs_item_sk": rng.integers(0, n_item, n_cs).astype(np.int64),
        "cs_promo_sk": rng.integers(0, n_promo, n_cs).astype(np.int64),
        "cs_order_number": o_rep.astype(np.int64),
        "cs_quantity": qty,
        "cs_wholesale_cost": whole,
        "cs_list_price": lp,
        "cs_sales_price": sp,
        "cs_ext_discount_amt": disc,
        "cs_ext_sales_price": (sp * qty).astype(np.int64),
        "cs_ext_wholesale_cost": (whole * qty).astype(np.int64),
        "cs_coupon_amt": cs_coupon,
        "cs_net_profit": ((sp - whole) * qty).astype(np.int64),
    }

    # ---- web_sales ----------------------------------------------------
    w_lines, n_ws, w_rep = _fact(n_ws_o, 1, 5)
    w_date = rng.integers(0, N_DATE - 125, n_ws_o)
    w_cust = rng.integers(0, n_cust, n_ws_o)
    w_addr = rng.integers(0, n_addr, n_ws_o)
    w_site = rng.integers(0, n_web, n_ws_o)
    qty = rng.integers(1, 101, n_ws).astype(np.int32)
    whole = _dec(rng, n_ws, 1.0, 100.0)
    lp = (whole * rng.integers(100, 201, n_ws) // 100).astype(np.int64)
    sp = (lp * rng.integers(20, 101, n_ws) // 100).astype(np.int64)
    web_sales = {
        "ws_sold_date_sk": w_date[w_rep].astype(np.int64),
        "ws_ship_date_sk": (w_date[w_rep]
                            + rng.integers(1, 121, n_ws)).astype(np.int64),
        "ws_item_sk": rng.integers(0, n_item, n_ws).astype(np.int64),
        "ws_bill_customer_sk": w_cust[w_rep].astype(np.int64),
        "ws_bill_addr_sk": w_addr[w_rep].astype(np.int64),
        "ws_web_site_sk": w_site[w_rep].astype(np.int64),
        "ws_ship_mode_sk": rng.integers(0, n_sm, n_ws).astype(np.int64),
        "ws_warehouse_sk": rng.integers(0, n_wh, n_ws).astype(np.int64),
        "ws_promo_sk": rng.integers(0, n_promo, n_ws).astype(np.int64),
        "ws_order_number": w_rep.astype(np.int64),
        "ws_quantity": qty,
        "ws_wholesale_cost": whole,
        "ws_list_price": lp,
        "ws_sales_price": sp,
        "ws_ext_discount_amt": ((lp - sp) * qty).astype(np.int64),
        "ws_ext_sales_price": (sp * qty).astype(np.int64),
        "ws_ext_wholesale_cost": (whole * qty).astype(np.int64),
        "ws_net_paid": (sp * qty).astype(np.int64),
        "ws_net_profit": ((sp - whole) * qty).astype(np.int64),
    }

    # ---- inventory: weekly snapshots ----------------------------------
    inv_dates = np.arange(0, N_DATE, 7, dtype=np.int64)
    ii, ww, dd = np.meshgrid(np.arange(n_item), np.arange(n_wh),
                             inv_dates[::4], indexing="ij")
    inventory = {
        "inv_item_sk": ii.ravel().astype(np.int64),
        "inv_warehouse_sk": ww.ravel().astype(np.int64),
        "inv_date_sk": dd.ravel().astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, ii.size).astype(np.int32),
    }

    return {
        "date_dim": date_dim, "time_dim": time_dim, "item": item,
        "store": store, "customer": customer,
        "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "household_demographics": household_demographics,
        "promotion": promotion, "warehouse": warehouse,
        "ship_mode": ship_mode, "web_site": web_site,
        "store_sales": store_sales, "catalog_sales": catalog_sales,
        "web_sales": web_sales, "inventory": inventory,
    }


DDL = """
create table if not exists date_dim (
  d_date_sk bigint, d_date date, d_year int, d_moy int, d_dom int,
  d_qoy int, d_dow int, d_day_name text, d_month_seq int, d_week_seq int
) distributed replicated;
create table if not exists time_dim (
  t_time_sk bigint, t_hour int, t_minute int
) distributed replicated;
create table if not exists item (
  i_item_sk bigint, i_item_id text, i_item_desc text,
  i_current_price decimal(7,2), i_wholesale_cost decimal(7,2),
  i_brand_id int, i_brand text,
  i_class_id int, i_class text, i_category_id int, i_category text,
  i_manufact_id int, i_manufact text, i_manager_id int
) distributed by (i_item_sk);
create table if not exists store (
  s_store_sk bigint, s_store_id text, s_store_name text,
  s_company_name text, s_state text, s_county text, s_city text,
  s_zip text, s_gmt_offset decimal(5,2)
) distributed replicated;
create table if not exists customer (
  c_customer_sk bigint, c_customer_id text, c_current_cdemo_sk bigint,
  c_current_hdemo_sk bigint, c_current_addr_sk bigint, c_first_name text,
  c_last_name text, c_salutation text, c_preferred_cust_flag text,
  c_birth_month int, c_birth_year int, c_birth_country text
) distributed by (c_customer_sk);
create table if not exists customer_address (
  ca_address_sk bigint, ca_state text, ca_county text, ca_city text,
  ca_zip text, ca_country text, ca_gmt_offset decimal(5,2),
  ca_location_type text
) distributed by (ca_address_sk);
create table if not exists customer_demographics (
  cd_demo_sk bigint, cd_gender text, cd_marital_status text,
  cd_education_status text, cd_purchase_estimate int,
  cd_credit_rating text, cd_dep_count int
) distributed by (cd_demo_sk);
create table if not exists household_demographics (
  hd_demo_sk bigint, hd_income_band_sk bigint, hd_buy_potential text,
  hd_dep_count int, hd_vehicle_count int
) distributed replicated;
create table if not exists promotion (
  p_promo_sk bigint, p_channel_dmail text, p_channel_email text,
  p_channel_tv text, p_channel_event text
) distributed replicated;
create table if not exists warehouse (
  w_warehouse_sk bigint, w_warehouse_name text, w_warehouse_sq_ft int,
  w_state text
) distributed replicated;
create table if not exists ship_mode (
  sm_ship_mode_sk bigint, sm_type text, sm_carrier text
) distributed replicated;
create table if not exists web_site (
  web_site_sk bigint, web_name text
) distributed replicated;
create table if not exists store_sales (
  ss_sold_date_sk bigint, ss_sold_time_sk bigint, ss_item_sk bigint,
  ss_customer_sk bigint, ss_cdemo_sk bigint, ss_hdemo_sk bigint,
  ss_addr_sk bigint, ss_store_sk bigint, ss_promo_sk bigint,
  ss_ticket_number bigint, ss_quantity int,
  ss_wholesale_cost decimal(7,2), ss_list_price decimal(7,2),
  ss_sales_price decimal(7,2), ss_ext_discount_amt decimal(7,2),
  ss_ext_sales_price decimal(7,2), ss_ext_wholesale_cost decimal(7,2),
  ss_ext_list_price decimal(7,2), ss_ext_tax decimal(7,2),
  ss_coupon_amt decimal(7,2), ss_net_paid decimal(7,2),
  ss_net_profit decimal(7,2)
) distributed by (ss_item_sk);
create table if not exists catalog_sales (
  cs_sold_date_sk bigint, cs_ship_date_sk bigint,
  cs_bill_customer_sk bigint, cs_bill_cdemo_sk bigint,
  cs_bill_addr_sk bigint, cs_ship_mode_sk bigint, cs_warehouse_sk bigint,
  cs_item_sk bigint, cs_promo_sk bigint, cs_order_number bigint,
  cs_quantity int, cs_wholesale_cost decimal(7,2),
  cs_list_price decimal(7,2), cs_sales_price decimal(7,2),
  cs_ext_discount_amt decimal(7,2), cs_ext_sales_price decimal(7,2),
  cs_ext_wholesale_cost decimal(7,2), cs_coupon_amt decimal(7,2),
  cs_net_profit decimal(7,2)
) distributed by (cs_item_sk);
create table if not exists web_sales (
  ws_sold_date_sk bigint, ws_ship_date_sk bigint, ws_item_sk bigint,
  ws_bill_customer_sk bigint, ws_bill_addr_sk bigint,
  ws_web_site_sk bigint, ws_ship_mode_sk bigint, ws_warehouse_sk bigint,
  ws_promo_sk bigint, ws_order_number bigint, ws_quantity int,
  ws_wholesale_cost decimal(7,2), ws_list_price decimal(7,2),
  ws_sales_price decimal(7,2), ws_ext_discount_amt decimal(7,2),
  ws_ext_sales_price decimal(7,2), ws_ext_wholesale_cost decimal(7,2),
  ws_net_paid decimal(7,2), ws_net_profit decimal(7,2)
) distributed by (ws_item_sk);
create table if not exists inventory (
  inv_item_sk bigint, inv_warehouse_sk bigint, inv_date_sk bigint,
  inv_quantity_on_hand int
) distributed by (inv_item_sk);
"""

_DEC_COLS = {
    "i_current_price", "i_wholesale_cost", "s_gmt_offset", "ca_gmt_offset",
    "ss_wholesale_cost", "ss_list_price", "ss_sales_price",
    "ss_ext_discount_amt", "ss_ext_sales_price", "ss_ext_wholesale_cost",
    "ss_ext_list_price", "ss_ext_tax", "ss_coupon_amt", "ss_net_paid",
    "ss_net_profit",
    "cs_wholesale_cost", "cs_list_price", "cs_sales_price",
    "cs_ext_discount_amt", "cs_ext_sales_price", "cs_ext_wholesale_cost",
    "cs_coupon_amt", "cs_net_profit",
    "ws_wholesale_cost", "ws_list_price", "ws_sales_price",
    "ws_ext_discount_amt", "ws_ext_sales_price", "ws_ext_wholesale_cost",
    "ws_net_paid", "ws_net_profit",
}


def load(db, scale: float = 1.0, seed: int = 20020101,
         tables: list[str] | None = None) -> dict[str, int]:
    """Create schema + bulk load into a Database -> {table: rows}."""
    db.sql(DDL)
    data = generate(scale, seed)
    for name, cols in data.items():
        if tables is not None and name not in tables:
            continue
        db.load_table(name, cols)
    return {k: len(next(iter(v.values()))) for k, v in data.items()}


def to_pandas(data: dict[str, dict]):
    """Oracle-side view (Coded decoded, decimals descaled to float)."""
    import pandas as pd

    out = {}
    for t, cols in data.items():
        df = {}
        for c, v in cols.items():
            if isinstance(v, Coded):
                df[c] = v.decode()
            elif c in _DEC_COLS:
                df[c] = np.asarray(v, dtype=np.float64) / 100.0
            else:
                df[c] = v
        out[t] = pd.DataFrame(df)
    return out
