"""Host-side SQL string function semantics.

One implementation shared by three lowering strategies (binder decides per
column encoding):
  - literal folding (all-constant arguments),
  - dictionary LUTs (function applied once per distinct value, device does
    an int32 gather — the TPU-native form of per-row varlena evaluation),
  - raw-TEXT host chains (applied per row at predicate staging / result
    decode, the fallback for high-cardinality columns).

Semantics follow PostgreSQL's varlena.c / oracle_compat.c behavior for the
common cases (1-based substring indexing, negative-start window clamping,
strpos returning 0 when absent); reference entry points:
src/backend/utils/adt/varlena.c (text_substr, textcat, textpos),
src/backend/utils/adt/oracle_compat.c (upper/lower/ltrim/rtrim/lpad/rpad).
"""

from __future__ import annotations

# name -> (min_args, max_args, result kind "str" | "int")
SPECS = {
    "upper": (1, 1, "str"),
    "lower": (1, 1, "str"),
    "trim": (1, 1, "str"),
    "ltrim": (1, 2, "str"),
    "rtrim": (1, 2, "str"),
    "substring": (2, 3, "str"),
    "substr": (2, 3, "str"),
    "replace": (3, 3, "str"),
    "left": (2, 2, "str"),
    "right": (2, 2, "str"),
    "lpad": (2, 3, "str"),
    "rpad": (2, 3, "str"),
    "concat": (1, None, "str"),   # bound from x || y; extras = (prefix, suffix)
    "reverse": (1, 1, "str"),
    "length": (1, 1, "int"),
    "char_length": (1, 1, "int"),
    "character_length": (1, 1, "int"),
    "strpos": (2, 2, "int"),
}


def apply(name: str, s: str, *extra):
    """Apply one function to one string; extra = literal arguments."""
    if name == "upper":
        return s.upper()
    if name == "lower":
        return s.lower()
    if name == "trim":
        # PG btrim strips SPACES only by default (not all whitespace) —
        # and so does the device byte-window path (ops/scalar.py)
        return s.strip(" ")
    if name == "ltrim":
        return s.lstrip(extra[0]) if extra else s.lstrip(" ")
    if name == "rtrim":
        return s.rstrip(extra[0]) if extra else s.rstrip(" ")
    if name in ("substring", "substr"):
        start = int(extra[0])
        if len(extra) == 1:
            return s[max(start - 1, 0):]
        ln = int(extra[1])
        if ln < 0:
            raise ValueError("negative substring length not allowed")
        # PG: the window is [start, start+ln); a start < 1 shortens it
        end = start - 1 + ln
        return s[max(start - 1, 0):max(end, 0)]
    if name == "replace":
        return s.replace(extra[0], extra[1])
    if name == "left":
        n = int(extra[0])
        return s[:n] if n >= 0 else s[: max(len(s) + n, 0)]
    if name == "right":
        n = int(extra[0])
        if n >= 0:
            return s[len(s) - n:] if n else ""
        return s[min(-n, len(s)):]
    if name == "lpad":
        n = int(extra[0])
        fill = extra[1] if len(extra) > 1 else " "
        if n <= len(s):
            return s[:n]
        pad = (fill * n)[: n - len(s)] if fill else ""
        return pad + s
    if name == "rpad":
        n = int(extra[0])
        fill = extra[1] if len(extra) > 1 else " "
        if n <= len(s):
            return s[:n]
        pad = (fill * n)[: n - len(s)] if fill else ""
        return s + pad
    if name == "concat":
        prefix, suffix = extra
        return f"{prefix}{s}{suffix}"
    if name == "reverse":
        return s[::-1]
    if name in ("length", "char_length", "character_length"):
        return len(s)
    if name == "strpos":
        return s.find(extra[0]) + 1
    raise ValueError(f"unknown string function {name}")


def apply_chain(s: str, chain) -> object:
    """Apply a sequence of [name, *extras] steps to one string."""
    for step in chain:
        s = apply(step[0], s, *step[1:])
    return s
