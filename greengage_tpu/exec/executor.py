"""Executor: stage inputs, run the compiled SPMD program, gather, finalize.

The QD-side ExecutorStart/Run/End (src/backend/executor/execMain.c) plus
Gather Motion receive (nodeMotion.c:378) in one place:

  - stage: per-segment storage columns padded to static capacity and
    device_put with the seg sharding (the scan's tuple delivery)
  - run: the jitted shard_map program; overflow flags trigger a re-compile
    at the next size tier (spill/flow-control analog)
  - gather: device->host fetch of every segment's shard (Gather Motion);
    SEGMENT_GENERAL results read one segment only
  - finalize: merge-sort by the plan's merge keys, OFFSET/LIMIT trim,
    dictionary decode of TEXT outputs
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

import jax

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.exec import staging
from greengage_tpu.runtime import lockdebug
from greengage_tpu.exec.compile import (VALID_PREFIX, Compiler, CompileResult,
                                        _pow2)
from greengage_tpu.parallel.mesh import seg_sharding
from greengage_tpu.planner.locus import LocusKind
from greengage_tpu.runtime import interrupt
from greengage_tpu.runtime import memaccount
from greengage_tpu.runtime import overload as _overload
from greengage_tpu.runtime import trace as _trace
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import (DEFAULT_BUCKETS_MB, counters,
                                          histograms)
from greengage_tpu.runtime.runaway import TRACKER

# per-statement I/O accounting reported in Result.stats["scan_io"] and the
# EXPLAIN ANALYZE host-data-path lines (counter deltas, never wall clocks,
# so tests can assert them deterministically)
SCAN_COUNTERS = ("scan_files_read", "scan_bytes_decoded", "scan_cache_hit",
                 "scan_cache_miss", "scan_cache_evict")


class QueryError(RuntimeError):
    pass


class AdmissionError(QueryError):
    """Raised ONLY for the vmem admission rejection (est_bytes > limit) —
    the signal the spill machinery keys its escalation on."""
    pass


class BatchFallback(Exception):
    """A batched-serving window cannot run as one program (admission
    ceiling, overflow flags, unsignable shape): every member re-runs
    serially through the classic path, which owns retries and spill.
    Never surfaces to a client — it only routes execution."""
    pass


class OutOfDeviceMemory(QueryError):
    """The device allocator refused the program (XLA RESOURCE_EXHAUSTED)
    after admission let it through — the typed OOM the reference's
    memaccounting.c dumps an owner tree for. Carries the forensics the
    session writes to ``mem-<statement id>.json``: the per-statement
    accounting snapshot, the offending executable's memory analysis (when
    XLA reported one), and the admission-time estimate."""

    def __init__(self, message: str, snapshot: dict | None = None,
                 mem_analysis: dict | None = None, est_bytes: int = 0):
        super().__init__(message)
        self.snapshot = snapshot or {}
        self.mem_analysis = mem_analysis
        self.est_bytes = int(est_bytes)


def effective_limit_bytes(settings) -> int:
    """Per-query device-memory ceiling: the tighter of the hardware vmem
    guard and the resource queue's cap (queue-capped queries spill rather
    than fail, like workfile-bound queries under the reference's resource
    queues). 0 = unlimited."""
    limit = settings.vmem_protect_limit_mb * (1 << 20)
    qcap = int(getattr(settings, "resource_queue_memory_mb", 0)) << 20
    if qcap and (not limit or qcap < limit):
        limit = qcap
    from greengage_tpu.runtime.resgroup import current_memory_limit_mb

    gcap = current_memory_limit_mb() << 20   # thread's resource group share
    if gcap and (not limit or gcap < limit):
        limit = gcap
    return limit


@dataclass
class Result:
    columns: list[str]
    cols: dict[str, np.ndarray]
    valids: dict[str, np.ndarray | None]
    _order: list[str]
    wall_ms: float = 0.0
    plan_text: str = ""
    # per-query instrumentation (cdbexplain_recvExecStats analog)
    stats: dict = None

    def __len__(self):
        for c in self._order:
            return len(self.cols[c])
        return 0

    def rows(self) -> list[tuple]:
        n = len(self)
        out = []
        for i in range(n):
            row = []
            for cid in self._order:
                v = self.valids.get(cid)
                if v is not None and not v[i]:
                    row.append(None)
                else:
                    row.append(self.cols[cid][i])
            out.append(tuple(row))
        return out

    def to_pandas(self):
        import pandas as pd

        data = {}
        names = []
        seen: dict = {}
        for name in self.columns:   # dedupe: two count() outputs must not
            k = seen.get(name, 0)   # collapse into one DataFrame column
            seen[name] = k + 1
            names.append(name if k == 0 else f"{name}_{k}")
        for name, cid in zip(names, self._order):
            col = self.cols[cid]
            v = self.valids.get(cid)
            if v is not None:
                col = np.where(v, col, None) if col.dtype == object else \
                    pd.array(col, dtype="object")
                if not isinstance(col, np.ndarray):
                    col = np.asarray(self.cols[cid], dtype=object)
                    col[~v] = None
            data[name] = col
        return pd.DataFrame(data)


class EndpointBatch:
    """A completed mesh program whose per-segment output shards are held
    (on host) for endpoint-at-a-time retrieval; the backing store of one
    parallel retrieve cursor.

    Shards are COMPACTED to their live rows at construction: an open
    cursor pins memory proportional to its actual result, not to the
    program's static nseg x capacity padding (a selective cursor over a
    big table would otherwise pin the whole scan capacity until CLOSE)."""

    def __init__(self, comp, flat, snapshot, raw: bool, nseg: int):
        self.comp = comp
        self.snapshot = snapshot
        self.raw = raw
        # replicated below-gather locus: a single endpoint carries the
        # whole (identical) result
        rep = comp.gather_child_locus.kind in (LocusKind.SEGMENT_GENERAL,
                                               LocusKind.GENERAL)
        self.nendpoints = 1 if rep else nseg
        ncols = len(comp.out_cols)
        cap = comp.capacity
        sel = np.asarray(flat[2 * ncols]).reshape(nseg, cap)
        self.segs: list[tuple[dict, dict]] = []
        for k in range(self.nendpoints):
            m = np.asarray(sel[k], bool)
            cols, valids = {}, {}
            for i, c in enumerate(comp.out_cols):
                cols[c.id] = np.asarray(flat[2 * i]).reshape(nseg, cap)[k][m]
                valids[c.id] = np.asarray(
                    flat[2 * i + 1]).reshape(nseg, cap)[k][m]
            self.segs.append((cols, valids))


class Executor:
    def __init__(self, catalog, store, mesh, nseg: int, settings,
                 multihost=None):
        self.catalog = catalog
        self.store = store
        self.mesh = mesh
        self.nseg = nseg
        self.settings = settings
        self.multihost = multihost    # parallel.multihost.MultihostRuntime
        # planner/feedback.py store, wired by the owning Database: gives
        # admission a persisted measured footprint and cap hints for
        # shapes this PROCESS has never dispatched (restart / standby
        # promotion). Single-host only at every read site — feedback
        # state is per-process and must not steer lockstep branches.
        self.feedback = None
        # staged device inputs live in the store's byte-accounted LRU
        # registry (storage/blockcache.py): bounded within a manifest
        # version, evicted by recency against scan_cache_limit_mb
        self._stage_cache = store.blockcache.cache("stage")
        # compiled-program cache (the gang-reuse analog), REAL LRU:
        # (statement signature, shape signature, fused_disabled) ->
        # CompileResult. The shape signature (Compiler.shape_signature)
        # captures everything the trace reads — bucketed capacities,
        # dictionary fingerprints, consts digest, param dtypes — so a
        # manifest-version bump that stays inside every capacity bucket
        # and grows no dictionary REUSES the hot XLA executable instead
        # of recompiling. Bounded by the plan_cache_size GUC.
        #
        # _cache_mu guards ALL program-cache bookkeeping (_plan_cache,
        # _cap_hints, _sig_memo, _fused_failed, _dyn_prune_cache): the
        # batch-serving stager mutates these concurrently with statement
        # threads (gg check races), and the old GIL-reliant try/KeyError
        # defenses only made lost updates quiet, not absent. RLock:
        # _cache_program -> _on_program_evicted nests. Critical sections
        # are dict ops only — never a compile, never device work.
        self._cache_mu = lockdebug.named(threading.RLock(),
                                         "executor._cache_mu")
        self._plan_cache: OrderedDict = lockdebug.shared(
            OrderedDict(), "executor._plan_cache")
        # statements whose fused pallas kernel failed to lower on this
        # backend: later runs skip the pallas attempt entirely instead of
        # paying a failed compile + XLA recompile every execution
        self._fused_failed: set = set()
        self.last_fused_error: str | None = None
        # runtime cardinality feedback (VERDICT r3 weak #3): the exact
        # counts the device reports for overflow-capable nodes (join
        # expansion totals, agg group counts, gather live rows) persist
        # per statement, so after DML bumps the manifest version the NEXT
        # compile sizes those capacities right instead of re-discovering
        # them through overflow-retry recompiles. cache_key -> {nid: cap},
        # LRU (recency = last record OR last use) under a fixed backstop
        # bound; the primary lifetime tie is _on_program_evicted
        self._cap_hints: OrderedDict = lockdebug.shared(
            OrderedDict(), "executor._cap_hints")
        # memoized shape signatures (see the dispatch loop in run());
        # insertion-order bounded — entries for dead versions age out
        self._sig_memo: OrderedDict = OrderedDict()
        # per-DISPATCH staging context (row ranges, aux tables, prune
        # stats): the serving stager stages batch k+1 WHILE a statement
        # thread stages its own classic dispatch on the same Executor, so
        # these travel per-thread — plain attributes were a cross-role
        # clobber (gg check races)
        self._tls = threading.local()

    # -- multihost spill-schedule parity (docs/PERF.md "Data movement") --
    # The tiered workfile's pass/bucket schedules are pure functions of
    # compiled estimates + settings, so every gang member computes the
    # same one. These hooks make that a VERIFIED invariant instead of a
    # hope: the coordinator arms recording per statement, every schedule
    # decision is noted (and broadcast one-way to the workers for
    # observability), workers ship the schedule they actually ran in
    # their completion ack, and the session compares. Single-host runs
    # never arm recording, so note() is a no-op there.
    def begin_spill_schedule(self) -> None:
        self._tls.spill_sched = []

    def note_spill_schedule(self, kind: str, **info) -> None:
        steps = getattr(self._tls, "spill_sched", None)
        if steps is None:
            return
        entry = {"kind": kind, **info}
        steps.append(entry)
        mh = self.multihost
        if mh is not None and getattr(mh, "is_coordinator", False):
            ch = getattr(mh, "channel", None)
            if ch is not None:
                try:
                    # one-way frame (workers' serve loop drops unknown
                    # ops): the schedule lands on every host's control
                    # log even if the statement later dies
                    ch.send({"op": "spill_schedule", **entry})
                except Exception:
                    pass   # observability must never fail the statement

    def collect_spill_schedule(self) -> list:
        steps = getattr(self._tls, "spill_sched", None)
        self._tls.spill_sched = None
        return steps or []

    # -- per-thread staging context (source-compatible properties) -----
    @property
    def _row_ranges(self):
        return getattr(self._tls, "row_ranges", {})

    @_row_ranges.setter
    def _row_ranges(self, value):
        self._tls.row_ranges = value

    @property
    def _aux_tables(self):
        return getattr(self._tls, "aux_tables", {})

    @_aux_tables.setter
    def _aux_tables(self, value):
        self._tls.aux_tables = value

    @property
    def _last_prune_stats(self):
        return getattr(self._tls, "last_prune_stats", {})

    @_last_prune_stats.setter
    def _last_prune_stats(self, value):
        self._tls.last_prune_stats = value

    @property
    def _last_dyn_stats(self):
        return getattr(self._tls, "last_dyn_stats", {})

    @_last_dyn_stats.setter
    def _last_dyn_stats(self, value):
        self._tls.last_dyn_stats = value

    # ------------------------------------------------------------------
    def run(self, plan, consts: dict, out_cols, cache_key=None,
            raw: bool = False, instrument: bool = False,
            scan_cap_override=None, row_ranges=None, aux_tables=None,
            allow_spill: bool = True, deferred: bool = False,
            no_direct: bool = False) -> Result:
        self._row_ranges = row_ranges or {}
        self._aux_tables = aux_tables or {}
        t0 = time.monotonic()
        snapshot = self.store.manifest.snapshot()
        version = snapshot.get("version", 0)
        with self._cache_mu:
            hints = dict(self._cap_hints.get(cache_key) or {})
            if hints:
                self._cap_hints.move_to_end(cache_key)
            fused_disabled = cache_key is not None \
                and cache_key in self._fused_failed
        if not hints and cache_key is not None and self.multihost is None \
                and self.feedback is not None:
            # persisted cap hints (feedback store): a restarted process
            # sizes overflow-capable capacities right on its FIRST
            # dispatch instead of re-discovering them via overflow-retry
            hints = dict(self.feedback.caps(cache_key))
        cap_overrides: dict = dict(hints)
        pack_disabled: set = set()
        TRACKER.enter()   # nested spill passes share the statement entry
        try:
            return self._run_tiers(
                plan, consts, out_cols, cache_key, raw, instrument,
                scan_cap_override, row_ranges, aux_tables, allow_spill,
                deferred, no_direct, t0, snapshot, version,
                hints, cap_overrides, pack_disabled, fused_disabled)
        finally:
            TRACKER.release()

    def _run_tiers(self, plan, consts, out_cols, cache_key, raw, instrument,
                   scan_cap_override, row_ranges, aux_tables, allow_spill,
                   deferred, no_direct, t0, snapshot, version,
                   hints, cap_overrides, pack_disabled,
                   fused_disabled) -> Result:
        last_err = None
        tier = 0
        attempts = 0
        # hoisted-literal parameter vector (sql/paramize.py): values feed
        # the program as traced inputs and resolve pushed prune predicates
        pvec = (consts or {}).get("@params@")
        # tiers grow capacities; a key-packing bounds violation (stale
        # ANALYZE stats) instead re-runs the SAME tier unpacked, so the
        # attempt bound covers both kinds of retry
        while tier < self.settings.motion_retry_tiers \
                and attempts < self.settings.motion_retry_tiers + 4:
            attempts += 1
            # retry-tier boundary = a CHECK_FOR_INTERRUPTS site: a flag
            # set while the previous attempt ran (user cancel, statement
            # timeout, runaway cleaner) terminates the statement here
            interrupt.check_interrupts()
            # fused_disabled programs cache under their own key: a backend
            # that can't lower the pallas kernel still gets gang reuse of
            # the working XLA fallback program (advisor r3). Feedback
            # hints are deterministic inputs folded into the shape
            # signature (they size capacities); only RUNTIME overrides (an
            # overflow retry in flight) disable caching.
            ck = None
            sig_comp = None
            if cache_key is not None and cap_overrides == hints \
                    and not instrument and not scan_cap_override \
                    and not row_ranges and not aux_tables \
                    and not pack_disabled:
                # signature memo: the digest is a pure function of these
                # inputs (seg counts and dictionary growth always bump the
                # manifest version; the bound plan is version-keyed in the
                # session cache), so steady-state program-cache hits skip
                # the whole-plan signature walk
                mk = (cache_key, version, tier,
                      tuple(sorted(cap_overrides.items())),
                      fused_disabled, no_direct,
                      Compiler.codegen_settings_sig(self.settings))
                try:
                    sig, sig_comp = self._memo_signature(
                        mk,
                        lambda: Compiler(self.catalog, self.store,
                                         self.mesh, self.nseg, consts,
                                         self.settings, tier=tier,
                                         cap_overrides=cap_overrides,
                                         multihost=self.multihost is not None,
                                         fused_disabled=fused_disabled,
                                         no_direct=no_direct),
                        plan, snapshot)
                except Exception:
                    # unsignable shape (e.g. evicted transient raw
                    # dict): compile uncached; counted so a signature
                    # bug shows up as a visible reuse regression, not
                    # silence
                    counters.inc("program_cache_unsignable")
                    sig, sig_comp = None, None
                if sig is not None:
                    # trailing 0 = the unbatched program; batched serving
                    # keys its width buckets in the same LRU (run_batch)
                    ck = (cache_key, sig, fused_disabled, 0)
            # fetch + recency bump in one _cache_mu section: a concurrent
            # statement's eviction can no longer interleave (the value
            # object stays alive once fetched either way)
            with self._cache_mu:
                comp = self._plan_cache.get(ck) if ck is not None else None
                was_cached = comp is not None
                if was_cached:
                    self._plan_cache.move_to_end(ck)
            compile_ms = 0.0
            if was_cached:
                counters.inc("program_cache_hit")
            else:
                if ck is not None:
                    counters.inc("program_cache_miss")
                t_comp = time.monotonic()
                with _trace.span("compile", tier=tier, cached=False):
                    if sig_comp is not None:
                        # reuse the signature walk's Compiler (same args by
                        # construction on this branch: the cacheable gate
                        # above pins instrument/overrides/aux off)
                        comp = sig_comp.compile(plan)
                    else:
                        comp = Compiler(self.catalog, self.store, self.mesh,
                                        self.nseg, consts, self.settings,
                                        tier=tier, cap_overrides=cap_overrides,
                                        instrument=instrument,
                                        multihost=self.multihost is not None,
                                        scan_cap_override=scan_cap_override,
                                        aux_tables=aux_tables,
                                        pack_disabled=pack_disabled,
                                        fused_disabled=fused_disabled,
                                        no_direct=no_direct).compile(plan)
                compile_ms = (time.monotonic() - t_comp) * 1e3
                if ck is not None:
                    # keep the compiled SPMD program for repeated dispatch
                    # of the same statement shape; LRU-bounded (each entry
                    # pins an XLA executable), with cap-hint / fused-failed
                    # bookkeeping evicted alongside the last program of a
                    # statement (unbounded-growth fix, ISSUE 5)
                    self._cache_program(ck, comp)
            limit = effective_limit_bytes(self.settings)
            if self.multihost is None:
                # memory-pressure brownout (runtime/overload.py): scale
                # the admission ceiling down so borderline statements
                # demote to the spill tier instead of racing a pressured
                # allocator. Single-host only — the factor is
                # process-local state and would desync the multihost
                # lockstep spill decision (est_bytes + settings only)
                limit = _overload.CONTROLLER.scaled_vmem(limit)
            # admission charge: the MEASURED per-segment executable
            # footprint when the executable is warm and the backend
            # reports real temps, else the compile-time estimate
            # (_admission_bytes) — four PRs of capacity bucketing finally
            # admit against ground truth on silicon
            admit_bytes, admit_measured = self._admission_bytes(
                comp, cache_key)
            if limit and admit_bytes > limit:
                if deferred:
                    raise QueryError(
                        f"parallel retrieve cursor would hold ~"
                        f"{admit_bytes >> 20} MB per segment, above the "
                        f"{limit >> 20} MB memory ceiling; cursors pin the "
                        "whole result and cannot spill")
                if allow_spill:
                    # host-offload spill (exec/spill.py): partition a
                    # probe-linear (or inner-join build) table into passes
                    # that fit, merge the captured partial states /
                    # deduped keys on a final pass. Multihost-safe: the
                    # pass decision is deterministic (est_bytes +
                    # settings) and every process gathers identical
                    # replicated results, so workers take the same
                    # branches in lockstep.
                    from greengage_tpu.exec import spill

                    try:
                        return self._spill_fallback(plan, consts, out_cols,
                                                    raw, instrument)
                    except spill.NotSpillable:
                        raise QueryError(
                            f"query would allocate ~"
                            f"{admit_bytes >> 20} MB "
                            f"per segment, above vmem_protect_limit_mb="
                            f"{self.settings.vmem_protect_limit_mb}, and "
                            "its shape is not spillable (no "
                            "partial-aggregate cut or sort over a "
                            "single-scan probe table)")
                raise AdmissionError(
                    f"query would allocate ~{admit_bytes >> 20} MB per "
                    f"segment, above the {limit >> 20} MB memory ceiling "
                    "(vmem protection / resource queue; raise the limit or "
                    "reduce the data)")
            # mid-flight enforcement (runaway_cleaner.c analog): ledger
            # what this statement will ACTUALLY hold (post-spill-decision
            # estimate), run the red-zone scan, and take any cancellation
            # aimed at us — a tier or spill-pass boundary is the XLA
            # CHECK_FOR_INTERRUPTS. Multihost: DISABLED — a per-process
            # tracker cancels nondeterministically across the mesh, and a
            # one-sided cancel desyncs the lockstep collectives (the
            # plan-hash invariant, parallel/multihost.py); the reference's
            # cleaner is likewise per-host vmem, not cluster-coordinated
            if self.multihost is None:
                # the cleaner prices victims by the same measured-when-warm
                # bytes admission charges — an over-estimated statement no
                # longer draws the red-zone cancellation for HBM it never
                # holds
                TRACKER.reprice(
                    admit_bytes,
                    int(getattr(self.settings,
                                "vmem_global_limit_mb", 0)) << 20,
                    float(getattr(self.settings, "runaway_red_zone", 0.9)),
                    measured=admit_measured)
                TRACKER.check()
            # host-data-path breakdown (EXPLAIN ANALYZE + bench microbench):
            # staging wall vs device compute vs result fetch, plus the scan
            # I/O counter deltas this statement caused
            io0 = {k: counters.get(k) for k in SCAN_COUNTERS}
            t_stage = time.monotonic()
            with _trace.span("stage", cat="stage",
                             tables=len(comp.input_spec)) as _sp_stage:
                inputs = self._stage(comp, snapshot, pvec)
                if comp.param_dtypes:
                    inputs = list(inputs) + [
                        self._put_param(np.asarray([v], dtype=dt))
                        for v, dt in zip(pvec.values, comp.param_dtypes)]
            t_compute = time.monotonic()
            stage_ms = (t_compute - t_stage) * 1e3
            scan_io = {k: counters.get(k) - io0[k] for k in SCAN_COUNTERS}
            _trace.annotate(_sp_stage, **scan_io)
            # last cancellation point before dispatch: once the program
            # is on the device it runs to this boundary (the documented
            # semantic — XLA programs cannot be preempted mid-flight)
            faults.check("cancel_before_dispatch")
            interrupt.check_interrupts()
            # measured memory accounting: AOT-compile once, attach XLA's
            # memory_analysis to the cached executable (warm hits reuse
            # it — zero re-analysis), and record the device owner on the
            # statement's account before the allocator commits to it
            self._ensure_mem_analysis(comp, inputs)
            if self.multihost is None and self.feedback is not None \
                    and cache_key is not None and comp.mem_analysis:
                _matot = (comp.mem_analysis["temp_bytes"]
                          + comp.mem_analysis.get("argument_bytes", 0)
                          + comp.mem_analysis.get("output_bytes", 0))
                # warm-shape calibration gauge: once the feedback store
                # predicts this shape's footprint (second execution on),
                # report the error of the PREDICTION, not of the planner
                # estimate — this is what collapses toward 0 warm
                _pred = self.feedback.measured_bytes(cache_key)
                if _pred:
                    counters.set("mem_est_error_pct", int(round(
                        100.0 * (_matot - _pred) / _pred)))
                self.feedback.note_measured(
                    cache_key, _matot,
                    comp.est_bytes * self._segments_per_device())
            _acct = memaccount.ACCOUNTS.current()
            if _acct is not None:
                _acct.set_device(comp.mem_analysis, comp.est_bytes)
            try:
                with _trace.span("dispatch", cat="device", tier=tier,
                                 est_bytes=comp.est_bytes):
                    if faults.check("device_oom"):
                        # faked allocator failure ('skip' type): the OOM
                        # classification/demotion path without needing a
                        # real 16 GB exhaustion in CI
                        raise RuntimeError(
                            "RESOURCE_EXHAUSTED: Out of memory while "
                            f"trying to allocate {comp.est_bytes} bytes "
                            "(fault injected: device_oom)")
                    flat = (comp.aot_fn or comp.device_fn)(*inputs)
                    # resolve async dispatch here so compute_ms is the
                    # device program (and a deferred pallas failure still
                    # lands in the retry logic below, not in device_get)
                    jax.block_until_ready(flat)
            except Exception as e:
                # a pallas lowering/compile failure on this backend must
                # not fail the query: retry the SAME tier on the pure-XLA
                # path and drop the poisoned cached program. Only programs
                # that actually embed the fused kernel AND errors that
                # carry pallas/Mosaic markers qualify — anything else
                # (OOM, interconnect) is a genuine runtime error, and a
                # transient one must not poison the fused memo.
                if fused_disabled or not comp.uses_fused \
                        or not self.settings.fused_dense_agg \
                        or not _is_pallas_error(e):
                    if memaccount.is_oom_error(e):
                        # OOM forensics + demotion (memaccounting.c's
                        # RESOURCE_EXHAUSTED dump): never a bare XLA
                        # traceback for an allocator refusal
                        return self._handle_oom(
                            e, comp, plan, consts, out_cols, raw,
                            instrument, allow_spill, deferred, tier)
                    raise
                fused_disabled = True
                self.last_fused_error = f"{type(e).__name__}: {e}"
                with self._cache_mu:
                    if cache_key is not None:
                        self._fused_failed.add(cache_key)
                    if ck is not None:
                        # plain pop, NOT _on_program_evicted: that would
                        # discard the fused-failed memo just recorded; the
                        # retry below immediately caches the unfused
                        # program for this statement, re-tying the
                        # bookkeeping to a live entry
                        self._plan_cache.pop(ck, None)
                continue
            t_fetch = time.monotonic()
            compute_ms = (t_fetch - t_compute) * 1e3
            # ONE device->host fetch for every output (per-transfer latency
            # through tunneled/remote device paths dwarfs per-byte cost)
            with _trace.span("fetch", cat="device") as _sp_f:
                flat = jax.device_get(list(flat))
            fetch_ms = (time.monotonic() - t_fetch) * 1e3
            _trace.annotate(_sp_f, bytes=int(sum(
                getattr(a, "nbytes", 0) for a in flat)))
            ncols = len(comp.out_cols)
            nflags = len(comp.flag_names)
            flags = dict(zip(comp.flag_names,
                             flat[2 * ncols + 1: 2 * ncols + 1 + nflags]))
            metrics = dict(zip(comp.metric_names,
                               flat[2 * ncols + 1 + nflags:]))
            dup = [k for k, v in flags.items() if k.startswith("join_dup") and v.any()]
            if dup:
                raise QueryError(
                    "hash join build side has duplicate keys; only unique-key "
                    "(PK-FK) hash joins are supported in this version")
            overflow = [k for k, v in flags.items()
                        if not k.startswith("join_dup") and v.any()]
            if not overflow:
                # cardinality feedback: persist the EXACT counts the
                # device reported so the next compile of this statement
                # (post-DML replan) sizes capacities right immediately;
                # metrics are device-reduced, so multihost processes
                # record identical hints and stay in lockstep
                if cache_key is not None and comp.flag_caps:
                    with self._cache_mu:
                        rec = self._cap_hints.setdefault(cache_key, {})
                        self._cap_hints.move_to_end(cache_key)
                        for _f, (nid, metric) in comp.flag_caps.items():
                            if metric in metrics:
                                need = (int(metrics[metric].flat[0])
                                        if self.multihost
                                        else int(np.max(metrics[metric])))
                                # pow2 bucket: small data drift re-records
                                # the SAME hint, so hint-sized programs
                                # keep their executable-cache entry
                                # across DML
                                rec[nid] = _pow2(need + max(need // 16, 64))
                        while len(self._cap_hints) > 512:
                            self._cap_hints.popitem(last=False)
                    if self.multihost is None and self.feedback is not None:
                        # mirror into the feedback store so a restarted
                        # process inherits the sizing (see run() seeding)
                        self.feedback.note_caps(cache_key, dict(rec))
                if deferred:
                    # parallel retrieve cursor: the program already ran and
                    # every segment's shard is on the host — finalization
                    # happens per-endpoint at RETRIEVE time
                    return EndpointBatch(comp, flat, snapshot, raw, self.nseg)
                with _trace.span("finalize", cat="host"):
                    res = self._finalize(comp, flat, snapshot, raw=raw)
                res.wall_ms = (time.monotonic() - t0) * 1e3
                if not was_cached:
                    # the first dispatch of a fresh program carries the
                    # XLA compile; fold it into the statement's compile
                    # cost (EXPLAIN ANALYZE "Plan cache" line, bench)
                    compile_ms += compute_ms
                    counters.inc("compile_ms", int(compile_ms))
                res.stats = {
                    "tiers_used": tier + 1,
                    "compiled": not was_cached,
                    "compile_ms": round(compile_ms, 1),
                    # host-data-path breakdown of the SUCCESSFUL attempt
                    "stage_ms": round(stage_ms, 2),
                    "compute_ms": round(compute_ms, 2),
                    "fetch_ms": round(fetch_ms, 2),
                    "scan_io": scan_io,
                    # True when the program embeds the fused pallas kernel
                    # (bench reports this: a silent XLA fallback must not
                    # masquerade as a pallas measurement)
                    "fused_kernel": bool(comp.uses_fused),
                    "segments": self.nseg,
                    # FTS/topology version the dispatch was bound against
                    # (bumped by mesh re-formation and mirror promotion;
                    # pjit resolves the mesh at call site, so a cached
                    # executable re-binds to the current topology without
                    # recompiling)
                    "topology_version": getattr(
                        getattr(self.catalog, "segments", None),
                        "version", 0),
                    "scan_tables": [t for t, *_ in comp.input_spec],
                    "direct_dispatch": {t: d for t, _, _, d, *_ in comp.input_spec
                                        if d is not None},
                    "partitions": {t: len(p) for t, _, _, _, _, p, _
                                   in comp.input_spec if p is not None},
                    "zone_prune": dict(getattr(self, "_last_prune_stats", {})),
                    # runtime PartitionSelector results: child partitions
                    # kept / total after the build-side key-value probe
                    "dynamic_prune": dict(getattr(self, "_last_dyn_stats", {})),
                    "below_gather_capacity": comp.capacity,
                    "rows_out": len(res),
                    # per-node row counters SUM across segments; capacity
                    # metrics report the per-segment max (multi-host:
                    # already device-reduced + replicated)
                    "metrics": {k: (int(v.flat[0]) if self.multihost
                                    else int(np.sum(v)) if k.startswith("nrows_")
                                    else int(np.max(v)))
                                for k, v in metrics.items()},
                    # nrows_* metrics are already psum-reduced on device
                    # under multihost (every process holds the cluster
                    # total replicated), so host-side summing there would
                    # over-count by the process count
                    "node_rows": {comp.node_rows[k]:
                                  (int(v.flat[0]) if self.multihost
                                   else int(np.sum(v)))
                                  for k, v in metrics.items()
                                  if k in comp.node_rows},
                    # measured memory accounting (docs/OBSERVABILITY.md):
                    # what admission charged, what XLA measured for the
                    # executable, and the statement's owner totals so far
                    "mem": self._mem_stats(comp, admit_bytes,
                                           admit_measured),
                }
                if instrument:
                    # per-node Memory annotation source (EXPLAIN ANALYZE)
                    res.stats["node_est_bytes"] = dict(comp.node_est_bytes)
                # latency histograms (the gpperfmon timing surface):
                # per-phase host-data-path distributions, exposed as
                # Prometheus histograms via `gg metrics`
                histograms.observe("stage_ms", stage_ms)
                histograms.observe("dispatch_ms", compute_ms)
                histograms.observe("fetch_ms", fetch_ms)
                if not was_cached:
                    # compile_latency_ms, NOT compile_ms: the legacy
                    # total-ms counter already owns that name and one
                    # exposition name cannot carry two TYPEs
                    histograms.observe("compile_latency_ms", compile_ms)
                return res
            # size the retry from exact cardinalities where the device
            # reported them (join expansion totals)
            pack_over = [f for f in overflow if f.startswith("pack_overflow")]
            capacity_over = [f for f in overflow
                             if not f.startswith("pack_overflow")]
            for fname in pack_over:
                pack_disabled.add(comp.flag_packs[fname])
            for fname in capacity_over:
                hint = comp.flag_caps.get(fname)
                if hint is not None:
                    plan_id, metric = hint
                    need = (int(metrics[metric].flat[0]) if self.multihost
                            else int(np.max(metrics[metric])))
                    cap_overrides[plan_id] = need + max(need // 16, 64)
            # a gather-compaction overflow carries its exact live count in
            # the cap override — re-run the SAME tier with just that slice
            # widened; bumping the tier would needlessly 4x every other
            # node and disable tier-0 direct joins (advisor r3)
            if [f for f in capacity_over
                    if not f.startswith("gather_compact_overflow")]:
                tier += 1
            last_err = f"capacity overflow in {overflow} at tier {tier}"
        raise QueryError(f"query exceeded capacity tiers: {last_err}")

    def finalize_endpoint(self, batch: "EndpointBatch", seg: int) -> Result:
        """RETRIEVE body: decode ONE segment's compacted shard of a
        deferred run (the retrieve-session path, reference: src/backend/
        cdb/endpoint/cdbendpointretrieve.c — there a direct segment
        connection, here a host-side per-shard decode)."""
        cols, valids = batch.segs[seg]
        # shallow dict copies: _present reassigns dict slots (merge/limit)
        return self._present(batch.comp, dict(cols), dict(valids),
                             batch.snapshot, batch.raw)

    def run_single(self, plan, consts, out_cols, raw=False,
                   scan_cap_override=None, row_ranges=None, aux_tables=None,
                   no_direct=False, instrument=False):
        """One spill pass: no recursive spilling, no plan caching.
        ``instrument`` flows through so EXPLAIN ANALYZE of a spilling
        statement still collects per-node row counts (summed across
        passes by the spill driver)."""
        return self.run(plan, consts, out_cols, cache_key=None, raw=raw,
                        scan_cap_override=scan_cap_override,
                        row_ranges=row_ranges, aux_tables=aux_tables,
                        allow_spill=False, no_direct=no_direct,
                        instrument=instrument)

    # ---- program-cache bookkeeping shared by the classic dispatch
    # ---- loop and the batched-serving path ---------------------------
    def _memo_signature(self, mk, make_compiler, plan, snapshot):
        """Memoized shape-signature walk -> (sig, walker Compiler or
        None when the memo hit). An unsignable shape raises through —
        callers choose their fallback (uncached compile / BatchFallback).
        The walker is returned so a compile on the miss path can reuse
        its scan collection instead of re-walking."""
        with self._cache_mu:
            sig = self._sig_memo.get(mk)
        if sig is not None:
            return sig, None
        comp = make_compiler()
        # the signature walk itself runs unlocked (it reads plan/manifest
        # state, not the memo); only the memo insert is serialized
        sig = comp.shape_signature(plan, snapshot)
        with self._cache_mu:
            self._sig_memo[mk] = sig
            while len(self._sig_memo) > 2048:
                self._sig_memo.popitem(last=False)
        return sig, comp

    def _cache_program(self, ck, comp) -> None:
        """Insert a compiled program into the bounded LRU; evictions
        drop their statement's cap-hint / fused-failed bookkeeping via
        _on_program_evicted (one policy for every caller)."""
        with self._cache_mu:
            self._plan_cache[ck] = comp
            limit_n = max(int(getattr(self.settings,
                                      "plan_cache_size", 128)), 1)
            while len(self._plan_cache) > limit_n:
                old_k, _old = self._plan_cache.popitem(last=False)
                self._on_program_evicted(old_k)

    # ---- vectorized serving (exec/batchserve.py) ---------------------
    # One XLA dispatch serves a whole admission window of same-shape
    # statements: their hoisted parameter vectors stack along a leading
    # member axis and the width-bucketed batched program (compile.py
    # batch_width) runs once over the shared staged inputs. Split into
    # prepare (compile/admit/stage) and dispatch (device) halves so the
    # serving pipeline can stage batch k+1 while batch k runs on device.

    def prepare_batch(self, plan, consts, out_cols, cache_key, pvec_rows):
        """Compile-or-reuse the width-bucketed batched program, admit it,
        and stage its (shared) table inputs plus the stacked parameter
        arrays. -> (comp, inputs, snapshot, compiled: bool). Raises
        BatchFallback when the batch cannot run as one program (admission
        ceiling, unsignable shape) — members then re-run serially."""
        width = len(pvec_rows)
        bucket = _pow2(max(width, 1))
        snapshot = self.store.manifest.snapshot()
        version = snapshot.get("version", 0)
        with self._cache_mu:
            hints = dict(self._cap_hints.get(cache_key) or {})
        # batched programs always disable the fused pallas kernel: the
        # dense-agg kernel has no vmap batching rule, and a mid-batch
        # lowering failure would cost every member a serial re-run
        mk = (cache_key, version, 0, tuple(sorted(hints.items())),
              True, False, Compiler.codegen_settings_sig(self.settings),
              "batch")
        try:
            sig, sig_comp = self._memo_signature(
                mk,
                lambda: Compiler(self.catalog, self.store, self.mesh,
                                 self.nseg, consts, self.settings,
                                 tier=0, cap_overrides=dict(hints),
                                 fused_disabled=True,
                                 batch_width=bucket),
                plan, snapshot)
        except Exception:
            counters.inc("program_cache_unsignable")
            raise BatchFallback("unsignable statement shape")
        ck = (cache_key, sig, True, bucket)
        with self._cache_mu:
            comp = self._plan_cache.get(ck)
            was_cached = comp is not None
            if was_cached:
                self._plan_cache.move_to_end(ck)
        if was_cached:
            counters.inc("program_cache_hit")
        else:
            counters.inc("program_cache_miss")
            t_comp = time.monotonic()
            with _trace.span("compile", cat="exec", batch_width=bucket,
                             cached=False):
                if sig_comp is None:
                    sig_comp = Compiler(self.catalog, self.store, self.mesh,
                                        self.nseg, consts, self.settings,
                                        tier=0, cap_overrides=dict(hints),
                                        fused_disabled=True,
                                        batch_width=bucket)
                comp = sig_comp.compile(plan)
            counters.inc("compile_ms",
                         int((time.monotonic() - t_comp) * 1e3))
            self._cache_program(ck, comp)
        # admission: est_bytes is already width-scaled (compile.py); the
        # measured footprint of a warm bucket takes over once the AOT
        # analysis ran — PR-10's ground truth bounding the batch width
        limit = effective_limit_bytes(self.settings)
        if cache_key is not None:
            # width-bucket-qualified feedback key: est/measured bytes are
            # width-scaled, so each bucket calibrates independently
            comp.fb_key = f"{cache_key}@w{bucket}"
        admit_bytes, _measured = self._admission_bytes(comp, comp.fb_key)
        if limit and admit_bytes > limit:
            raise BatchFallback(
                f"batched program would hold ~{admit_bytes >> 20} MB "
                f"per segment at width {bucket}, above the "
                f"{limit >> 20} MB ceiling")
        # staging: identical to the classic single-statement stage except
        # that parameter-valued prune predicates are DROPPED (pvec=None):
        # zone-map pruning by one member's values would starve its
        # batch-mates of blocks their rows live in. Value-pinned prune
        # predicates are shared by every member and stay active.
        self._row_ranges = {}
        self._aux_tables = {}
        with _trace.span("stage", cat="stage",
                         tables=len(comp.input_spec)) as _sp:
            inputs = list(self._stage(comp, snapshot, None))
            padded = list(pvec_rows) \
                + [pvec_rows[-1]] * (bucket - width)
            for slot, dt in enumerate(comp.param_dtypes):
                host = np.asarray([[pv.values[slot]] for pv in padded],
                                  dtype=dt)
                inputs.append(self._put_param(host))
        _trace.annotate(_sp, batch_width=width, batch_bucket=bucket)
        return comp, inputs, snapshot, not was_cached

    def dispatch_batch(self, comp: CompileResult, inputs) -> list:
        """Run a prepared batched program and fetch every output to host.
        The serving pipeline's device stage — runs on the dispatcher
        thread with NO statement context, so a member's cancellation can
        never abort its batch-mates (members are masked at demux)."""
        self._ensure_mem_analysis(comp, inputs)
        if comp.fb_key is not None and self.multihost is None \
                and self.feedback is not None and comp.mem_analysis:
            self.feedback.note_measured(
                comp.fb_key,
                comp.mem_analysis["temp_bytes"]
                + comp.mem_analysis.get("argument_bytes", 0)
                + comp.mem_analysis.get("output_bytes", 0),
                comp.est_bytes * self._segments_per_device())
        with _trace.span("dispatch", cat="device",
                         batch_width=comp.batch_width,
                         est_bytes=comp.est_bytes):
            faults.check("batch_dispatch")
            flat = (comp.aot_fn or comp.device_fn)(*inputs)
            jax.block_until_ready(flat)
        with _trace.span("fetch", cat="device") as _sp:
            flat = jax.device_get(list(flat))
        _trace.annotate(_sp, bytes=int(sum(
            getattr(a, "nbytes", 0) for a in flat)))
        return flat

    def batch_overflowed(self, comp: CompileResult, flat) -> list[str]:
        """Flag names any member tripped — capacity overflow, packing
        bounds, duplicate join keys. A batched program never retries in
        place (per-member capacity needs differ); any flag sends every
        member down the serial path, whose tier machinery handles it."""
        ncols_part = 2 * len(comp.out_cols) + 1
        out = []
        for j, name in enumerate(comp.flag_names):
            if np.asarray(flat[ncols_part + j]).any():
                out.append(name)
        return out

    def demux_batch(self, comp: CompileResult, flat, member: int,
                    snapshot) -> Result:
        """One member's Result from a fetched batched output: slice its
        row along the leading member axis and finalize exactly like a
        classic dispatch (merge keys, host LIMIT, TEXT decode)."""
        ncols_part = 2 * len(comp.out_cols) + 1
        member_flat = [np.asarray(flat[i])[member]
                       for i in range(ncols_part)]
        with _trace.span("finalize", cat="host", member=member):
            return self._finalize(comp, member_flat, snapshot, raw=False)

    def run_batch(self, plan, consts, out_cols, cache_key,
                  pvec_rows) -> list[Result]:
        """Synchronous prepare+dispatch+demux of one batch (the test and
        fallback surface; the serving pipeline calls the halves from its
        own stage/dispatch threads). Raises BatchFallback when the batch
        must be served serially."""
        comp, inputs, snapshot, compiled = self.prepare_batch(
            plan, consts, out_cols, cache_key, pvec_rows)
        flat = self.dispatch_batch(comp, inputs)
        over = self.batch_overflowed(comp, flat)
        if over:
            raise BatchFallback(f"overflow flags {over} at width "
                                f"{len(pvec_rows)}")
        out = []
        for m in range(len(pvec_rows)):
            res = self.demux_batch(comp, flat, m, snapshot)
            res.stats = {"batched": True, "batch_width": len(pvec_rows),
                         "batch_bucket": comp.batch_width,
                         "compiled": compiled, "segments": self.nseg}
            out.append(res)
        return out

    # ---- measured memory accounting (runtime/memaccount.py) ----------
    def _ensure_mem_analysis(self, comp: CompileResult, inputs) -> None:
        """First dispatch of a program: AOT-compile it (lower().compile())
        and attach XLA's memory_analysis — temp/argument/output/generated-
        code bytes — to the cached CompileResult. Dispatch then goes
        through the AOT executable, so the program still compiles exactly
        once (the AOT call path measures no slower than the jit wrapper),
        and every warm program-cache hit reuses both the executable and
        the analysis: ``mem_analysis_runs`` counts analyses, and tests
        assert a warm hit adds zero."""
        if comp.mem_failed or comp.aot_fn is not None \
                or not bool(getattr(self.settings,
                                    "mem_accounting_enabled", True)):
            return
        if self.multihost is not None:
            # multihost keeps the plain jit path: an AOT executable pins
            # the compile-time device assignment, and the PR-6 topology
            # re-formation contract depends on pjit re-binding cached
            # executables to the CURRENT mesh at call site; per-process
            # analysis state would also leak into admission and desync
            # the lockstep branch decisions (see _admission_bytes)
            return
        # serialize the first analysis per program: two server threads
        # cold-dispatching the same cached CompileResult must not both
        # pay the XLA compile; the loser of the race waits and reuses
        with comp.mem_lock:
            if comp.mem_failed or comp.aot_fn is not None:
                return
            try:
                comp.aot_fn = comp.device_fn.lower(*inputs).compile()
            except Exception:
                # a shape/backend the AOT path can't lower (incl. pallas
                # compile failures): latch off and fall back to the jit
                # path, which re-raises real errors into the dispatch
                # retry logic
                comp.mem_failed = True
                return
            try:
                ma = comp.aot_fn.memory_analysis()
                comp.mem_analysis = {
                    "argument_bytes": int(
                        getattr(ma, "argument_size_in_bytes", 0)),
                    "output_bytes": int(
                        getattr(ma, "output_size_in_bytes", 0)),
                    "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                    "generated_code_bytes": int(
                        getattr(ma, "generated_code_size_in_bytes", 0)),
                    "alias_bytes": int(
                        getattr(ma, "alias_size_in_bytes", 0)),
                }
            except Exception:
                comp.mem_failed = True   # executable stays dispatchable
                return
            counters.inc("mem_analysis_runs")
            total = (comp.mem_analysis["argument_bytes"]
                     + comp.mem_analysis["output_bytes"]
                     + comp.mem_analysis["temp_bytes"])
            histograms.observe("executable_mem_mb", total / 1e6,
                               buckets=DEFAULT_BUCKETS_MB)
            # estimate-vs-measured calibration gauge: the analysis is
            # per DEVICE (one SPMD module), so compare against the
            # estimate for the segments that device hosts
            est_dev = comp.est_bytes * self._segments_per_device()
            if est_dev > 0:
                counters.set("mem_est_error_pct", int(round(
                    100.0 * (total - est_dev) / est_dev)))

    def _admission_bytes(self, comp: CompileResult,
                         cache_key=None) -> tuple[int, bool]:
        """Bytes the admission check and runaway ledger charge for this
        program -> (bytes, measured?). Prefers the measured per-segment
        executable footprint once the executable is warm AND the backend
        has a real device allocator (memory_stats() reports one — TPU/
        GPU); falls back to the feedback store's persisted measurement of
        the same statement shape when THIS process hasn't analyzed it yet
        (restart, standby promotion). The CPU backend's memory_analysis
        covers host buffers that no HBM limit governs, so estimates keep
        governing there — and the vmem GUC semantics the spill tests pin
        stay estimate-driven."""
        ma = comp.mem_analysis
        # multihost NEVER prefers measured bytes: comp.mem_analysis is
        # per-process state (one worker's transient AOT failure would
        # flip only ITS admission/spill branch and desync the lockstep
        # collectives) — the spill decision must stay a pure function of
        # est_bytes + settings, the PR-3 determinism contract
        if ma and self.multihost is None \
                and bool(getattr(self.settings,
                                 "mem_accounting_enabled", True)) \
                and ma.get("temp_bytes", 0) > 0 \
                and memaccount.device_memory_stats() is not None:
            # memory_analysis describes the per-DEVICE SPMD module (one
            # device's shard of every buffer): scale to per-segment by
            # the segments each device hosts, not by nseg — on a 1-chip
            # backend all nseg segments share the device
            measured = (ma["temp_bytes"] + ma.get("argument_bytes", 0)
                        + ma.get("output_bytes", 0)) \
                // self._segments_per_device()
            if measured > 0:
                counters.inc("admission_measured_total")
                return measured, True
        if ma is None and cache_key is not None and self.multihost is None \
                and self.feedback is not None \
                and bool(getattr(self.settings,
                                 "mem_accounting_enabled", True)) \
                and memaccount.device_memory_stats() is not None:
            # a prior execution (possibly an earlier PROCESS — the store
            # persists beside the catalog) measured this shape: a cold
            # program still admits against ground truth
            mtot = self.feedback.measured_bytes(cache_key)
            if mtot:
                per_seg = int(mtot) // self._segments_per_device()
                if per_seg > 0:
                    counters.inc("admission_measured_total")
                    counters.inc("admission_measured_feedback_total")
                    return per_seg, True
        counters.inc("admission_estimated_total")
        return comp.est_bytes, False

    def _segments_per_device(self) -> int:
        ndev = max(int(getattr(getattr(self.mesh, "devices", None),
                               "size", 1) or 1), 1)
        return max(self.nseg // ndev, 1)

    def _mem_stats(self, comp: CompileResult, admit_bytes: int,
                   admit_measured: bool) -> dict:
        """The Result.stats['mem'] block: estimate vs measurement vs live
        device watermark (EXPLAIN ANALYZE's Memory lines + bench)."""
        out = {
            "est_bytes": int(comp.est_bytes),
            "admitted_bytes": int(admit_bytes),
            "admitted_by": "measured" if admit_measured else "estimate",
            "measured": (dict(comp.mem_analysis)
                         if comp.mem_analysis else None),
        }
        dstats = memaccount.device_memory_stats()
        if dstats is not None:
            out["device_bytes_in_use"] = int(dstats.get("bytes_in_use", 0))
            out["device_peak_bytes_in_use"] = int(
                dstats.get("peak_bytes_in_use", 0))
        acct = memaccount.ACCOUNTS.current()
        if acct is not None:
            out["owners"] = acct.owner_totals()
        return out

    def _spill_fallback(self, plan, consts, out_cols, raw, instrument):
        """Host-offload spill paths, shared by the admission rejection
        and the OOM demotion: partial-aggregate passes first, then
        window-partition passes over the PARTITION BY hash space, then
        the external-merge sort. Raises spill.NotSpillable through when
        no shape applies."""
        from greengage_tpu.exec import spill

        try:
            res, npasses = spill.spill_run(
                self, plan, consts, out_cols, raw, instrument=instrument)
        except spill.NotSpillable:
            try:
                # window-partition spill (exec/spill.py spill_window_run):
                # whole partitions per hash bucket, exact results
                res, npasses = spill.spill_window_run(
                    self, plan, consts, out_cols, raw,
                    instrument=instrument)
            except spill.NotSpillable:
                # external-merge sort spill (tuplesort role): ORDER BY
                # results merge on the host from per-pass device-sorted
                # runs
                res, npasses = spill.spill_sort_run(
                    self, plan, consts, out_cols, raw,
                    instrument=instrument)
        res.stats = dict(res.stats or {})
        res.stats["spill_passes"] = npasses
        return res

    def _handle_oom(self, e, comp, plan, consts, out_cols, raw, instrument,
                    allow_spill, deferred, tier):
        """A dispatched program hit RESOURCE_EXHAUSTED: build the typed
        OutOfDeviceMemory (accounting snapshot + the executable's memory
        analysis — the memaccounting.c OOM dump payload), then demote to
        the spill path ONCE when allowed (oom_spill_retry) before
        surfacing. Multihost never demotes: a one-sided runtime OOM is
        not a deterministic input, and a lone process entering the spill
        regime would desync the lockstep collectives."""
        counters.inc("oom_events")
        acct = memaccount.ACCOUNTS.current()
        snap = acct.snapshot() if acct is not None else {}
        snap["device_stats"] = memaccount.device_memory_stats()
        oom = OutOfDeviceMemory(
            f"out of device memory dispatching at tier {tier} "
            f"(estimated ~{comp.est_bytes >> 20} MB/segment): {e}",
            snapshot=snap, mem_analysis=comp.mem_analysis,
            est_bytes=comp.est_bytes)
        if allow_spill and not deferred and self.multihost is None \
                and bool(getattr(self.settings, "oom_spill_retry", True)):
            from greengage_tpu.exec import spill

            try:
                res = self._spill_fallback(plan, consts, out_cols, raw,
                                           instrument)
            except spill.NotSpillable:
                raise oom from e
            counters.inc("oom_spill_retries")
            res.stats["oom_demoted"] = True
            return res
        raise oom from e

    # ------------------------------------------------------------------
    def _local_segments(self):
        if self.multihost is None:
            return set(range(self.nseg))
        if not self.multihost.local_segments:
            from greengage_tpu.parallel.multihost import local_segment_positions

            self.multihost.local_segments = local_segment_positions()
        return set(s for s in self.multihost.local_segments if s < self.nseg)

    def _on_program_evicted(self, key) -> None:
        """A compiled program left the LRU: when it was the LAST program
        of its statement, drop the statement's cap-hint and fused-failed
        bookkeeping too — their lifetime is tied to the plan cache
        (unbounded-growth fix, ISSUE 5)."""
        cache_key = key[0]
        # callers hold _cache_mu (RLock): the membership scan, the
        # cap-hint drop, and the fused-failed drop are one atomic step
        with self._cache_mu:
            if any(k[0] == cache_key for k in list(self._plan_cache)):
                return
            self._cap_hints.pop(cache_key, None)
            self._fused_failed.discard(cache_key)

    def invalidate_table(self, table: str) -> None:
        """Drop compiled programs scanning ``table`` (DROP TABLE / DROP
        PARTITION): a same-named recreated table could otherwise alias a
        stale executable whose shape signature coincides."""
        base = table.split("#", 1)[0]
        with self._cache_mu:
            stale = [k for k, c in list(self._plan_cache.items())
                     if any(t == table or t.split("#", 1)[0] == base
                            for t, *_ in c.input_spec)]
            for k in stale:
                self._plan_cache.pop(k, None)
            for k in stale:
                self._on_program_evicted(k)

    @staticmethod
    def _resolve_prune(prune, pvec):
        """Substitute hoisted-parameter operands in pushed zone-map prune
        predicates with the statement's CURRENT values (planner
        _param_value / sql/paramize.resolve_param_value): pruning stays
        value-exact while the compiled program stays value-generic."""
        if not prune or not any(isinstance(v, E.Expr) for _, _, v in prune):
            return prune
        from greengage_tpu.sql.paramize import resolve_param_value

        out = []
        for col, op, v in prune:
            if isinstance(v, E.Expr):
                if pvec is None:
                    continue   # no vector bound: skip only this predicate
                val = resolve_param_value(v, pvec)
                v = (float(val) if isinstance(val, (float, np.floating))
                     else int(val))
            out.append((col, op, v))
        return tuple(out)

    def _put_param(self, host: np.ndarray):
        """Place one parameter scalar on the mesh, replicated (multi-host:
        every process binds the same values from the same statement text,
        keeping the lockstep invariant)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        sh = NamedSharding(self.mesh, P())
        if self.multihost is None:
            return jax.device_put(host, sh)
        return jax.make_array_from_callback(host.shape, sh,
                                            lambda idx: host[idx])

    def _stage(self, comp: CompileResult, snapshot, pvec=None) -> list:
        """Pipelined input staging (exec/staging.py, docs/PERF.md): submit
        every (table, segment) read+decode unit of the WHOLE input spec to
        the staging pool first, then assemble tables in spec order into
        preallocated [nseg*cap] buffers and issue each table's device
        transfer as soon as its buffers fill — later tables' disk reads
        overlap earlier tables' assembly and host->device transfer, and
        (with JAX async dispatch) the device program itself."""
        arrays = []
        shard = seg_sharding(self.mesh)
        local_segs = self._local_segments()
        # evict staged arrays + store cache entries from older manifest
        # versions (any write bumps the version, so stale device copies are
        # unreachable and only waste HBM — the dispatcher's
        # CdbComponentDatabases invalidation analog)
        version = snapshot.get("version", 0)
        self.store.blockcache.invalidate_versions(version)
        self._last_prune_stats = {}
        self._last_dyn_stats = {}
        aux = getattr(self, "_aux_tables", {})
        ranges = getattr(self, "_row_ranges", {})
        rpool = staging.pool(self.settings)
        # the statement's interrupt context, captured HERE because read
        # units run on pool threads (interrupt.current() is thread-keyed):
        # each unit checks the flag before its read, so a multi-second
        # cold stage cancels mid-flight instead of at the next boundary
        stmt_ctx = interrupt.REGISTRY.current()
        # the statement's memory account travels the same way: pool
        # threads bind to it for the unit's duration, so block-cache
        # inserts inside the read attribute to the right owner tree
        stmt_acct = memaccount.ACCOUNTS.current()

        # plan phase: resolve per-table staging decisions. Read units are
        # submitted through a bounded LOOKAHEAD window (the table being
        # assembled plus one ahead): later tables' reads overlap earlier
        # tables' assembly and transfer WITHOUT holding every table's
        # decoded columns in flight at once — peak host memory stays at
        # ~two tables, like the old serial loop's one.
        plans = []   # [kind, table, cols, cap, key, prune, payload]
        staged_local: dict = {}   # key -> (staged, pstats) THIS statement
        for table, cols, cap, direct, prune, child_parts, dyn in comp.input_spec:
            # hoisted parameters resolve HERE — staging decisions (zone
            # maps, block indexes, dynamic partition pruning) see the
            # statement's current values, and the stage-cache key below
            # carries the resolved predicate so different values never
            # share a pruned staging
            prune = self._resolve_prune(prune, pvec)
            if dyn is not None and isinstance(dyn, tuple):
                dyn = (dyn[0], self._resolve_prune(dyn[1], pvec) or (),
                       dyn[2])
            if table in aux:
                plans.append(("aux", table, cols, cap, None, None, None))
                continue
            if child_parts is not None and dyn is not None:
                # join-driven runtime partition elimination: evaluate the
                # build side's pushed filter on the host, keep only the
                # child partitions a surviving key value can land in
                # (deterministic per manifest version — multihost
                # processes compute the same set from shared storage)
                child_parts = self._dyn_pruned_parts(
                    table, child_parts, dyn, snapshot)
            key = (table, tuple(cols), cap, version, direct, prune,
                   child_parts, ranges.get(table))
            if table not in ranges:
                hit = self._stage_cache.get(key, staging.MISS)
                if hit is not staging.MISS:
                    plans.append(("hit", table, cols, cap, key, prune, hit))
                    continue
            if key in staged_local:
                # same scan twice in ONE input spec (self-join): reuse the
                # first occurrence's staged arrays instead of reading and
                # transferring the identical inputs again
                plans.append(("dup", table, cols, cap, key, prune, None))
                continue
            staged_local[key] = None   # first occurrence claims the key
            plans.append(("read", table, cols, cap, key, prune, {
                "storage_cols": [c for c in cols
                                 if not c.startswith(VALID_PREFIX)],
                "child_parts": child_parts, "direct": direct,
                "rng": ranges.get(table), "futs": None, "buffers": None}))

        read_plans = [p for p in plans if p[0] == "read"]

        def _submit(p):
            _, table, cols, cap, _key, prune, st = p
            if st["futs"] is not None:
                return
            # preallocate the [nseg*cap] staging buffers so eligible
            # columns decode straight into their slots inside the pool
            # (read_segment's in-place fast path); ranged/partitioned
            # scans slice after the read and keep the copy path, and so
            # do scans that fill only SOME segments (direct dispatch,
            # multihost remotes) — a cached view of a partially-used
            # buffer would pin far more memory than its byte accounting
            buffers = None
            if st["rng"] is None and st["child_parts"] is None \
                    and st["direct"] is None \
                    and len(local_segs) == self.nseg:
                schema = self.catalog.get(table)
                buffers = {c: np.empty(self.nseg * cap,
                                       self._stage_dtype(schema, c))
                           for c in st["storage_cols"]}
            futs = []
            for seg in range(self.nseg):
                if seg not in local_segs or (st["direct"] is not None
                                            and seg != st["direct"]):
                    # direct dispatch: only the owning segment's storage
                    # is read/staged (cdbtargeteddispatch.c analog)
                    futs.append(None)
                else:
                    dest = ({c: buf[seg * cap: (seg + 1) * cap]
                             for c, buf in buffers.items()}
                            if buffers is not None else None)
                    futs.append(rpool.submit(
                        self._read_unit, table, st["child_parts"], seg,
                        st["storage_cols"], snapshot, prune, st["rng"],
                        dest, stmt_ctx, stmt_acct))
            st["buffers"] = buffers
            st["futs"] = futs

        # assemble phase (spec order, deterministic): fill staging buffers
        # in place and put each table on the mesh as soon as it completes
        done_reads = 0
        for kind, table, cols, cap, key, prune, payload in plans:
            interrupt.check_interrupts()   # between per-table assemblies
            # one span per (table) staging unit — read+decode+assemble+
            # device-put for misses, a cache probe for hits; rows/bytes
            # land in the span args (the trace's data-movement accounting)
            with _trace.span("stage:" + table, cat="stage",
                             kind=kind) as _sp_t:
                if kind == "aux":
                    staged_aux = self._stage_aux(table, cols, cap,
                                                 aux[table], shard)
                    memaccount.charge(
                        "staging",
                        sum(int(getattr(a, "nbytes", 64))
                            for a in staged_aux), item=table)
                    arrays.extend(staged_aux)
                    continue
                if kind == "hit":
                    staged, pstats = payload
                    arrays.extend(staged)
                    if pstats is not None:
                        self._last_prune_stats[table] = pstats
                    continue
                if kind == "dup":
                    # eviction-immune within the statement: the first
                    # occurrence stored its result here whatever the cache
                    # budget did since
                    staged, pstats = staged_local[key]
                    arrays.extend(staged)
                    if pstats is not None:
                        self._last_prune_stats[table] = pstats
                    continue
                for j in range(done_reads, min(done_reads + 2,
                                               len(read_plans))):
                    _submit(read_plans[j])   # this table + one of lookahead
                st = payload
                storage_cols, futs, buffers = \
                    st["storage_cols"], st["futs"], st["buffers"]
                per_seg = []
                kept = total_blocks = 0
                for fut in futs:
                    if fut is None:
                        per_seg.append(({c: np.empty(0, dtype=np.int64)
                                         for c in storage_cols}, {}, 0))
                        continue
                    c, v, n, pstat = fut.result()
                    per_seg.append((c, v, n))
                    if pstat is not None:
                        kept += pstat[0]
                        total_blocks += pstat[1]
                if prune and total_blocks:
                    self._last_prune_stats[table] = (kept, total_blocks)
                staged = self._assemble(table, cols, cap, per_seg, shard,
                                        buffers)
                staged_local[key] = (staged,
                                     self._last_prune_stats.get(table))
                nbytes = sum(int(getattr(a, "nbytes", 64)) for a in staged)
                memaccount.charge("staging", nbytes, item=table)
                _trace.annotate(_sp_t, rows=int(sum(n for _, _, n in per_seg)),
                                bytes=nbytes, segments=len(per_seg))
                if st["rng"] is None:
                    self._stage_cache.put(
                        key, (staged, self._last_prune_stats.get(table)),
                        nbytes=nbytes, version=version)
                arrays.extend(staged)
                done_reads += 1
        return arrays

    def _read_unit(self, table, child_parts, seg, storage_cols, snapshot,
                   prune, rng, dest=None, stmt_ctx=None, stmt_acct=None):
        """One pooled staging unit: one segment's decoded columns (+ this
        thread's zone-prune stats). Runs concurrently with other units —
        the store's caches and read-path self-heal are thread-safe.
        ``dest`` carries this segment's staging-buffer slots for the
        in-place decode fast path. ``stmt_ctx`` is the owning statement's
        interrupt context: each unit is a cancellation point, and the
        raise travels back to the statement thread via fut.result().
        ``stmt_acct`` binds this pool thread to the statement's memory
        account so block-cache inserts inside the read attribute right."""
        faults.check("cancel_in_staging", segment=seg)
        if stmt_ctx is not None:
            stmt_ctx.check()
        with memaccount.ACCOUNTS.bind(stmt_acct):
            c, v, n = self._read_segment_parts(
                table, child_parts, seg, storage_cols, snapshot, prune,
                dest=dest)
        if rng is not None:
            a, b = rng
            c = {k: arr[a:b] for k, arr in c.items()}
            v = {k: (arr[a:b] if arr is not None else None)
                 for k, arr in v.items()}
            n = max(min(n, b) - a, 0)
        return c, v, n, (self.store.last_prune if prune else None)

    @staticmethod
    def _stage_dtype(schema, c) -> np.dtype:
        """The dtype a column STAGES as (may differ from storage)."""
        if c.startswith("@hp:"):
            return np.dtype(bool)         # host-evaluated predicate col
        if c.startswith("@rc:"):
            return np.dtype(np.int32)     # transient raw-dict codes
        if c.startswith(("@rp:", "@rw:")):
            return np.dtype(np.int64)     # packed raw prefix word
        if c.startswith("@rl:"):
            return np.dtype(np.int32)     # raw byte length
        col_s = schema.column(c)
        # raw TEXT stages int64 row surrogates, not the int32 dict-code
        # dtype (segment bits live above 40)
        return (np.dtype(np.int64)
                if col_s.type.kind == T.Kind.TEXT
                and col_s.encoding == "raw"
                else col_s.type.np_dtype)

    def _assemble(self, table, cols, cap, per_seg, shard,
                  buffers=None) -> list:
        """Fill one preallocated [nseg*cap] staging buffer per column IN
        PLACE from the per-segment decoded arrays (no pad-then-concatenate
        copy pair) and place each on the mesh. Columns whose segments
        already decoded into their buffer slots (read_segment's dest fast
        path) skip even that one copy — only their padding tails are
        written."""
        schema = self.catalog.get(table)
        staged = []
        nseg = self.nseg
        booldt = np.dtype(bool)
        for c in cols:
            if c.startswith(VALID_PREFIX):
                name = c[len(VALID_PREFIX):]
                host = staging.fill_buffer(
                    nseg, cap, booldt,
                    ((s, vv[name] if vv.get(name) is not None
                      else np.ones(n, dtype=bool))
                     for s, (_, vv, n) in enumerate(per_seg)), False)
            else:
                dt = self._stage_dtype(schema, c)
                buf = buffers.get(c) if buffers is not None else None
                if buf is None:
                    host = staging.fill_buffer(
                        nseg, cap, dt,
                        ((s, cc.get(c, np.zeros(0, dt))
                          .astype(dt, copy=False))
                         for s, (cc, _, _) in enumerate(per_seg)), 0)
                else:
                    for s, (cc, _, _) in enumerate(per_seg):
                        arr = cc.get(c)
                        n = 0 if arr is None else len(arr)
                        if n and getattr(arr, "base", None) is not buf:
                            buf[s * cap: s * cap + n] = arr
                        if n < cap:
                            buf[s * cap + n: (s + 1) * cap] = 0
                    host = buf
            staged.append(self._put(host, shard, cap))
        present = staging.fill_buffer(
            nseg, cap, booldt,
            ((s, np.ones(n, dtype=bool))
             for s, (_, _, n) in enumerate(per_seg)), False)
        staged.append(self._put(present, shard, cap))
        return staged

    def _dyn_pruned_parts(self, table, child_parts, dyn, snapshot) -> tuple:
        """-> child partitions surviving the build-side key-value probe
        (the execution-time PartitionSelector, nodePartitionSelector.c).
        Manifest-version cached; falls back to the full set on any
        irregularity (a missed prune is only a perf loss)."""
        version = snapshot.get("version", 0)
        ck = (table, child_parts, dyn, version)
        with self._cache_mu:
            cache = getattr(self, "_dyn_prune_cache", None)
            if cache is None:
                cache = self._dyn_prune_cache = {}
            hit = cache.get(ck)
        if hit is not None:
            self._last_dyn_stats[table] = (len(hit), len(child_parts))
            return hit
        dim_table, preds, key_col = dyn
        try:
            schema = self.catalog.get(table)
            dim_schema = self.catalog.get(dim_table)
            need = {key_col} | {c for c, _, _ in preds}
            from greengage_tpu.catalog.schema import PolicyKind

            segs = ([0] if dim_schema.policy.kind is PolicyKind.REPLICATED
                    else range(dim_schema.policy.numsegments))
            vals_parts = []
            for seg in segs:
                c, v, n = self.store.read_segment(
                    dim_table, seg, sorted(need), snapshot)
                m = np.ones(n, dtype=bool)
                for col, op, val in preds:
                    arr = c[col]
                    cv = v.get(col)
                    if cv is not None:
                        m &= np.asarray(cv, bool)
                    m &= {"=": arr == val, "<": arr < val, "<=": arr <= val,
                          ">": arr > val, ">=": arr >= val}[op]
                kv = v.get(key_col)
                if kv is not None:
                    m &= np.asarray(kv, bool)   # NULL keys never join
                vals_parts.append(c[key_col][m])
            values = np.unique(np.concatenate(vals_parts)) if vals_parts \
                else np.empty(0)
            keep_idx = set(schema.partitions_for_values(values))
            name_keep = {schema.partitions[i].storage_name(table)
                         for i in keep_idx}
            kept = tuple(p for p in child_parts if p in name_keep)
        except Exception:
            return child_parts   # never fail the query for a prune
        self._last_dyn_stats[table] = (len(kept), len(child_parts))
        with self._cache_mu:
            if len(cache) > 64:
                cache.pop(next(iter(cache)))
            cache[ck] = kept
        return kept

    def _read_segment_parts(self, table, child_parts, seg, storage_cols,
                            snapshot, prune, dest=None):
        """Read one segment's rows — for a partitioned scan, the (pruned)
        child tables' rows concatenated in partition order. Zone-map
        pruning applies per child; block stats sum across children."""
        if child_parts is None:
            return self.store.read_segment(table, seg, storage_cols,
                                           snapshot, prune=prune, dest=dest)
        per = []
        kept = total = 0
        any_prune = False
        for child in child_parts:
            c, v, n = self.store.read_segment(child, seg, storage_cols,
                                              snapshot, prune=prune)
            per.append((c, v, n))
            st = self.store.last_prune
            if st is not None:
                any_prune = True
                kept += st[0]
                total += st[1]
        self.store.last_prune = (kept, total) if any_prune else None
        cols_out: dict = {}
        valids_out: dict = {}
        ntot = sum(n for _, _, n in per)
        for col in storage_cols:
            arrs = [c[col] for c, _, _ in per]
            cols_out[col] = (np.concatenate(arrs) if arrs
                             else np.empty(0, dtype=np.int64))
            if any(v.get(col) is not None for _, v, _ in per):
                valids_out[col] = np.concatenate([
                    (v[col] if v.get(col) is not None
                     else np.ones(n, dtype=bool))
                    for _, v, n in per])
        return cols_out, valids_out, ntot

    def _stage_aux(self, table, cols, cap, data, shard):
        """Stage an ephemeral host table ('@spill:' partial rows): rows
        split contiguously across segments, padded to cap."""
        aux_cols, aux_valids = data
        n = len(next(iter(aux_cols.values()))) if aux_cols else 0
        staged = []
        counts = [max(min(n, (s + 1) * cap) - s * cap, 0)
                  for s in range(self.nseg)]
        for c in cols:
            if c.startswith(VALID_PREFIX):
                name = c[len(VALID_PREFIX):]
                src = aux_valids.get(name)
                if src is None:
                    src = np.ones(n, dtype=bool)
                parts = [_pad(src[s * cap: s * cap + counts[s]], cap, False)
                         for s in range(self.nseg)]
            else:
                src = aux_cols[c]
                parts = [_pad(src[s * cap: s * cap + counts[s]], cap)
                         for s in range(self.nseg)]
            staged.append(self._put(np.concatenate(parts), shard, cap))
        present = np.concatenate(
            [_pad(np.ones(cn, dtype=bool), cap, False) for cn in counts])
        staged.append(self._put(present, shard, cap))
        return staged

    def _put(self, host: np.ndarray, shard, cap: int):
        """Place a [nseg*cap] host array onto the mesh. Multi-host: each
        process holds data only for its LOCAL segments (remote positions
        are zero padding) and contributes exactly its addressable shards
        via make_array_from_callback."""
        if self.multihost is None:
            return jax.device_put(host, shard)

        def cb(index):
            sl = index[0]
            return host[sl.start or 0: sl.stop]

        return jax.make_array_from_callback(host.shape, shard, cb)

    # ------------------------------------------------------------------
    def _finalize(self, comp: CompileResult, flat, snapshot,
                  seg_slice=None, raw: bool = False) -> Result:
        # raw is an explicit parameter, never instance state: a lock-free
        # RETRIEVE finalizing concurrently with a DML's raw-mode run must
        # not flip the other call's decode behavior
        ncols = len(comp.out_cols)
        cap = comp.capacity
        sel = flat[2 * ncols].reshape(self.nseg, cap)
        cols_np = {}
        valids_np = {}
        if seg_slice is None:
            if comp.gather_child_locus.kind in (LocusKind.SEGMENT_GENERAL,
                                                LocusKind.GENERAL):
                seg_slice = [0]  # replicated: one copy suffices
            else:
                seg_slice = range(self.nseg)
        mask = np.concatenate([sel[s] for s in seg_slice])
        for i, c in enumerate(comp.out_cols):
            data = flat[2 * i].reshape(self.nseg, cap)
            valid = flat[2 * i + 1].reshape(self.nseg, cap)
            cols_np[c.id] = np.concatenate([data[s] for s in seg_slice])[mask]
            valids_np[c.id] = np.concatenate([valid[s] for s in seg_slice])[mask]
        return self._present(comp, cols_np, valids_np, snapshot, raw)

    def _present(self, comp: CompileResult, cols_np, valids_np, snapshot,
                 raw: bool) -> Result:
        """Host-side presentation of extracted row data: merge-sorted
        receive, host LIMIT, TEXT/decimal/date decode, Result assembly."""
        # host merge of per-segment sorted runs (Merge Receive analog)
        if comp.merge_keys:
            order = _host_sort_order(cols_np, valids_np, comp.merge_keys, self.store)
            for k in cols_np:
                cols_np[k] = cols_np[k][order]
                valids_np[k] = valids_np[k][order]
        if comp.host_limit is not None:
            limit, offset = comp.host_limit
            end = None if limit is None else offset + limit
            for k in cols_np:
                cols_np[k] = cols_np[k][offset:end]
                valids_np[k] = valids_np[k][offset:end]

        # decode TEXT + decimals for presentation (raw mode keeps storage
        # representation for DML republish paths)
        out_cols = {}
        out_valids = {}
        for c in comp.out_cols:
            data = cols_np[c.id]
            valid = valids_np[c.id]
            if raw or getattr(c, "hidden", False):
                out_cols[c.id] = data
                out_valids[c.id] = None if valid.all() else valid
                continue
            if c.type.kind is T.Kind.TEXT and getattr(c, "raw_ref", None) is not None:
                # raw TEXT: device carried row surrogates; decode from the
                # byte-blob storage now. NULL/padded rows carry garbage
                # surrogates — never dereference them.
                vals = np.empty(len(data), dtype=object)
                m = np.asarray(valid, bool)
                decoded = self.store.fetch_raw(
                    c.raw_ref[0], c.raw_ref[1], data[m], snapshot)
                if getattr(c, "raw_chain", None):
                    from greengage_tpu.utils import strfuncs

                    decoded = np.array(
                        [strfuncs.apply_chain(s, c.raw_chain)
                         for s in decoded], dtype=object)
                vals[m] = decoded
                out_cols[c.id] = vals
            elif c.type.kind is T.Kind.TEXT and c.dict_ref is not None:
                d = self.store.dictionary(*c.dict_ref)
                vals = np.array(
                    [d.values[x] if 0 <= x < len(d) else None for x in data], dtype=object)
                out_cols[c.id] = vals
            elif c.type.kind is T.Kind.DECIMAL:
                out_cols[c.id] = data / (10.0 ** c.type.scale)
            elif c.type.kind is T.Kind.DATE:
                out_cols[c.id] = (np.datetime64("1970-01-01", "D")
                                  + data.astype("timedelta64[D]"))
            else:
                out_cols[c.id] = data
            out_valids[c.id] = None if valid.all() else valid
        visible = [c for c in comp.out_cols if not getattr(c, "hidden", False)]
        return Result(
            columns=[c.name for c in visible],
            cols=out_cols,
            valids=out_valids,
            _order=[c.id for c in visible],
        )


def _is_pallas_error(e: Exception) -> bool:
    """Does this exception look like a pallas/Mosaic lowering or compile
    failure (vs a genuine runtime error like OOM or a dead interconnect)?
    Mosaic failures surface as XlaRuntimeError/JaxRuntimeError whose text
    names Mosaic or the TPU custom call; pallas tracing failures name
    pallas itself."""
    s = f"{type(e).__name__}: {e}".lower()
    return any(m in s for m in ("pallas", "mosaic", "tpu_custom_call"))


def _pad(arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
    if len(arr) == cap:
        return arr
    out = np.full(cap, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _host_sort_order(cols, valids, merge_keys, store) -> np.ndarray:
    """Stable numpy lexsort matching ops/sort.py semantics."""
    from greengage_tpu import expr as E

    keys = []  # mirror of ops/sort._order_encode, in numpy
    for e, desc, nulls_first in merge_keys:
        if not isinstance(e, E.ColRef):
            raise QueryError("merge sort key must be an output column")
        v = cols[e.name]
        valid = valids.get(e.name)
        if e.type.kind is T.Kind.TEXT:
            dref = getattr(e, "_dict_ref", None)
            if dref is not None:
                dic = store.dictionary(*dref)
                rank = np.argsort(np.argsort(dic.values, kind="stable"), kind="stable")
                ints = np.concatenate([rank.astype(np.int64), [np.int64(-1)]])[v]
            else:
                ints = v.astype(np.int64)
            enc = ints.view(np.uint64) ^ (np.uint64(1) << np.uint64(63))
        elif e.type.kind is T.Kind.FLOAT64:
            bits = np.ascontiguousarray(v, dtype=np.float64).view(np.uint64)
            enc = np.where(bits >> np.uint64(63) == 1, ~bits,
                           bits | np.uint64(1) << np.uint64(63))
        else:
            enc = v.astype(np.int64).view(np.uint64) ^ (np.uint64(1) << np.uint64(63))
        if desc:
            enc = ~enc
        nf = nulls_first if nulls_first is not None else desc
        if valid is not None:
            nullkey = np.where(valid, 0, -1 if nf else 1).astype(np.int8)
            enc = np.where(valid, enc, np.uint64(0))
        else:
            nullkey = np.zeros(len(enc), dtype=np.int8)
        keys.append((nullkey, enc))
    lex = []
    for nullkey, enc in reversed(keys):
        lex.append(enc)
        lex.append(nullkey)
    if not lex:
        return np.arange(len(next(iter(cols.values()))))
    return np.lexsort(lex)
