"""Placeholder; full Database facade lands with the executor."""


class Database:
    def __init__(self, path=None, numsegments=None):
        raise NotImplementedError("executor not built yet")
